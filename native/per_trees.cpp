// Native PER segment trees: batched sum/min tree updates and inverse-CDF
// sampling for prioritized replay.
//
// The reference's Python sampler walks the sum tree one transition at a time
// (prioritized_replay_memory.py:126-149), an O(B log N) pointer chase in the
// interpreter that SURVEY.md flags as the throughput hazard feeding a TPU
// learner. The numpy backend (d4pg_tpu/replay/segment_tree.py) vectorizes
// the walk; this C++ backend removes the remaining numpy dispatch overhead
// for large capacities and serves as the framework's host-side native
// component (SURVEY.md §2 "Native components").
//
// Layout: one object holds BOTH trees (PER always writes the same priorities
// to both): flat arrays of 2*cap nodes, node 1 = root, leaf i at cap + i.
// C ABI for ctypes; no exceptions cross the boundary.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <vector>

namespace {

struct PerTrees {
  int64_t cap;        // power-of-two leaf count
  int levels;
  std::vector<double> sum;  // 2*cap
  std::vector<double> mn;   // 2*cap
};

int64_t next_pow2(int64_t n) {
  int64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void* pt_new(int64_t capacity) {
  auto* t = new PerTrees();
  t->cap = next_pow2(capacity);
  t->levels = static_cast<int>(std::log2(static_cast<double>(t->cap)) + 0.5);
  t->sum.assign(2 * t->cap, 0.0);
  t->mn.assign(2 * t->cap, std::numeric_limits<double>::infinity());
  return t;
}

void pt_free(void* h) { delete static_cast<PerTrees*>(h); }

int64_t pt_capacity(void* h) { return static_cast<PerTrees*>(h)->cap; }

// Batched leaf write + ancestor repair on the touched path only.
void pt_set(void* h, const int64_t* idx, const double* values, int64_t n) {
  auto* t = static_cast<PerTrees*>(h);
  for (int64_t k = 0; k < n; ++k) {
    int64_t node = idx[k] + t->cap;
    t->sum[node] = values[k];
    t->mn[node] = values[k];
  }
  for (int64_t k = 0; k < n; ++k) {
    int64_t node = (idx[k] + t->cap) >> 1;
    while (node >= 1) {
      int64_t l = node << 1;
      double s = t->sum[l] + t->sum[l | 1];
      double m = std::min(t->mn[l], t->mn[l | 1]);
      if (t->sum[node] == s && t->mn[node] == m) break;  // path already fixed
      t->sum[node] = s;
      t->mn[node] = m;
      node >>= 1;
    }
  }
}

double pt_total(void* h) { return static_cast<PerTrees*>(h)->sum[1]; }

double pt_min(void* h) { return static_cast<PerTrees*>(h)->mn[1]; }

void pt_get(void* h, const int64_t* idx, double* out, int64_t n) {
  auto* t = static_cast<PerTrees*>(h);
  for (int64_t k = 0; k < n; ++k) out[k] = t->sum[idx[k] + t->cap];
}

// Batched inverse-CDF: for each prefix mass, the smallest leaf i with
// cumulative sum(leaves[:i+1]) > mass.
void pt_find_prefix(void* h, const double* mass, int64_t* out, int64_t n) {
  auto* t = static_cast<PerTrees*>(h);
  for (int64_t k = 0; k < n; ++k) {
    double p = mass[k];
    int64_t node = 1;
    for (int lv = 0; lv < t->levels; ++lv) {
      int64_t l = node << 1;
      double ls = t->sum[l];
      if (p >= ls) {
        p -= ls;
        node = l | 1;
      } else {
        node = l;
      }
    }
    out[k] = node - t->cap;
  }
}

}  // extern "C"
