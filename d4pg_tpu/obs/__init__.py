"""Observability plane: wire-to-grad trace spans, the unified metrics
registry, and the chaos flight recorder.

Five stdlib-only modules (nothing here may import jax — the plane must
be importable from the transport/locking layers that run before any
backend exists):

- ``obs.registry`` — ONE process-wide registry of named counters/gauges/
  histograms plus *snapshot providers* (callables that produce a
  consistent dict under their own locks — the PR-4 rule that every
  counter is read under the lock that writes it). ``replay_service``,
  ``staging``, ``fused_buffer``, ``core.locking``, the profiling
  sentinels and the fleet harness all publish here; the bespoke
  ``*_stats()`` dicts survive as thin views over the same snapshots.
- ``obs.trace`` — sampled per-frame trace spans riding the v2 wire
  codec's header extension: birth timestamp at the actor's socket
  write, span timestamps at admission, decode, stage, merge-pop,
  commit and grad-step consumption, aggregated into per-stage latency
  histograms with end-to-end wire-to-grad as the headline series.
- ``obs.flight`` — a bounded in-memory ring of recent structured
  events (admissions, sheds, evictions, order-breaks, lock-hierarchy
  violations, retries) the fleet harness dumps to
  ``docs/evidence/fleet/`` on deadlock, crash or assertion, so a chaos
  failure comes with a postmortem instead of a stack trace.
- ``obs.containment`` — the one-call crash-containment breadcrumb every
  thread role's top frame uses (``threads.contained_crashes`` counter +
  a flight event); jaxlint family 16 enforces its presence statically.
- ``obs.draw_ledger`` — the runtime twin of the rnggraph determinism
  pass (jaxlint families 22-24): per-stream RNG draw-call counts behind
  a transparent Generator proxy, exported as a canonical digest the A/B
  chaos drivers pin across arms ("equal seeded offered load" as an
  oracle, not an argument).

Lock discipline: every lock in this package is named ``_mu`` — a plain
``threading.Lock`` OUTSIDE the tiered hierarchy, deliberately terminal:
no code path holding an ``_mu`` acquires any other lock, so the
observability plane can be called from under any tiered lock without
adding an edge the lock graph could cycle through.
"""

from d4pg_tpu.obs import containment, draw_ledger, flight, registry, trace
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.draw_ledger import LEDGER, DrawLedger
from d4pg_tpu.obs.flight import FlightRecorder, record_event
from d4pg_tpu.obs.registry import REGISTRY, MetricsRegistry
from d4pg_tpu.obs.trace import DEFAULT_SAMPLE, TraceRecorder

__all__ = [
    "containment", "draw_ledger", "flight", "registry", "trace",
    "FlightRecorder", "record_event", "contained_crash",
    "REGISTRY", "MetricsRegistry",
    "DEFAULT_SAMPLE", "TraceRecorder",
    "LEDGER", "DrawLedger",
]
