"""Chaos flight recorder: the last N structured events, dumped on death.

A chaos-smoke failure used to come with a stack trace and a pile of
end-of-run counters — everything about *what* the plane was doing in
the seconds before the wedge was already overwritten. The flight
recorder is a bounded in-memory ring of recent structured events
(admissions, sheds, evictions, order-breaks, lock-hierarchy
violations, transport retries, receiver stalls) that the fleet harness
dumps to ``docs/evidence/fleet/`` when a run ends in deadlock, crash,
assertion, or a recorded hierarchy violation — a postmortem instead of
a stack trace.

Event volume: the ring is ``maxlen``-bounded (append drops the oldest),
so per-frame admission events are safe to record at full ingest rate —
they are exactly the context a postmortem needs ("what was the plane
doing in the 2048 events before the violation").

Lock discipline: one terminal ``_mu`` (obs/__init__); ``record`` is a
lock round trip + deque append. Callers must record OUTSIDE their own
critical sections where convenient — not for correctness (``_mu`` is
terminal) but to keep tiered-lock hold times honest.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque


class FlightRecorder:
    def __init__(self, maxlen: int = 2048):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=int(maxlen))
        self._seq = 0
        self.enabled = True

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        t = time.monotonic()
        with self._mu:
            self._seq += 1
            self._ring.append({"seq": self._seq, "t": round(t, 6),
                               "kind": kind, **fields})

    def events(self) -> list[dict]:
        with self._mu:
            return list(self._ring)

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()
            self._seq = 0

    def dump(self, directory: str, reason: str,
             extra: dict | None = None) -> str:
        """Write the ring as a JSON postmortem; returns the path. The
        filename carries a wall-clock stamp + the reason so a directory
        of dumps reads as an incident log."""
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:40]
        path = os.path.join(directory, f"flight_{stamp}_{safe}.json")
        payload = {
            "reason": reason,
            "dumped_at": stamp,
            "n_events": len(self),
            "events": self.events(),
        }
        if extra:
            payload["context"] = extra
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)
        return path


# THE process-wide recorder: the receiver-side planes (replay service,
# locking sentinels, transport retries) publish here, the fleet harness
# dumps it.
RECORDER = FlightRecorder()


def record_event(kind: str, **fields) -> None:
    """Module-level convenience over the process recorder."""
    RECORDER.record(kind, **fields)
