"""Chaos flight recorder: the last N structured events, dumped on death.

A chaos-smoke failure used to come with a stack trace and a pile of
end-of-run counters — everything about *what* the plane was doing in
the seconds before the wedge was already overwritten. The flight
recorder is a bounded in-memory ring of recent structured events
(admissions, sheds, evictions, order-breaks, lock-hierarchy
violations, transport retries, receiver stalls) that the fleet harness
dumps to ``docs/evidence/fleet/`` when a run ends in deadlock, crash,
assertion, or a recorded hierarchy violation — a postmortem instead of
a stack trace.

Event volume: the ring is ``maxlen``-bounded (append drops the oldest),
so per-frame admission events are safe to record at full ingest rate —
they are exactly the context a postmortem needs ("what was the plane
doing in the 2048 events before the violation").

Lock discipline: one terminal ``_mu`` (obs/__init__); ``record`` is a
lock round trip + deque append. Callers must record OUTSIDE their own
critical sections where convenient — not for correctness (``_mu`` is
terminal) but to keep tiered-lock hold times honest.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque


class FlightRecorder:
    # Dump retention: a chaos soak that dumps on every kill would grow
    # docs/evidence/fleet/ without bound; ``dump`` prunes its own
    # ``flight_*.json`` family (never the fleet artifacts) down to the
    # newest ``keep_dumps`` after each write. Class default, overridable
    # per instance or per call.
    keep_dumps = 32

    def __init__(self, maxlen: int = 2048, keep_dumps: int | None = None):
        self._mu = threading.Lock()
        self._ring: deque = deque(maxlen=int(maxlen))
        self._seq = 0
        self._dump_seq = 0
        if keep_dumps is not None:
            self.keep_dumps = int(keep_dumps)
        self.enabled = True

    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        t = time.monotonic()
        with self._mu:
            self._seq += 1
            self._ring.append({"seq": self._seq, "t": round(t, 6),
                               "kind": kind, **fields})

    def events(self) -> list[dict]:
        with self._mu:
            return list(self._ring)

    def __len__(self) -> int:
        with self._mu:
            return len(self._ring)

    def reset(self) -> None:
        with self._mu:
            self._ring.clear()
            self._seq = 0

    def dump(self, directory: str, reason: str,
             extra: dict | None = None, keep: int | None = None) -> str:
        """Write the ring as a JSON postmortem; returns the path. The
        filename carries a wall-clock stamp + the reason so a directory
        of dumps reads as an incident log; a per-process dump sequence
        and the pid keep same-second dumps (two supervisor kills in one
        second, two harnesses in one test run) from colliding while
        lexical sort stays chronological. After writing, the directory
        is pruned to the newest ``keep`` (default ``keep_dumps``)
        ``flight_*.json`` files — the fleet artifacts beside them are
        never touched."""
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        with self._mu:
            self._dump_seq += 1
            seq = self._dump_seq
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:40]
        path = os.path.join(
            directory,
            f"flight_{stamp}_{os.getpid():07d}-{seq:04d}_{safe}.json")
        payload = {
            "reason": reason,
            "dumped_at": stamp,
            "n_events": len(self),
            "events": self.events(),
        }
        if extra:
            payload["context"] = extra
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)
        prune_artifacts(directory, "flight_",
                        self.keep_dumps if keep is None else keep)
        return path


def prune_artifacts(directory: str, prefix: str, keep: int) -> list[str]:
    """Bounded-evidence rule: keep the newest ``keep`` ``{prefix}*.json``
    files in ``directory`` (newest = lexically greatest — both the
    flight and fleet families stamp ``%Y%m%d-%H%M%S`` first, so lexical
    order IS chronological order), delete the rest. Returns the deleted
    paths; ``keep <= 0`` disables pruning (an explicit "keep everything"
    for soak archaeology). Racing deleters are tolerated — a file
    removed under us is someone else finishing the same prune."""
    if keep <= 0:
        return []
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(prefix) and n.endswith(".json"))
    except OSError:
        return []
    doomed = []
    for name in names[:-keep] if len(names) > keep else []:
        path = os.path.join(directory, name)
        try:
            os.remove(path)
            doomed.append(path)
        except OSError:
            pass
    return doomed


# Elastic-plane event kinds (d4pg_tpu/elastic): the autoscaler records
# one event per applied scaling decision and the admission-controlled
# services record one per class-attributed rejection. Declared here as
# constants so the recorder, the emitters, and the postmortem readers
# agree on the vocabulary (free-form kinds stay legal — these are the
# ones the elastic drill's assertions grep for).
EVENT_SCALE_UP = "scale_up"
EVENT_SCALE_DOWN = "scale_down"
EVENT_ADMISSION_REJECT = "admission_reject"

# THE process-wide recorder: the receiver-side planes (replay service,
# locking sentinels, transport retries) publish here, the fleet harness
# dumps it.
RECORDER = FlightRecorder()


def record_event(kind: str, **fields) -> None:
    """Module-level convenience over the process recorder."""
    RECORDER.record(kind, **fields)
