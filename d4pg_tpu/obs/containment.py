"""Top-frame crash containment for long-lived thread roles.

Every ``threading.Thread`` target in the five wire planes wraps its body
in a broad handler that calls :func:`contained_crash` — the thread dies,
but the death is *counted* (``threads.contained_crashes`` registry
counter) and *flight-recorded* (a ``thread_crash_contained`` event with
the role name and the exception), so a silently-dead plane shows up in
the next metrics snapshot instead of as a mystery stall.  The static
side of the contract is jaxlint family 16 (``thread-crash-containment``
in ``lint/failgraph.py``); the runtime side is the chaos smokes
asserting the counter stayed at zero across a healthy run.
"""

from __future__ import annotations

from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import REGISTRY


def contained_crash(role: str, exc: BaseException) -> None:
    """Count and flight-record a thread-top-frame crash for ``role``."""
    REGISTRY.counter("threads.contained_crashes").inc()
    record_event("thread_crash_contained", role=role,
                 error=f"{type(exc).__name__}: {exc}")
