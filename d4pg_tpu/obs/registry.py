"""Unified metrics registry: one process-wide home for named counters,
gauges, histograms and consistent-snapshot providers.

Before this module the repo's telemetry was N disjoint ledgers —
``ReplayService.ingest_stats()``, ``core.locking.lock_stats()``, the
sentinel counts in ``io/profiling.py``, per-sender counters, the fleet
harness's report dict — with no single place to ask "what does this
process know about itself right now". The registry is that place.

Consistency contract (the PR-4 rule, verbatim): **every counter is read
under the lock that writes it.** Two mechanisms honor it:

- *Direct metrics* (``Counter``/``Gauge``/``Histogram``) each own one
  plain lock (``_mu``); ``inc``/``set``/``observe`` and the export-time
  read both take it, so a metric's value is never torn.
- *Snapshot providers*: a component whose counters live under its OWN
  locks (a shard's deque+counters under one condition) registers a
  callable that produces its consistent snapshot — ``export()`` invokes
  it with NO registry lock held, so the provider takes exactly the
  locks it always takes. The bespoke ``*_stats()`` methods ARE those
  providers; they survive as thin compatibility views.

Providers are held by weak reference (``WeakMethod`` for bound
methods): a test that builds twenty ``ReplayService`` instances leaks
nothing, and a dead provider silently drops out of ``export()``.

Lock discipline (see ``obs/__init__``): ``_mu`` locks are terminal —
no path holding one acquires any other lock. ``export()`` therefore
copies the provider list under ``_mu`` and calls the providers after
releasing it.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque


class Counter:
    """Monotonic named counter. ``inc`` is one lock round trip (~100 ns)
    — cheap enough for per-frame paths, too expensive for per-row ones
    (callers on row paths aggregate per block and ``inc(n)`` once)."""

    __slots__ = ("name", "_mu", "_v")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._v = 0

    def inc(self, n: int = 1) -> None:
        with self._mu:
            self._v += n

    @property
    def value(self) -> int:
        with self._mu:
            return self._v

    def reset(self) -> None:
        with self._mu:
            self._v = 0


class Gauge:
    """Last-write-wins named value."""

    __slots__ = ("name", "_mu", "_v")

    def __init__(self, name: str):
        self.name = name
        self._mu = threading.Lock()
        self._v: float | None = None

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    @property
    def value(self) -> float | None:
        with self._mu:
            return self._v

    def reset(self) -> None:
        with self._mu:
            self._v = None


class Histogram:
    """Bounded-reservoir histogram: keeps the newest ``maxlen``
    observations plus lifetime count/sum, and reports p50/p95/p99 over
    the reservoir at snapshot time. The reservoir bound makes a
    long-lived learner's memory flat; the percentiles are then over the
    RECENT window, which is what a latency series wants anyway."""

    __slots__ = ("name", "_mu", "_window", "_count", "_sum")

    def __init__(self, name: str, maxlen: int = 4096):
        self.name = name
        self._mu = threading.Lock()
        self._window: deque = deque(maxlen=maxlen)
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        with self._mu:
            self._window.append(float(v))
            self._count += 1
            self._sum += float(v)

    def reset(self) -> None:
        with self._mu:
            self._window.clear()
            self._count = 0
            self._sum = 0.0

    def snapshot_dict(self) -> dict:
        with self._mu:
            window = list(self._window)
            count, total = self._count, self._sum
        return percentile_summary(window, count=count, total=total)


def percentile_summary(values: list[float], count: int | None = None,
                       total: float | None = None) -> dict:
    """p50/p95/p99/mean/n over ``values`` (no numpy: the registry must
    stay importable before any backend exists)."""
    n = len(values)
    if n == 0:
        return {"p50": None, "p95": None, "p99": None, "mean": None,
                "n": 0, "count": count or 0}
    ordered = sorted(values)

    def pct(q: float) -> float:
        # linear interpolation on the sorted reservoir (np.percentile's
        # default convention, without requiring numpy here)
        pos = q * (n - 1)
        lo = int(pos)
        hi = min(n - 1, lo + 1)
        frac = pos - lo
        return round(ordered[lo] * (1 - frac) + ordered[hi] * frac, 6)

    mean = (total / count) if (total is not None and count) \
        else sum(values) / n
    return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99),
            "mean": round(mean, 6), "n": n,
            "count": count if count is not None else n}


class MetricsRegistry:
    """The process-wide metric namespace. ``counter``/``gauge``/
    ``histogram`` are get-or-create (idempotent by name, so call sites
    can look metrics up cheaply without import-order coupling);
    ``register_provider`` attaches a consistent-snapshot callable."""

    def __init__(self):
        self._mu = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        # name -> weak callable (WeakMethod for bound methods so a dead
        # ReplayService's provider drops out instead of leaking it)
        self._providers: dict[str, object] = {}

    # -- metric construction (get-or-create) -------------------------------
    def counter(self, name: str) -> Counter:
        with self._mu:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._mu:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, maxlen: int = 4096) -> Histogram:
        with self._mu:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, maxlen)
            return h

    # -- providers ----------------------------------------------------------
    def register_provider(self, name: str, fn) -> None:
        """Attach a consistent-snapshot callable under ``name``
        (re-registering replaces — "the process's replay service" is a
        last-wins slot). Bound methods are held weakly."""
        ref = (weakref.WeakMethod(fn) if hasattr(fn, "__self__")
               else weakref.ref(fn))
        with self._mu:
            self._providers[name] = ref

    def unregister_provider(self, name: str, fn=None) -> None:
        """Drop the provider slot. With ``fn`` given, only drop it when
        the slot still points at ``fn`` — a closing component must not
        evict a newer one that took over its name (bound methods compare
        by equality: same function, same instance)."""
        with self._mu:
            if fn is not None:
                ref = self._providers.get(name)
                if ref is None:
                    return
                cur = ref()
                if cur is not None and cur != fn:
                    return
            self._providers.pop(name, None)

    # -- snapshot -----------------------------------------------------------
    def export(self) -> dict:
        """One consistent-enough snapshot of everything: each direct
        metric is read under its own lock; each provider runs under ITS
        owner's locks (invoked with no registry lock held — a provider
        is free to take shard conditions, the service lock, whatever it
        always takes). Cross-component totals are therefore sums of
        per-component-consistent snapshots, the same contract
        ``ingest_stats()`` documents."""
        with self._mu:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
            providers = list(self._providers.items())
        out: dict = {
            "counters": {c.name: c.value for c in counters},
            "gauges": {g.name: g.value for g in gauges
                       if g.value is not None},
            "histograms": {h.name: h.snapshot_dict() for h in histograms},
        }
        dead = []
        for name, ref in providers:
            fn = ref()
            if fn is None:
                dead.append(name)
                continue
            try:
                out[name] = fn()
            except Exception as e:  # a crashed provider must not kill export
                out[name] = {"provider_error": f"{type(e).__name__}: {e}"}
        if dead:
            with self._mu:
                for name in dead:
                    # only drop if nobody re-registered the slot meanwhile
                    if self._providers.get(name) is not None \
                            and self._providers[name]() is None:
                        self._providers.pop(name, None)
        return out

    def reset_metrics(self) -> None:
        """Zero every direct metric (providers are their owners'
        business). Test/bench bracketing."""
        with self._mu:
            metrics = (list(self._counters.values())
                       + list(self._gauges.values())
                       + list(self._histograms.values()))
        for m in metrics:
            m.reset()


# THE process-wide registry. Components publish here by default; tests
# that need isolation construct their own MetricsRegistry.
REGISTRY = MetricsRegistry()
