"""Wire-to-grad trace spans: where does a frame's time go?

The ROADMAP's perf items (multi-core K-sweep attribution, IMPACT-style
multi-learner, sample-on-ingest) all need one measurement the repo
could not make: the latency decomposition between an actor's socket
write and the grad step that consumes the rows. This module is that
measurement plane.

Mechanics: the SENDER samples frames at ``trace_sample`` (seeded rng —
fleet runs stay reproducible) and stamps the sampled frame's v2 wire
header with a trace id + birth timestamp (``transport.encode_raw``
extension; frames without the extension decode unchanged forever, npz
frames are never traced). The receiver records a span timestamp at
each stage the frame passes:

    send ──> admission ──> decode ──> stage ──> merge ──> commit ──> grad
                 │             │                  │
                 └── shed ─────┴──── shed ────────┘   (terminal: counted,
                                                       never leaked)

- ``admission``  — the frame entered an ingest shard's deque
  (``ReplayService.add_payload``; zero-decode for v2 frames).
- ``decode``     — the shard worker parsed the columns.
- ``stage``      — rows staged (direct-stage ring copy, or handed to
  the ordered-merge inbox on the non-fused path).
- ``merge``      — the commit thread popped the ticket in global order.
- ``commit``     — rows landed in replay state (buffer insert /
  direct-stage accounting settled).
- ``grad``       — first learner consumption after commit: the fused
  loop marks it right after each chunk dispatch
  (``train.train_steps_fused``), the fleet harness's consumer lane
  marks it after each concurrent ``sample()``. Dispatch time is the
  host-side proxy for "a grad step consumed these rows" — the device
  executes asynchronously and the host cannot observe the kernel
  without a sync that would distort the measurement.

A shed/tombstoned/undecodable frame gets a terminal ``shed`` span so
every admitted trace terminates — the zero-orphan invariant the K-shard
propagation test pins.

Clock: ``time.monotonic()`` throughout. On Linux that is
CLOCK_MONOTONIC, one timeline across processes on a host, so spawned
actor lanes stamp births the receiver's spans compare against directly.

Cost: a span is one terminal-lock round trip + one dict store (~1 us);
at the default 2% sample over 16-row frames that is ~1.3 ns/row —
unmeasurable against the ~190 us/row ingest budget. The recorder is
disabled by default; ``enable()`` is the only switch.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque

from d4pg_tpu.obs.registry import percentile_summary

# The default sampling rate the --trace_sample knobs document: dense
# enough for stable p99s over a 10 s fleet run, sparse enough that the
# acceptance overhead bound (<= 2%) holds with an order of magnitude of
# margin.
DEFAULT_SAMPLE = 0.02

# Pipeline stages in order; `shed` is the failure terminal. ``deal`` is
# the sample-on-ingest plane's post-commit stage: the dealer stamps the
# NEWEST constituent frame of each dealt block, so a block's deal span is
# a child of a committed trace. Terminals are unchanged — commit already
# terminates a trace, so a dealt block lost to a learner kill can never
# orphan the accounting.
STAGES = ("send", "admission", "decode", "stage", "merge", "commit", "deal",
          "grad")
TERMINALS = ("commit", "grad", "shed")

# Stage pairs the latency block reports (label, from, to).
_PAIRS = (
    ("wire_to_admission", "send", "admission"),
    ("admission_to_decode", "admission", "decode"),
    ("decode_to_stage", "decode", "stage"),
    ("stage_to_merge", "stage", "merge"),
    ("merge_to_commit", "merge", "commit"),
    ("commit_to_deal", "commit", "deal"),
    ("deal_to_grad", "deal", "grad"),
    ("commit_to_grad", "commit", "grad"),
    ("wire_to_commit", "send", "commit"),
    ("wire_to_grad", "send", "grad"),
)

_tid_counter = itertools.count(1)  # next() is GIL-atomic in CPython


def new_trace_id(salt: int = 0) -> int:
    """Process-unique u64 trace id; ``salt`` (e.g. an actor index)
    decorrelates ids across sender processes sharing a receiver."""
    return ((salt & 0xFFFF) << 48) | (next(_tid_counter) & 0xFFFFFFFFFFFF)


class TraceRecorder:
    """Receiver-side span table, keyed by trace id.

    Bounded: at most ``max_traces`` live records; past the bound new
    traces are dropped and counted (``overflow``) — the plane degrades
    by losing samples, never by growing without bound. All mutation
    under one terminal lock (``_mu``; see obs/__init__ discipline)."""

    def __init__(self, max_traces: int = 8192):
        self._mu = threading.Lock()
        self.max_traces = int(max_traces)
        self._spans: OrderedDict[int, dict] = OrderedDict()
        self._await_grad: deque = deque()
        self.enabled = False
        self.sample_rate = 0.0
        self.overflow = 0

    # -- lifecycle ----------------------------------------------------------
    def enable(self, sample_rate: float = DEFAULT_SAMPLE) -> None:
        with self._mu:
            self.enabled = True
            self.sample_rate = float(sample_rate)

    def disable(self) -> None:
        with self._mu:
            self.enabled = False

    def reset(self) -> None:
        with self._mu:
            self._spans.clear()
            self._await_grad.clear()
            self.overflow = 0

    # -- span recording (hot path) ------------------------------------------
    def begin(self, tid: int, birth_ts: float) -> None:
        """Open a trace at admission with the sender's birth stamp."""
        if not self.enabled:
            return
        with self._mu:
            if tid in self._spans:
                return
            if len(self._spans) >= self.max_traces:
                # evict the oldest COMPLETED record; if none, drop the
                # new trace (live records must keep accumulating spans)
                evicted = False
                for old_tid, spans in self._spans.items():
                    if any(t in spans for t in TERMINALS):
                        del self._spans[old_tid]
                        evicted = True
                        break
                if not evicted:
                    self.overflow += 1
                    return
            self._spans[tid] = {"send": float(birth_ts)}

    def record_span(self, tid: int, stage: str, ts: float | None = None
                    ) -> None:
        if not self.enabled:
            return
        t = time.monotonic() if ts is None else ts
        with self._mu:
            spans = self._spans.get(tid)
            if spans is not None and stage not in spans:
                spans[stage] = t

    def terminal_shed(self, tid: int) -> None:
        """Terminal span for a frame that left the pipeline early (shed,
        tombstoned, undecodable). Opens the record if admission never
        stamped it (admission-reject path)."""
        if not self.enabled:
            return
        t = time.monotonic()
        with self._mu:
            spans = self._spans.get(tid)
            if spans is None:
                if len(self._spans) >= self.max_traces:
                    self.overflow += 1
                    return
                spans = self._spans[tid] = {}
            spans.setdefault("shed", t)

    def mark_committed(self, tids) -> None:
        """Commit spans for a merged group + queue them for the next
        grad-consumption mark."""
        if not self.enabled:
            return
        t = time.monotonic()
        with self._mu:
            for tid in tids:
                spans = self._spans.get(tid)
                if spans is not None and "commit" not in spans:
                    spans["commit"] = t
                    self._await_grad.append(tid)

    def mark_grad(self, ts: float | None = None) -> int:
        """Stamp every commit-pending trace with grad-consumption time.
        Called by the learner right after a fused-chunk dispatch (and by
        the fleet harness's consumer lane after each concurrent sample).
        Near-free when nothing is pending (one unlocked emptiness probe,
        benign race under the GIL)."""
        if not self._await_grad:
            return 0
        t = time.monotonic() if ts is None else ts
        n = 0
        with self._mu:
            while self._await_grad:
                tid = self._await_grad.popleft()
                spans = self._spans.get(tid)
                if spans is not None and "grad" not in spans:
                    spans["grad"] = t
                    n += 1
        return n

    # -- analysis (cold path) -----------------------------------------------
    def span_table(self) -> dict[int, dict]:
        with self._mu:
            return {tid: dict(spans) for tid, spans in self._spans.items()}

    def orphans(self) -> list[int]:
        """Admitted traces with no terminal span — each one is a leak in
        the pipeline's accounting (the K-shard propagation test pins
        this at zero after flush)."""
        with self._mu:
            return [tid for tid, spans in self._spans.items()
                    if "admission" in spans
                    and not any(t in spans for t in TERMINALS)]

    def latency_block(self) -> dict:
        """The artifact block: per-stage latency percentiles (ms) plus
        end-to-end wire-to-commit / wire-to-grad, the sample rate, and
        the trace accounting (completed / shed / orphaned / overflow)."""
        table = self.span_table()
        stages: dict[str, list[float]] = {label: [] for label, _, _ in _PAIRS}
        completed = shed = 0
        for spans in table.values():
            if "shed" in spans:
                shed += 1
            elif "commit" in spans:
                completed += 1
            for label, a, b in _PAIRS:
                # b >= a: pipeline pairs are naturally ordered, except
                # deal/grad — a frame's first grad-after-commit can
                # predate a later RE-deal of the same slot, in which
                # case the deal span did not feed that grad and the
                # pair is causally mispaired, not a negative latency
                if a in spans and b in spans and spans[b] >= spans[a]:
                    stages[label].append(1e3 * (spans[b] - spans[a]))
        with self._mu:
            rate, overflow = self.sample_rate, self.overflow
        return {
            "unit": "ms",
            "sample_rate": rate,
            "stages": {label: percentile_summary(vals)
                       for label, vals in stages.items()},
            "wire_to_grad": percentile_summary(stages["wire_to_grad"]),
            "n_traces": len(table),
            "completed": completed,
            "shed": shed,
            "orphans": len(self.orphans()),
            "overflow": overflow,
        }


# THE process-wide recorder (one receiver per process is the shipped
# topology). Senders never touch it — their trace state rides the wire.
RECORDER = TraceRecorder()
