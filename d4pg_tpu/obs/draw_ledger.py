"""DrawLedger: the runtime twin of jaxlint's rnggraph determinism pass.

The static families (22-24) prove the *shape* of the RNG discipline —
one SeedSequence branch per component, fixed draws per event, skip
before the first draw.  The ledger proves the *execution*: it wraps
component Generators in a counting proxy (or takes explicit
``count()`` calls), accumulates draw-call counts per named stream, and
exposes a canonical sha256 digest over the sorted ``stream=count``
table.  Two runs that claim "equal seeded offered load" must produce
the same digest for their schedule-class streams — the A/B chaos
drivers (sampler, elastic) pin exactly that, turning the equal-load
premise of every A/B gate from an argument into an oracle.

Stream naming convention: ``schedule.*`` streams are drawn while
materializing seeded schedules and models up front (kill schedules,
TrafficModel construction) — config-deterministic, so their counts are
comparable across arms and runs.  Everything else (``chaos.*`` per-
actor event draws) is runtime-paced: counted and reported, but only
the ``schedule.`` namespace participates in the A/B equality digest.

Counting unit: one draw-method *call* (not array elements) — the same
unit family 24's static interpreter reasons about, so a runtime count
can be read against the lint stream table directly.

House obs contract: stdlib-only (the proxy duck-types the Generator,
so numpy never gets imported here), and the one lock is ``_mu`` — a
terminal ``threading.Lock``: no path holding it acquires any other
lock, so ``count()`` is safe from under any tiered lock.
"""

from __future__ import annotations

import hashlib
import threading

SCHEDULE_PREFIX = "schedule."

# Generator draw surface the proxy intercepts (modern Generator plus
# the legacy RandomState spellings); everything else delegates
# untouched, so a wrapped stream is a drop-in Generator.
_DRAW_METHODS = frozenset({
    "beta", "binomial", "bytes", "chisquare", "choice", "dirichlet",
    "exponential", "gamma", "geometric", "gumbel", "hypergeometric",
    "integers", "laplace", "logistic", "lognormal", "logseries",
    "multinomial", "multivariate_normal", "negative_binomial",
    "noncentral_chisquare", "normal", "pareto", "permutation",
    "permuted", "poisson", "power", "random", "rayleigh", "shuffle",
    "standard_cauchy", "standard_exponential", "standard_gamma",
    "standard_normal", "standard_t", "triangular", "uniform",
    "vonmises", "wald", "weibull", "zipf",
    "rand", "randn", "randint", "random_sample",
})


class _CountedStream:
    """Duck-typed proxy over a Generator: draw methods count one call
    into the ledger then delegate; every other attribute passes
    through.  Never caches bound methods — the ledger's armed state is
    consulted per call."""

    __slots__ = ("_ledger", "_stream", "_rng")

    def __init__(self, ledger: "DrawLedger", stream: str, rng) -> None:
        self._ledger = ledger
        self._stream = stream
        self._rng = rng

    def __getattr__(self, name: str):
        attr = getattr(self._rng, name)
        if name in _DRAW_METHODS and callable(attr):
            ledger, stream = self._ledger, self._stream
            def counted(*args, **kwargs):
                ledger.count(stream)
                return attr(*args, **kwargs)
            return counted
        return attr


class DrawLedger:
    """Per-stream draw-call counts + canonical digest.

    Instances default to armed (A/B drivers build one per arm); the
    process-wide ``LEDGER`` starts disarmed and is armed by the fleet
    harness at run start, so wrapped component streams cost one
    attribute lookup and a bool check per draw outside chaos runs.
    """

    def __init__(self, armed: bool = True) -> None:
        self._mu = threading.Lock()  # terminal: guards _counts only
        self._armed = bool(armed)
        self._counts: dict[str, int] = {}

    # -- arming ------------------------------------------------------------
    def arm(self) -> None:
        self._armed = True

    def disarm(self) -> None:
        self._armed = False

    def reset(self, armed: bool | None = None) -> None:
        with self._mu:
            self._counts.clear()
        if armed is not None:
            self._armed = bool(armed)

    # -- counting ----------------------------------------------------------
    def count(self, stream: str, n: int = 1) -> None:
        """Record ``n`` draw calls against ``stream``; no-op unless
        armed (the disarmed fast path takes no lock)."""
        if not self._armed:
            return
        with self._mu:
            self._counts[stream] = self._counts.get(stream, 0) + int(n)

    def wrap(self, stream: str, rng):
        """Wrap a Generator so its draw-method calls count against
        ``stream``.  The proxy is transparent for everything else."""
        return _CountedStream(self, stream, rng)

    # -- export ------------------------------------------------------------
    def counts(self) -> dict[str, int]:
        with self._mu:
            return dict(self._counts)

    def digest(self, prefix: str = "") -> str:
        """sha256 over the sorted ``stream=count`` lines whose stream
        name starts with ``prefix`` — the canonical form, so equal
        counted histories hash equal regardless of arrival order."""
        snap = self.counts()
        h = hashlib.sha256()
        for name in sorted(snap):
            if name.startswith(prefix):
                h.update(f"{name}={snap[name]}\n".encode("ascii"))
        return h.hexdigest()

    def export(self) -> dict:
        """The ``draw_ledger`` artifact block: per-stream counts, the
        all-streams digest, and the schedule-namespace digest the A/B
        drivers pin across arms."""
        snap = self.counts()
        return {
            "streams": dict(sorted(snap.items())),
            "total_draws": sum(snap.values()),
            "digest": self.digest(),
            "schedule_digest": self.digest(SCHEDULE_PREFIX),
        }


# Process-wide ledger (disarmed until a harness arms it), mirroring
# obs.registry.REGISTRY / obs.flight.RECORDER.
LEDGER = DrawLedger(armed=False)
