"""Accelerator liveness probe for driver entry points.

The tunnel to the chip can wedge in a way that makes ``jax.devices()``
hang forever (observed on this image) — an in-process try/except cannot
catch a hang, and a hung bench/dryrun costs the round its artifact. So
the probe runs in a SUBPROCESS with a timeout, and also reports which
platform actually resolved: ``jax.devices()`` succeeding proves nothing
about an accelerator (JAX silently falls back to CPU), so callers must
not label CPU-measured numbers as accelerator numbers.
"""

from __future__ import annotations

import subprocess
import sys

_CHILD = "import jax; print(jax.devices()[0].platform)"


def accelerator_alive(timeout: float = 180.0) -> bool:
    """True iff a NON-CPU backend initializes and answers within
    ``timeout``. On False, callers force ``jax_platforms=cpu`` BEFORE any
    backend-initializing call and record the fallback."""
    try:
        r = subprocess.run([sys.executable, "-c", _CHILD],
                           timeout=timeout, capture_output=True, text=True)
    except (subprocess.TimeoutExpired, OSError):
        return False
    return r.returncode == 0 and r.stdout.strip().lower() != "cpu"
