"""Accelerator liveness probe for driver entry points.

The tunnel to the chip can wedge in a way that makes ``jax.devices()``
hang forever (observed on this image) — an in-process try/except cannot
catch a hang, and a hung bench/dryrun costs the round its artifact. So
the probe runs in a SUBPROCESS with a timeout, and also reports which
platform actually resolved: ``jax.devices()`` succeeding proves nothing
about an accelerator (JAX silently falls back to CPU), so callers must
not label CPU-measured numbers as accelerator numbers.

The probing entry points (``bench.py``, ``__graft_entry__``,
``d4pg_tpu.train`` via ``--platform auto``) share :func:`ensure_backend`;
``d4pg_tpu.actor_main`` instead forces CPU outright for its default
``--actor_device cpu`` (no probe — with ``--actor_device default`` a
wedged accelerator on the actor host will still hang backend init). The
``D4PG_PLATFORM`` env var (``accel`` / ``cpu``) skips the probe for tight
benchmark loops or forces the host backend outright.
"""

from __future__ import annotations

import os
import subprocess
import sys

# The child must DISPATCH a computation, not just enumerate devices:
# jax.devices() succeeds on a libtpu-version-mismatched chip while the
# first apply_primitive raises FAILED_PRECONDITION (MULTICHIP_r04's
# failure). Only a completed jitted op proves the backend usable.
_CHILD = ("import jax; "
          "jax.block_until_ready(jax.jit(lambda x: x + 1)(1.0)); "
          "print(jax.devices()[0].platform)")


def probe_platform(timeout: float = 90.0) -> str:
    """Resolve the default backend in a throwaway subprocess.

    Returns ``'accel'`` (a non-CPU backend answered), ``'cpu'`` (backend
    init succeeded but only CPU exists — an accelerator-less machine, not
    a failure), or ``'dead'`` (init crashed or hung past ``timeout`` — the
    wedged-tunnel case an in-process try/except cannot catch)."""
    try:
        r = subprocess.run([sys.executable, "-c", _CHILD],
                           timeout=timeout, capture_output=True, text=True)
    except (subprocess.TimeoutExpired, OSError):
        return "dead"
    if r.returncode != 0:
        return "dead"
    return "cpu" if r.stdout.strip().lower() == "cpu" else "accel"


def accelerator_alive(timeout: float = 180.0) -> bool:
    """True iff a NON-CPU backend initializes and answers within
    ``timeout``. On False, callers force ``jax_platforms=cpu`` BEFORE any
    backend-initializing call and record the fallback."""
    return probe_platform(timeout) == "accel"


def ensure_backend(timeout: float = 90.0) -> str:
    """Probe the default backend and force CPU when it is unusable.

    The single fallback policy shared by every entry point. Returns
      - ``'accel'``        — accelerator alive; default backend untouched,
      - ``'cpu-absent'``   — no accelerator on this machine; CPU forced
                             (so later init skips plugin discovery),
      - ``'cpu-wedged'``   — backend init hung or crashed (wedged tunnel);
                             CPU forced,
      - ``'cpu-forced'``   — ``D4PG_PLATFORM=cpu`` requested CPU outright.
    ``D4PG_PLATFORM=accel`` skips the probe (and its duplicate backend
    init) for tight loops on known-healthy hardware.

    Must run before any backend-initializing jax call in the process.
    """
    override = os.environ.get("D4PG_PLATFORM", "").lower()
    if override == "accel":
        return "accel"
    import jax

    if override == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return "cpu-forced"
    status = probe_platform(timeout)
    if status == "accel":
        return "accel"
    jax.config.update("jax_platforms", "cpu")
    return "cpu-absent" if status == "cpu" else "cpu-wedged"


def describe(status: str) -> str:
    """Human-readable reason for an :func:`ensure_backend` status — the one
    phrasing every entry point logs/records."""
    return {
        "accel": "accelerator backend alive",
        "cpu-wedged": "accelerator backend hung or crashed (wedged tunnel?)",
        "cpu-absent": "no accelerator on this machine",
        "cpu-forced": "CPU backend forced (D4PG_PLATFORM=cpu)",
    }[status]
