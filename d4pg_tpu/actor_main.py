"""Remote actor runner: ``python -m d4pg_tpu.actor_main --learner_host ...``

Runs acting on a separate host (TPU-VM actor fleet), streaming transitions
to the learner's ``TransitionReceiver`` and pulling weights from its
``WeightServer`` — the cross-host replacement for the reference's fork'd
same-host workers sharing memory (``main.py:393-405``). Actors are
stateless: kill one and start another; replay and weights live with the
learner.
"""

from __future__ import annotations

import argparse

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.distributed.actor import (
    ActorConfig,
    ActorWorker,
    GoalActorWorker,
)
from d4pg_tpu.distributed.transport import CoalescingSender, TransitionSender
from d4pg_tpu.distributed.weight_server import WeightClient
from d4pg_tpu.envs import EnvPool
from d4pg_tpu.replay.uniform import TransitionBatch
from d4pg_tpu.train import infer_dims, make_env_fn


class RemoteReplayClient:
    """ReplayService-shaped adapter over the transition socket."""

    def __init__(self, sender: TransitionSender):
        self._sender = sender

    def add(self, batch: TransitionBatch, actor_id: str = "remote",
            block: bool = True, timeout: float | None = None,
            count_env_steps: bool = True) -> bool:
        # TCP provides ordering + backpressure. count_env_steps crosses the
        # wire as a frame flag so remote HER relabels don't inflate the
        # learner's env-step counter. Under --drop_on_timeout the sender
        # sheds timed-out frames and returns False — the actor counts the
        # loss (dropped_batches) and keeps acting instead of dying.
        del actor_id, block, timeout
        return self._sender.send(batch, count_env_steps=count_env_steps)


def run_actor(
    cfg: ExperimentConfig,
    learner_host: str,
    transitions_port: int,
    weights_port: int,
    actor_id: str = "remote-0",
    max_ticks: int | None = None,
    secret: str | None = None,
    send_timeout: float = 300.0,
    send_retries: int | None = None,
    drop_on_timeout: bool = False,
    codec: str = "npz",
    trace_sample: float = 0.0,
    expect_generation: bool = False,
    weight_codec: str | None = None,
    weight_delta: bool = True,
    policy_port: int | None = None,
    policy_timeout: float = 0.5,
) -> int:
    cfg = cfg.resolve()
    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    config = cfg.learner_config(obs_dim, act_dim)
    # Block-coalescing transport (docs/architecture.md "Ingest plane"):
    # per-tick rows ride one frame per block instead of one frame per
    # send, with backpressure-aware block sizing. Episode boundaries and
    # close() flush partial blocks. The fleet-degradation knobs
    # (--send_timeout/--send_retries/--drop_on_timeout) bound how long a
    # frame may retry and what happens at the bound: raise (default, a
    # lone actor should fail loudly) or shed-and-count (a 256-actor fleet
    # member should lose rows, not wedge).
    # --codec raw: the sharded receiver's native v2 frames — ~25x cheaper
    # to encode+decode than npz and admissible (routed/shed/counted) from
    # the fixed header alone; npz (default) interops with any receiver.
    # --trace_sample: fraction of raw frames stamped with a trace id +
    # birth timestamp (the wire-to-grad tracing plane, d4pg_tpu/obs);
    # inert at codec='npz' — only v2 headers carry the extension.
    # --expect_generation: read the service-generation greeting after the
    # handshake and stamp raw frames with it, so a learner that restarted
    # and restored a snapshot can fence pre-crash frames at admission
    # (the crash-recovery plane's exactly-once rule); requires a greeting
    # receiver (train.py serve mode always greets).
    sender = CoalescingSender(learner_host, transitions_port,
                              actor_id=actor_id, secret=secret,
                              retry_timeout=send_timeout,
                              max_retries=send_retries,
                              drop_on_timeout=drop_on_timeout,
                              codec=codec,
                              trace_sample=trace_sample,
                              expect_generation=expect_generation)
    # --weight_codec opts into the v2 weight plane (delta-encoded pulls,
    # optional bf16/int8 quantized transport, generation fencing across
    # learner restarts); the default stays the v1 full-snapshot puller —
    # the server answers both protocols on one port, per client.
    if weight_codec is not None:
        from d4pg_tpu.distributed.weight_plane import WeightPlaneClient

        weights = WeightPlaneClient(learner_host, weights_port,
                                    codec=weight_codec, delta=weight_delta,
                                    secret=secret)
    else:
        weights = WeightClient(learner_host, weights_port, secret=secret)
    actor_cfg = ActorConfig(
        epsilon_0=cfg.epsilon_0, min_epsilon=cfg.min_epsilon,
        epsilon_horizon=cfg.epsilon_horizon, n_step=cfg.n_steps,
        gamma=cfg.gamma, reward_scale=cfg.reward_scale, noise=cfg.noise,
        random_eps=cfg.random_eps, ou_theta=cfg.ou_theta,
        ou_sigma=cfg.ou_sigma, ou_mu=cfg.ou_mu, device=cfg.actor_device,
    )
    pool = None
    goal_env = None
    if cfg.her:
        # remote goal actor: whole episodes on one env, originals + HER
        # relabels streamed with the count_env_steps frame flag so the
        # learner's env-step counter stays honest
        if cfg.num_envs > 1:
            print(f"[{actor_id}] --her runs a SINGLE env per remote actor "
                  f"(episode-granular HER relabeling); ignoring "
                  f"--num_envs {cfg.num_envs}. Launch more actor processes "
                  "for width.", flush=True)
        goal_env = make_env_fn(cfg, seed=cfg.seed)()
        actor = GoalActorWorker(
            actor_id, config, actor_cfg, goal_env,
            RemoteReplayClient(sender), weights, her_ratio=cfg.her_ratio,
            rng_seed=cfg.seed, seed=cfg.seed,
        )
    else:
        pool = EnvPool(
            [make_env_fn(cfg, seed=cfg.seed + i) for i in range(cfg.num_envs)],
            seed=cfg.seed,
        )
        policy = None
        if policy_port is not None:
            # --policy_port: SEED-style serving — greedy mu comes from
            # the learner's continuous-batching PolicyInferenceServer;
            # exploration noise stays here. The weight puller above
            # still runs, but only to back the degradation ladder's
            # cached-params fallback (server down -> local mu, counted).
            import zlib as _zlib

            from d4pg_tpu.serving.client import RemotePolicyClient

            policy = RemotePolicyClient(
                config, actor_cfg, learner_host, policy_port,
                secret=secret,
                lane_id=_zlib.crc32(actor_id.encode()) & 0xFFF,
                seed=cfg.seed, timeout=policy_timeout, weights=weights)
        actor = ActorWorker(
            actor_id, config, actor_cfg, pool, RemoteReplayClient(sender),
            weights, seed=cfg.seed, obs_dtype=obs_dtype, policy=policy,
        )
    try:
        done = 0
        while max_ticks is None or done < max_ticks:
            if cfg.her:
                done += actor.run_episode(cfg.max_steps)
            else:
                chunk = 1000 if max_ticks is None else min(1000, max_ticks - done)
                actor.run(chunk)
                done += chunk
            sender.flush()  # partial blocks must not outlive the tick loop
    except (KeyboardInterrupt, ConnectionError, BrokenPipeError, OSError) as e:
        print(f"actor {actor_id} stopping: {type(e).__name__}: {e}")
    finally:
        if sender.frames_dropped or actor.dropped_batches:
            # shed rows are benign but NEVER silent (fleet-plane rule)
            print(f"actor {actor_id} shed {sender.frames_dropped} frames "
                  f"({sender.retries} transport retries) under backpressure",
                  flush=True)
        sender.close()
        weights.close()
        if pool is not None:
            pool.close()
        if goal_env is not None and hasattr(goal_env, "close"):
            goal_env.close()
    return actor.env_steps


def run_local_actor_process(
    cfg: ExperimentConfig,
    learner_host: str,
    transitions_port: int,
    weights_port: int,
    actor_id: str,
    secret: str | None = None,
    expect_generation: bool = False,
) -> None:
    """Entry point for locally SPAWNED actor processes (``train.py
    --actor_procs N`` — the proper replacement for the reference's
    ``mp.Process`` fan-out, ``main.py:399-405``, which shared memory and
    the GIL-free illusion; these are real processes talking TCP).

    Forces the CPU backend first: the accelerator belongs to the learner
    process, and actor inference on these MLPs is host-friendly.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        run_actor(cfg, learner_host, transitions_port, weights_port,
                  actor_id=actor_id, secret=secret,
                  expect_generation=expect_generation)
    except KeyboardInterrupt:
        pass


def main(argv=None):
    p = argparse.ArgumentParser(prog="d4pg_tpu.actor_main")
    p.add_argument("--learner_host", required=True)
    p.add_argument("--transitions_port", type=int, required=True)
    p.add_argument("--weights_port", type=int, required=True)
    p.add_argument("--actor_id", default="remote-0")
    p.add_argument("--env", default="Pendulum-v1")
    p.add_argument("--num_envs", type=int, default=4,
                   help="vectorized env pool width; with --her 1 the remote "
                        "actor always runs a single env (launch more actor "
                        "processes for width)")
    p.add_argument("--n_steps", type=int, default=None,
                   help="n-step horizon (default: from the env preset)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--noise", choices=("gaussian", "ou"), default="gaussian")
    p.add_argument("--random_eps", type=float, default=0.0)
    p.add_argument("--her", type=int, choices=(0, 1), default=0)
    p.add_argument("--her_ratio", type=float, default=0.8)
    p.add_argument("--max_steps", type=int, default=None,
                   help="episode horizon (default: from the env preset)")
    p.add_argument("--max_ticks", type=int, default=None)
    p.add_argument("--secret", default="",
                   help="shared secret matching the learner's --serve_secret")
    p.add_argument("--actor_device", choices=("cpu", "default"), default="cpu")
    p.add_argument("--send_timeout", type=float, default=300.0,
                   help="seconds a frame may retry across reconnects")
    p.add_argument("--send_retries", type=int, default=None,
                   help="max reconnect attempts per frame (default: "
                        "unbounded within --send_timeout)")
    p.add_argument("--drop_on_timeout", type=int, choices=(0, 1), default=0,
                   help="1: shed timed-out frames (counted) and keep "
                        "acting — the fleet-member policy; 0: raise and "
                        "stop (default)")
    p.add_argument("--codec", choices=("npz", "raw"), default="npz",
                   help="wire frame format: npz (legacy, self-describing) "
                        "or raw (v2 column frames — the sharded receiver's "
                        "native format, ~25x cheaper per frame)")
    p.add_argument("--trace_sample", type=float, default=0.0,
                   help="fraction of frames stamped with a wire-to-grad "
                        "trace id + birth timestamp in the v2 header "
                        "extension (requires --codec raw; the learner "
                        "aggregates per-stage latency histograms)")
    p.add_argument("--expect_generation", type=int, choices=(0, 1), default=0,
                   help="1: read the learner's service-generation greeting "
                        "on connect and stamp raw frames with it, so a "
                        "restarted learner fences pre-crash frames instead "
                        "of double-inserting them (requires a greeting "
                        "learner, e.g. train.py serve mode)")
    p.add_argument("--weight_codec", choices=("f32", "bf16", "int8"),
                   default=None,
                   help="opt into the v2 weight plane with this transport "
                        "codec: f32 (full precision), bf16 (2x smaller, "
                        "rel err <= 2^-8) or int8 (4x smaller, per-tensor "
                        "scale); default: the v1 full-snapshot puller")
    p.add_argument("--policy_port", type=int, default=None,
                   help="query greedy actions from the learner's "
                        "continuous-batching policy server on this port "
                        "(train.py --serve_policy) instead of acting "
                        "locally; on timeout/corruption the actor degrades "
                        "to its cached weights — counted, never a stall "
                        "(gaussian noise only)")
    p.add_argument("--policy_timeout", type=float, default=0.5,
                   help="per-request serving timeout (s) before the "
                        "cached-params fallback")
    p.add_argument("--weight_delta", type=int, choices=(0, 1), default=1,
                   help="with --weight_codec: 1 (default) pulls per-tensor "
                        "deltas against the last accepted version when the "
                        "server still holds it in its window; 0 always "
                        "pulls full frames")
    ns = p.parse_args(argv)
    if ns.actor_device == "cpu":
        # Acting runs on host CPU; force the platform BEFORE any jax call
        # so even backend discovery never touches a (possibly wedged)
        # accelerator plugin on this actor host.
        import jax

        jax.config.update("jax_platforms", "cpu")
    cfg = ExperimentConfig(
        env=ns.env, num_envs=ns.num_envs, n_steps=ns.n_steps,
        max_steps=ns.max_steps, seed=ns.seed, noise=ns.noise,
        random_eps=ns.random_eps, her=bool(ns.her), her_ratio=ns.her_ratio,
        actor_device=ns.actor_device)
    steps = run_actor(cfg, ns.learner_host, ns.transitions_port,
                      ns.weights_port, ns.actor_id, ns.max_ticks,
                      secret=ns.secret or None,
                      send_timeout=ns.send_timeout,
                      send_retries=ns.send_retries,
                      drop_on_timeout=bool(ns.drop_on_timeout),
                      codec=ns.codec, trace_sample=ns.trace_sample,
                      expect_generation=bool(ns.expect_generation),
                      weight_codec=ns.weight_codec,
                      weight_delta=bool(ns.weight_delta),
                      policy_port=ns.policy_port,
                      policy_timeout=ns.policy_timeout)
    print(f"collected {steps} env steps")


if __name__ == "__main__":
    main()
