"""Remote actor runner: ``python -m d4pg_tpu.actor_main --learner_host ...``

Runs acting on a separate host (TPU-VM actor fleet), streaming transitions
to the learner's ``TransitionReceiver`` and pulling weights from its
``WeightServer`` — the cross-host replacement for the reference's fork'd
same-host workers sharing memory (``main.py:393-405``). Actors are
stateless: kill one and start another; replay and weights live with the
learner.
"""

from __future__ import annotations

import argparse

from d4pg_tpu.config import ExperimentConfig
from d4pg_tpu.distributed.actor import ActorConfig, ActorWorker
from d4pg_tpu.distributed.transport import TransitionSender
from d4pg_tpu.distributed.weight_server import WeightClient
from d4pg_tpu.envs import EnvPool
from d4pg_tpu.replay.uniform import TransitionBatch
from d4pg_tpu.train import infer_dims, make_env_fn


class RemoteReplayClient:
    """ReplayService-shaped adapter over the transition socket."""

    def __init__(self, sender: TransitionSender):
        self._sender = sender

    def add(self, batch: TransitionBatch, actor_id: str = "remote",
            block: bool = True, timeout: float | None = None,
            count_env_steps: bool = True) -> bool:
        # TCP provides ordering + backpressure. count_env_steps does not
        # cross the wire: the learner counts every remote row as an env
        # step (remote HER actors would need a frame flag — not wired).
        del actor_id, block, timeout, count_env_steps
        self._sender.send(batch)
        return True


def run_actor(
    cfg: ExperimentConfig,
    learner_host: str,
    transitions_port: int,
    weights_port: int,
    actor_id: str = "remote-0",
    max_ticks: int | None = None,
    secret: str | None = None,
) -> int:
    cfg = cfg.resolve()
    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    config = cfg.learner_config(obs_dim, act_dim)
    sender = TransitionSender(learner_host, transitions_port,
                              actor_id=actor_id, secret=secret)
    weights = WeightClient(learner_host, weights_port, secret=secret)
    pool = EnvPool(
        [make_env_fn(cfg, seed=cfg.seed + i) for i in range(cfg.num_envs)],
        seed=cfg.seed,
    )
    actor = ActorWorker(
        actor_id, config,
        ActorConfig(
            epsilon_0=cfg.epsilon_0, min_epsilon=cfg.min_epsilon,
            epsilon_horizon=cfg.epsilon_horizon, n_step=cfg.n_steps,
            gamma=cfg.gamma, reward_scale=cfg.reward_scale, noise=cfg.noise,
            random_eps=cfg.random_eps, ou_theta=cfg.ou_theta,
            ou_sigma=cfg.ou_sigma, ou_mu=cfg.ou_mu, device=cfg.actor_device,
        ),
        pool, RemoteReplayClient(sender), weights, seed=cfg.seed,
        obs_dtype=obs_dtype,
    )
    try:
        if max_ticks is None:
            while True:
                actor.run(1000)
        else:
            actor.run(max_ticks)
    except (KeyboardInterrupt, ConnectionError, BrokenPipeError, OSError) as e:
        print(f"actor {actor_id} stopping: {type(e).__name__}: {e}")
    finally:
        sender.close()
        weights.close()
        pool.close()
    return actor.env_steps


def run_local_actor_process(
    cfg: ExperimentConfig,
    learner_host: str,
    transitions_port: int,
    weights_port: int,
    actor_id: str,
    secret: str | None = None,
) -> None:
    """Entry point for locally SPAWNED actor processes (``train.py
    --actor_procs N`` — the proper replacement for the reference's
    ``mp.Process`` fan-out, ``main.py:399-405``, which shared memory and
    the GIL-free illusion; these are real processes talking TCP).

    Forces the CPU backend first: the accelerator belongs to the learner
    process, and actor inference on these MLPs is host-friendly.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        run_actor(cfg, learner_host, transitions_port, weights_port,
                  actor_id=actor_id, secret=secret)
    except KeyboardInterrupt:
        pass


def main(argv=None):
    p = argparse.ArgumentParser(prog="d4pg_tpu.actor_main")
    p.add_argument("--learner_host", required=True)
    p.add_argument("--transitions_port", type=int, required=True)
    p.add_argument("--weights_port", type=int, required=True)
    p.add_argument("--actor_id", default="remote-0")
    p.add_argument("--env", default="Pendulum-v1")
    p.add_argument("--num_envs", type=int, default=4)
    p.add_argument("--n_steps", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--noise", choices=("gaussian", "ou"), default="gaussian")
    p.add_argument("--random_eps", type=float, default=0.0)
    p.add_argument("--max_ticks", type=int, default=None)
    p.add_argument("--secret", default="",
                   help="shared secret matching the learner's --serve_secret")
    p.add_argument("--actor_device", choices=("cpu", "default"), default="cpu")
    ns = p.parse_args(argv)
    if ns.actor_device == "cpu":
        # Acting runs on host CPU; force the platform BEFORE any jax call
        # so even backend discovery never touches a (possibly wedged)
        # accelerator plugin on this actor host.
        import jax

        jax.config.update("jax_platforms", "cpu")
    cfg = ExperimentConfig(env=ns.env, num_envs=ns.num_envs, n_steps=ns.n_steps,
                           seed=ns.seed, noise=ns.noise,
                           random_eps=ns.random_eps,
                           actor_device=ns.actor_device)
    steps = run_actor(cfg, ns.learner_host, ns.transitions_port,
                      ns.weights_port, ns.actor_id, ns.max_ticks,
                      secret=ns.secret or None)
    print(f"collected {steps} env steps")


if __name__ == "__main__":
    main()
