"""Training driver: ``python -m d4pg_tpu.train --env Pendulum-v1 ...``

Parity: the reference's ``main.py`` orchestration (SURVEY.md S1/C15): the
HER-paper-shaped loop — epochs x cycles x (collect episodes + train steps)
with per-cycle eval, TensorBoard logging and checkpointing
(``main.py:299-368``) — rebuilt around the decoupled TPU runtime:

  - actors collect into the central ``ReplayService`` (vectorized pool,
    batched jit inference) instead of per-process buffers;
  - the learner runs the single jit'd (optionally mesh-sharded) update;
  - weights flow learner -> actors via the versioned ``WeightStore``
    instead of shared-memory state_dict pulls;
  - checkpoints are full-state Orbax saves with ``--resume 1`` restore
    (the reference can only save, ``main.py:367-368``).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import traceback

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.config import ExperimentConfig, parse_args
from d4pg_tpu.distributed import (
    ActorConfig,
    ActorWorker,
    AsyncEvaluator,
    Evaluator,
    ReplayService,
    WeightStore,
)
from d4pg_tpu.distributed.actor import GoalActorWorker
from d4pg_tpu.envs import (
    EnvPool,
    FakeGoalEnv,
    PixelPointEnv,
    PointMassEnv,
    get_preset,
)
from d4pg_tpu.io import CheckpointManager, CsvLogger, MetricsBus, TensorBoardSink
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.io.profiling import StepTimer, xla_trace
from d4pg_tpu.learner import init_state, make_multi_update, make_update
from d4pg_tpu.learner.loop import FusedLoop
from d4pg_tpu.learner.pipeline import ChunkPipeline
from d4pg_tpu.parallel import (
    MeshSpec,
    make_mesh,
    make_sharded_multi_update,
    make_sharded_update,
    replicate_state,
    shard_batch,
    stacked_sharding,
)
from d4pg_tpu.parallel.mesh import DATA_AXIS
from d4pg_tpu.replay import LinearSchedule, PrioritizedReplayBuffer, ReplayBuffer
from d4pg_tpu.replay.uniform import TransitionBatch


def make_env_fn(cfg: ExperimentConfig, seed: int):
    """Build one env instance; gymnasium by id, with fake-env fallbacks for
    ids 'point' and 'fake-goal' (tests/smoke, SURVEY.md §4)."""
    if ((cfg.env in ("point", "fake-goal")
         or cfg.env.startswith("point-slow:")) and cfg.frame_stack > 1):
        # fail loudly rather than silently training on unstacked frames —
        # the exact POMDP failure the flag exists to fix
        raise ValueError(
            f"--frame_stack {cfg.frame_stack} requires a pixel env; "
            f"{cfg.env!r} is state-observation")
    if cfg.env == "point":
        return lambda: PointMassEnv(horizon=cfg.max_steps, seed=seed)
    if cfg.env.startswith("point-slow:"):
        # 'point-slow:<ms>' — point mass with a fixed <ms> wall cost per
        # step, emulating a physics-bound env for transport-plane scaling
        # measurements (analysis/actor_scaling.py) without MuJoCo
        from d4pg_tpu.envs.fake import SlowEnv

        step_ms = float(cfg.env.split(":", 1)[1])
        return lambda: SlowEnv(PointMassEnv(horizon=cfg.max_steps, seed=seed),
                               step_ms / 1e3)
    if cfg.env == "fake-goal":
        return lambda: FakeGoalEnv(horizon=cfg.max_steps, seed=seed)
    def stack(make_pixel_env):
        # FrameStack restores the Markov property for pixel control
        # (single frames hide velocities); no-op at the default k=1
        if cfg.frame_stack <= 1:
            return make_pixel_env
        from d4pg_tpu.envs.wrappers import FrameStack

        return lambda: FrameStack(make_pixel_env(), cfg.frame_stack)

    if cfg.env == "pixel-point":
        return stack(lambda: PixelPointEnv(horizon=cfg.max_steps, seed=seed))
    from d4pg_tpu.envs.dmc import DMControlEnv, parse_dmc_id

    dmc = parse_dmc_id(cfg.env)
    if dmc is not None:
        domain, task, pixels = dmc
        if not pixels and cfg.frame_stack > 1:
            raise ValueError(
                f"--frame_stack {cfg.frame_stack} requires a pixel env; "
                f"{cfg.env!r} is state-observation")
        mk = lambda: DMControlEnv(domain, task, pixels=pixels, seed=seed,
                                  height=cfg.pixel_size,
                                  width=cfg.pixel_size)
        return stack(mk) if pixels else mk
    import gymnasium as gym

    def make():
        try:
            env = gym.make(cfg.env)
        except (gym.error.NameNotFound, gym.error.VersionNotFound):
            # Fetch/Adroit/Shadow-Hand live in gymnasium_robotics, which
            # registers its ids only once imported (BASELINE.md config #5).
            # Their MuJoCo-2-era MJCF needs the apirate compat shim to load
            # under MuJoCo 3 (envs/robotics_compat.py).
            import gymnasium_robotics

            from d4pg_tpu.envs.robotics_compat import install

            install()
            gym.register_envs(gymnasium_robotics)
            env = gym.make(cfg.env)
        if cfg.frame_stack > 1:
            # stack 3-D (pixel) observations; anything else is a config
            # error — silently dropping the flag would train on single
            # frames, the exact POMDP failure it exists to fix
            if len(env.observation_space.shape or ()) != 3:
                raise ValueError(
                    f"--frame_stack {cfg.frame_stack} requires pixel "
                    f"[H, W, C] observations; {cfg.env!r} has shape "
                    f"{env.observation_space.shape}")
            from d4pg_tpu.envs.wrappers import FrameStack

            return FrameStack(env, cfg.frame_stack)
        return env

    return make


def infer_dims(cfg: ExperimentConfig) -> tuple[int | tuple, int, np.dtype]:
    """obs spec, act dim, and obs storage dtype; goal-concatenated for HER
    envs (``main.py:73-80``), an [H, W, C] shape tuple for pixel envs. The
    dtype comes from an actual reset observation — rank alone must not
    decide it (a float-valued 3-D obs stored as uint8 would be silently
    truncated to garbage)."""
    env = make_env_fn(cfg, seed=0)()
    try:
        shape = env.observation_space.shape
        obs_dtype = np.dtype(np.float32)
        if cfg.her:
            obs, _ = env.reset(seed=0)
            obs_dim = obs["observation"].shape[-1] + obs["desired_goal"].shape[-1]
        elif len(shape) == 3:  # pixels
            obs_dim = tuple(shape)
            obs, _ = env.reset(seed=0)
            obs_dtype = np.asarray(obs).dtype
            if np.issubdtype(obs_dtype, np.floating):
                obs_dtype = np.dtype(np.float32)
        else:
            obs_dim = int(np.prod(shape))
        act_dim = int(np.prod(env.action_space.shape))
    finally:
        env.close()
    return obs_dim, act_dim, obs_dtype


def _host_replay_path(run_dir: str, process_index: int) -> str:
    from d4pg_tpu.io.checkpoint import replay_sidecar_path

    return replay_sidecar_path(run_dir, process_index)


def _save_host_replay(run_dir: str, process_index: int, step: int,
                      snap: dict) -> None:
    """Sidecar replay snapshot — EVERY host's, process 0 included (round
    4: replay used to ride the Orbax ``extra`` payload on process 0, but
    that couples replay availability to the checkpoint retention window —
    with a coarser ``--checkpoint_replay_every`` cadence the LATEST state
    checkpoint usually lacks the payload and resume silently restarted
    with an empty buffer). Stamped with the learner step it was taken at.
    The io-layer writer (``io/checkpoint.save_replay_sidecar``) does the
    write-then-rename AND frames the pickle with a CRC, so a crash
    mid-save leaves the previous snapshot intact and a torn file is
    rejected cleanly at load instead of half-restoring."""
    from d4pg_tpu.io.checkpoint import save_replay_sidecar

    save_replay_sidecar(run_dir, process_index, step, snap)


def _load_host_replay(run_dir: str, process_index: int,
                      step: int) -> tuple[dict | None, int]:
    """Load this host's replay sidecar; returns ``(snap, snap_step)``
    (``(None, -1)`` when absent/refused). A snapshot OLDER than the
    restored state is accepted with a warning — stale rows are still
    valid experience, and an almost-full slightly-stale buffer resumes
    far better than an empty one (the strict-equality rule this replaces
    emptied the buffer whenever the replay cadence was coarser than the
    state cadence). A snapshot NEWER than the state is refused: the save
    site commits the state checkpoint BEFORE renaming the sidecar, so
    ahead-of-state means mixed-up run dirs or a rolled-back checkpoint.
    A CORRUPT sidecar (CRC/format failure) is refused the same way, with
    the io layer's diagnostic — learner-only resume beats poisoning the
    buffer with a torn snapshot. Multi-host fused restores additionally
    require the snapshot step to AGREE across hosts (see the resume
    site) — per-host staleness is fine for independent host buffers, but
    the sharded device buffer is one logical store whose shard-sets must
    come from one save moment."""
    from d4pg_tpu.io.checkpoint import (SnapshotCorruptError,
                                        load_replay_sidecar)

    try:
        loaded = load_replay_sidecar(run_dir, process_index)
    except SnapshotCorruptError as e:
        print(f"[p{process_index}] replay sidecar is corrupt ({e}); "
              "refusing it — resuming learner-only with an empty buffer",
              flush=True)
        return None, -1
    if loaded is None:
        return None, -1
    snap, snap_step = loaded
    if snap_step > int(step):
        print(f"[p{process_index}] replay sidecar is from step "
              f"{snap_step}, AHEAD of the restored state at step {step}; "
              "refusing it (mixed run dirs?) — starting with an empty "
              "buffer", flush=True)
        return None, -1
    if snap_step < int(step):
        print(f"[p{process_index}] replay sidecar is from step "
              f"{snap_step} ({int(step) - snap_step} steps behind the "
              "restored state); resuming with the slightly-stale buffer",
              flush=True)
    return snap, snap_step


def _restore_replay(service, snap: dict, env_steps: int) -> None:
    """Land a sidecar snapshot in the service. A SERVICE-level snapshot
    (crash-recovery plane: buffer cut + ticket floor + generation) goes
    through ``ReplayService.restore`` — which also bumps the generation
    so pre-crash raw frames fence at admission; a legacy buffer-only
    dict keeps the old ``load_replay_state`` path. The env-step counter
    stays with the CHECKPOINT's value either way: a stale sidecar must
    not roll the interaction ledger back below the restored state's."""
    if isinstance(snap, dict) and "buffer" in snap:
        service.restore(snap)
        service.set_env_steps(env_steps)
    else:
        service.load_replay_state(snap)


def train(cfg: ExperimentConfig) -> dict:
    cfg = cfg.resolve()
    if cfg.platform == "cpu":
        # honor an explicit CPU request for programmatic callers too (the
        # CLI path already forced it in main()); a no-op if the backend is
        # already pinned. 'auto' probing stays CLI-only — a subprocess
        # probe per train() call would tax every test/embedding caller.
        jax.config.update("jax_platforms", "cpu")
    # Multi-host SPMD (parallel/multihost.py): every host runs this same
    # function with identical flags; host-side work (replay, actors) is
    # per-host, device work spans the global mesh. Process 0 owns io/eval.
    multi_host = jax.process_count() > 1
    is_main = jax.process_index() == 0
    run_dir = os.path.join(cfg.log_dir, cfg.run_name())
    # every process may write here (multi-host hosts > 0 put their replay
    # sidecar snapshots in the run dir)
    os.makedirs(run_dir, exist_ok=True)

    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    config = cfg.learner_config(obs_dim, act_dim)

    # --- learner state + update (single-device or sharded) ----------------
    mesh = None
    if multi_host:
        from functools import partial

        from d4pg_tpu.parallel import multihost

        mesh = multihost.global_mesh()
        # identical seed on every host -> identical replicated state;
        # constructed inside jit because host device_put cannot address
        # other hosts' devices
        state = multihost.replicate_state_global(
            partial(init_state, config, jax.random.key(cfg.seed)), mesh)
        update = make_sharded_update(config, mesh, donate=True,
                                     use_is_weights=cfg.prioritized_replay)
    elif cfg.data_parallel > 1:
        mesh = make_mesh(MeshSpec(data_parallel=cfg.data_parallel),
                         devices=jax.devices()[:cfg.data_parallel])
        state = replicate_state(init_state(config, jax.random.key(cfg.seed)),
                                mesh)
        update = make_sharded_update(config, mesh, donate=True,
                                     use_is_weights=cfg.prioritized_replay)
    else:
        state = init_state(config, jax.random.key(cfg.seed))
        update = make_update(config, donate=True,
                             use_is_weights=cfg.prioritized_replay)

    # --- replay + schedule ------------------------------------------------
    storage = cfg.replay_storage
    if storage == "auto":
        # Device-resident ring when an accelerator is attached:
        # per-dispatch H2D drops from O(batch bytes) to O(indices) —
        # single device (replay/device_ring.py) or sharded over the mesh's
        # data axis (replay/sharded_per.py). Multi-host keeps rows on the
        # host (per-host replay shards); fall back when the ring wouldn't
        # fit comfortably in HBM.
        obs_elems = int(np.prod(obs_dim)) if not np.isscalar(obs_dim) else obs_dim
        ring_bytes = cfg.memory_size * (
            2 * obs_elems * np.dtype(obs_dtype).itemsize + (act_dim + 3) * 4)
        # the ring shards over the mesh's data axis, so the HBM budget is
        # per-shard, not whole-ring
        n_ring_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1
        storage = (
            "device"
            if jax.default_backend() != "cpu"
            and ring_bytes / n_ring_shards < 8e9
            # a sharded (mesh) learner — and ANY multi-host learner — can
            # only use device storage through the fused path; 'auto' must
            # resolve to host, not raise, when that path is disabled
            and (cfg.fused_replay != "off"
                 or (cfg.data_parallel == 1 and not multi_host))
            else "host"
        )
    elif storage == "device" and multi_host and cfg.fused_replay == "off":
        raise ValueError(
            "--replay_storage device on the multi-host runtime requires "
            "the fused replay path (--fused_replay auto/on); with it "
            "disabled, per-host replay shards stay in host RAM — use "
            "'host' or 'auto'")
    # Fully-fused replay+learn path (learner/fused.py): the PER trees join
    # the ring in HBM and the whole per-step replay protocol runs inside
    # the scanned dispatch — zero per-chunk host round trips, zero priority
    # staleness (at K=1 this IS the reference's exact per-step write-back,
    # ddpg.py:252-255, executed on device). With a mesh the ring and trees
    # shard over the data axis (each device samples its own B/N rows);
    # multi-host, each host owns its local devices' shards and drains its
    # own actors' rows into them (replay/sharded_per.py).
    fused = cfg.fused_replay != "off" and storage == "device"
    if cfg.fused_replay == "on" and not fused:
        raise ValueError(
            "--fused_replay on requires device replay storage "
            f"(storage resolved to {storage!r})")
    if storage == "device" and not fused:
        # the non-fused device ring lives on ONE device; a sharded learner
        # would re-pay the cross-device copy every dispatch
        if mesh is not None:
            raise ValueError(
                "--replay_storage device with --data_parallel > 1 requires "
                "the fused path (--fused_replay auto/on)")
    # Sample-path arm for --sample_on_ingest (ops/autotune.select_sampler,
    # the third arbitration surface): resolved BEFORE buffer construction
    # because the device arms ('scan'/'pallas') change what the service
    # owns — a gen-tracked fused device ring whose commit thread runs the
    # stratified descent fused behind the commit dispatch, dealing
    # device-resident blocks. 'host' keeps the PR-12 host SampleDealer
    # against host replay storage (the fallback arm).
    dealt_arm = None
    if cfg.sample_on_ingest and cfg.prioritized_replay:
        from d4pg_tpu.ops.autotune import select_sampler

        dealt_arm = select_sampler(
            cfg.sampler, capacity=cfg.memory_size,
            k=max(1, cfg.updates_per_dispatch),
            batch_size=cfg.batch_size).selected
        if dealt_arm in ("scan", "pallas"):
            if mesh is not None or multi_host:
                raise ValueError(
                    "--sampler scan/pallas (device-dealt) makes the commit "
                    "thread the single owner of every device handle — "
                    "mesh/multi-host learners need --sampler host")
            if cfg.ingest_shards != 1:
                raise ValueError(
                    "--sampler scan/pallas needs --ingest_shards 1: the "
                    "gen-tracked ring pre-assigns slots under ONE commit "
                    "thread (shard it with --sampler host instead)")
            if cfg.fused_replay == "on":
                raise ValueError(
                    "--fused_replay on (the FusedLoop learner) conflicts "
                    "with --sample_on_ingest: the device-dealt arm owns "
                    "the commit dispatch itself — drop --fused_replay on")
            # The learner-side fused path is OFF (replicas consume dealt
            # blocks); the service's buffer is still a fused device ring,
            # built gen-tracked below.
            fused = False
    if fused and mesh is not None:
        from d4pg_tpu.replay.sharded_per import ShardedFusedReplay

        n_data = int(mesh.shape[DATA_AXIS])
        if cfg.batch_size % n_data:
            # fail at startup, not after a whole warmup of rollouts
            raise ValueError(
                f"--bsize {cfg.batch_size} must divide by the mesh's data "
                f"axis ({n_data}) for the sharded fused replay path")
        buffer = ShardedFusedReplay(cfg.memory_size, obs_dim, act_dim, mesh,
                                    alpha=cfg.per_alpha,
                                    prioritized=cfg.prioritized_replay,
                                    obs_dtype=obs_dtype)
    elif fused:
        from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

        # ingest_shards must match the service's K: the shard workers
        # direct-stage into per-shard rings, so a lone ring would get K
        # pushers with interleaved tickets (merge assumes per-ring
        # ticket-ascending) — ReplayService.__init__ asserts agreement
        buffer = FusedDeviceReplay(cfg.memory_size, obs_dim, act_dim,
                                   alpha=cfg.per_alpha,
                                   prioritized=cfg.prioritized_replay,
                                   obs_dtype=obs_dtype,
                                   ingest_shards=cfg.ingest_shards)
    elif dealt_arm in ("scan", "pallas"):
        from d4pg_tpu.replay.fused_buffer import FusedDeviceReplay

        # the device-dealt service buffer: slots pre-assigned on the
        # host, priorities/generations committed by the ONE jitted
        # dispatch, sampled on device by the attached DeviceSampleDealer
        buffer = FusedDeviceReplay(cfg.memory_size, obs_dim, act_dim,
                                   alpha=cfg.per_alpha, prioritized=True,
                                   obs_dtype=obs_dtype, ingest_shards=1,
                                   gen_tracked=True)
    elif cfg.prioritized_replay:
        buffer = PrioritizedReplayBuffer(cfg.memory_size, obs_dim, act_dim,
                                         alpha=cfg.per_alpha, seed=cfg.seed,
                                         obs_dtype=obs_dtype, storage=storage)
    else:
        buffer = ReplayBuffer(cfg.memory_size, obs_dim, act_dim, seed=cfg.seed,
                              obs_dtype=obs_dtype, storage=storage)
    if cfg.debug:
        print(f"replay storage: {storage} (fused={fused})", flush=True)
    beta = LinearSchedule(cfg.per_beta_steps, 1.0, cfg.per_beta0)
    # Observation normalization lives with the replay service (single
    # writer: its drain thread folds every ingested row into the stats and
    # inserts normalized); actors/eval hold read-only views, remote actors
    # get (mean, std) over the weight channel.
    obs_norm = None
    if cfg.normalize_obs:
        if config.pixels:
            raise ValueError("--normalize_obs is for vector observations; "
                             "the pixel encoder already normalizes by /255")
        if multi_host:
            # per-host stats would normalize each host's replay rows
            # differently under globally-shared params; the synced variant
            # allgather-merges per-cycle deltas so every host standardizes
            # with identical statistics (HER paper's MPI-averaged stats)
            from d4pg_tpu.envs.normalizer import SyncedRunningMeanStd

            obs_norm = SyncedRunningMeanStd(config.obs_dim,
                                            clip=cfg.normalize_clip)
        else:
            from d4pg_tpu.envs.normalizer import RunningMeanStd

            obs_norm = RunningMeanStd(config.obs_dim, clip=cfg.normalize_clip)
    service = ReplayService(buffer, obs_norm=obs_norm,
                            num_ingest_shards=cfg.ingest_shards)

    # --- io (process 0 owns all of it in multi-host mode) ----------------
    bus = MetricsBus(echo=is_main)
    ckpt = None
    if is_main:
        try:
            bus.add_sink(TensorBoardSink(run_dir))
        except Exception as e:  # tensorboard optional at runtime
            print(f"tensorboard disabled: {e}")
        # first two data columns keep the reference's offline-plot shape
        # (plots/plots.py:29-37 reads step,avg,curr); success_rate rides as
        # a third column for the sparse-reward/HER evidence plots
        bus.add_sink(CsvLogger(
            os.path.join(run_dir, "returns.csv"),
            ["avg_test_reward", "ewma_test_reward", "success_rate"]))
        ckpt = CheckpointManager(
            os.path.join(run_dir, "ckpt"),
            active_processes={0} if multi_host else None)
    extra: dict = {"env_steps": 0}
    if cfg.resume and multi_host:
        # Restore on process 0, broadcast, re-replicate over the global
        # mesh; every host then loads ITS OWN replay shard snapshot
        # (process 0's rides the Orbax extra payload, hosts > 0 write
        # sidecar files — see the save site below).
        from jax.experimental import multihost_utils

        def _state_raw(s):
            # typed PRNG keys don't cross the allgather; carry raw key data
            d = s._asdict()
            d["key"] = jax.random.key_data(d["key"])
            return jax.tree_util.tree_map(np.asarray, d)

        host_state = jax.device_get(state)  # replicated -> host template
        if is_main and ckpt is not None and ckpt.latest_step is not None:
            restored, extra = ckpt.restore(host_state)
            raw, found = _state_raw(restored), 1
        else:
            raw, found = _state_raw(host_state), 0
        found = int(multihost_utils.broadcast_one_to_all(np.int32(found)))
        if found:
            raw = multihost_utils.broadcast_one_to_all(raw)

            def _rebuild():
                d = {k: jax.tree_util.tree_map(jnp.asarray, v)
                     for k, v in raw.items()}
                d["key"] = jax.random.wrap_key_data(jnp.asarray(raw["key"]))
                from d4pg_tpu.learner.state import D4PGState

                return D4PGState(**d)

            state = multihost.replicate_state_global(_rebuild, mesh)
            env_steps = int(multihost_utils.broadcast_one_to_all(
                np.int64(extra.get("env_steps", 0))))
            extra["env_steps"] = env_steps
            service.set_env_steps(env_steps)
            # normalize-flag agreement must be decided identically on ALL
            # hosts before any further collective: a process-0-only raise
            # would leave the other hosts hung in the next barrier
            has_norm = int(multihost_utils.broadcast_one_to_all(
                np.int32(1 if extra.get("obs_norm") else 0)))
            if has_norm and obs_norm is None:
                raise ValueError(
                    "checkpoint was trained with --normalize_obs (its "
                    "policy and replay rows live in normalized space); "
                    "resume with the flag")
            if obs_norm is not None:
                if not has_norm and env_steps > 0:
                    raise ValueError(
                        "--normalize_obs resume from a checkpoint without "
                        "obs_norm statistics: the restored policy/replay "
                        "are in raw space — resume without the flag, or "
                        "restart training")
                if has_norm:
                    # fixed-shape stats payload -> identical estimators
                    d = (extra.get("obs_norm")
                         or {"count": 0.0,
                             "mean": np.zeros(config.obs_dim),
                             "m2": np.zeros(config.obs_dim),
                             "clip": cfg.normalize_clip, "eps": 1e-2})
                    payload = np.concatenate(
                        [[d["count"]], d["mean"], d["m2"],
                         [d["clip"], d["eps"]]]).astype(np.float64)
                    payload = np.asarray(
                        multihost_utils.broadcast_one_to_all(payload))
                    n = config.obs_dim
                    extra["obs_norm"] = {
                        "count": float(payload[0]), "mean": payload[1:1 + n],
                        "m2": payload[1 + n:1 + 2 * n],
                        "clip": float(payload[-2]), "eps": float(payload[-1]),
                    }
            restored_step = int(np.asarray(raw["step"]))
            # every host restores from its sidecar; a legacy checkpoint
            # may still carry process 0's buffer in the Orbax extra
            # (saved atomically with the state, so its step IS the state's)
            snap, snap_step = (extra.pop("replay", None), restored_step) \
                if is_main and extra.get("replay") else (None, -1)
            if snap is None:
                snap, snap_step = _load_host_replay(
                    run_dir, jax.process_index(), restored_step)
            if fused:
                # The sharded fused restore is COLLECTIVE downstream (the
                # next drain allgathers), and the device buffer is ONE
                # logical store: every host's shard-set must come from
                # the SAME save moment. Agree on the snapshot step — a
                # host that crashed between its peers' sidecar renames
                # holds an older one, and loading mixed-step shard-sets
                # would silently mix replay timelines (rows, priorities,
                # size counters) within one buffer. On any mismatch or
                # missing snapshot, ALL hosts restart with empty replay.
                steps_all = multihost_utils.process_allgather(
                    np.int64(snap_step))
                agreed = (int(steps_all.min()) == int(steps_all.max())
                          and int(steps_all.min()) >= 0)
                if agreed:
                    _restore_replay(service, snap, env_steps)
                elif snap is not None:
                    print(f"[p{jax.process_index()}] replay sidecar steps "
                          f"disagree across hosts ({steps_all.tolist()}); "
                          "all hosts restart with empty replay", flush=True)
            elif snap is not None:
                _restore_replay(service, snap, env_steps)
            print(f"[p{jax.process_index()}] resumed from step "
                  f"{int(jax.device_get(state.step))} ({service.env_steps} "
                  f"env steps, {len(service)} replay rows)", flush=True)
    elif cfg.resume and ckpt is not None and ckpt.latest_step is not None:
        state, extra = ckpt.restore(state if mesh is None else jax.device_get(state))
        if mesh is not None:
            state = replicate_state(state, mesh)
        service.set_env_steps(extra.get("env_steps", 0))
        # elastic recovery: buffer contents + PER priorities (resumed
        # learners otherwise retrain from an empty buffer). Legacy
        # checkpoints carry the buffer in the Orbax extra; current runs
        # write the step-stamped sidecar (stale-tolerant — see
        # _load_host_replay).
        snap = extra.pop("replay", None)
        if snap is None:
            snap, _ = _load_host_replay(run_dir, 0, int(state.step))
        if snap:
            _restore_replay(service, snap, extra.get("env_steps", 0))
        print(f"resumed from step {int(state.step)} "
              f"({service.env_steps} env steps, "
              f"{len(service)} replay rows)")

    # --- actors + evaluator ----------------------------------------------
    if obs_norm is not None:
        if extra.get("obs_norm"):
            # resume with the statistics the stored replay rows (and the
            # restored policy) were normalized with
            obs_norm.load_state_dict(extra.pop("obs_norm"))
        elif cfg.resume and extra.get("env_steps"):
            raise ValueError(
                "--normalize_obs resume from a checkpoint without obs_norm "
                "statistics: the restored policy/replay are in raw space — "
                "resume without the flag, or restart training")
    elif extra.get("obs_norm"):
        raise ValueError(
            "checkpoint was trained with --normalize_obs (its policy and "
            "replay rows live in normalized space); resume with the flag")
    weights = WeightStore()

    def _norm_snapshot():
        # (mean, std, clip): clip travels with the stats so remote actors
        # standardize policy inputs bitwise-identically to the replay rows
        # even under a non-default --normalize_clip.
        return ((*obs_norm.stats(), obs_norm.clip)
                if obs_norm is not None else None)

    weights.publish(
        state.actor_params if mesh is None else jax.device_get(state.actor_params),
        step=int(jax.device_get(state.step)),
        norm_stats=_norm_snapshot(),
    )
    actor_cfg = ActorConfig(
        epsilon_0=cfg.epsilon_0, min_epsilon=cfg.min_epsilon,
        epsilon_horizon=cfg.epsilon_horizon, n_step=cfg.n_steps,
        gamma=cfg.gamma, reward_scale=cfg.reward_scale,
        noise=cfg.noise, random_eps=cfg.random_eps, ou_theta=cfg.ou_theta,
        ou_sigma=cfg.ou_sigma, ou_mu=cfg.ou_mu, device=cfg.actor_device,
    )
    # Actor/env seeds get a per-PROCESS offset: the learner's init seed must
    # be identical on every host (replicated params), but each host's actors
    # must explore decorrelated — without this, all hosts collect the same
    # trajectories and the multi-host fleet adds no data diversity.
    aseed = cfg.seed + 100_003 * jax.process_index()
    actors = []
    for w in range(cfg.n_workers):
        if cfg.her:
            actor = GoalActorWorker(
                f"actor-{w}", config, actor_cfg,
                make_env_fn(cfg, seed=aseed + w)(), service, weights,
                her_ratio=cfg.her_ratio, rng_seed=aseed + w, seed=aseed + w,
                obs_norm=obs_norm,
            )
        else:
            pool = EnvPool(
                [make_env_fn(cfg, seed=aseed + w * cfg.num_envs + i)
                 for i in range(cfg.num_envs)],
                seed=aseed + w,
            )
            actor = ActorWorker(f"actor-{w}", config, actor_cfg, pool, service,
                                weights, seed=aseed + w, obs_dtype=obs_dtype,
                                obs_norm=obs_norm)
        actors.append(actor)
    # Process 0 owns eval (multi-host: other hosts' rollouts would only be
    # discarded — their metrics bus has no sinks).
    evaluator = (
        Evaluator(config, make_env_fn(cfg, seed=cfg.seed + 777), weights,
                  max_steps=cfg.max_steps, goal_conditioned=cfg.her,
                  device=cfg.actor_device, obs_norm=obs_norm)
        if is_main else None
    )
    # Concurrent eval (main.py:395-397: the reference's evaluator is a
    # separate process): greedy rollouts run on a background thread against
    # published weights; the learner never blocks on them.
    async_eval = (AsyncEvaluator(evaluator)
                  if cfg.concurrent_eval and evaluator is not None else None)

    # --- warmup (main.py:200-207); skipped when a restored replay
    # checkpoint already covers it -----------------------------------------
    if len(service) < cfg.warmup:
        warmup_ticks = max(1, cfg.warmup // max(1, cfg.num_envs))
        for actor in actors:
            if cfg.her:
                while actor.env_steps < cfg.warmup // cfg.n_workers:
                    actor.run_episode(cfg.max_steps)
            else:
                actor.run(warmup_ticks // cfg.n_workers)
        service.flush()
    print(f"warmup done: {len(service)} transitions")

    # --- optional network serving for remote actors (actor_main.py) ------
    receiver = weight_server = None
    actor_processes: list = []
    # per-slot respawn bookkeeping (supervisor below): generation varies
    # the child's seed; consecutive failures cap the crash-loop
    actor_proc_gen: list[int] = [0] * max(0, cfg.actor_procs)
    actor_proc_fails: list[int] = [0] * max(0, cfg.actor_procs)
    if cfg.serve or cfg.actor_procs > 0:
        from d4pg_tpu.distributed.transport import TransitionReceiver
        from d4pg_tpu.distributed.weight_plane import WeightPlaneServer

        # K>1: shard-aware receiver — frames forwarded undecoded to the
        # owning ingest shard's worker (raw frames admit on header
        # metadata; npz frames decode at admission, as before). Note the
        # normalizer still folds on the single commit thread in ticket
        # order, so sharding never changes the statistics stream.
        receiver = TransitionReceiver(
            lambda b, aid, count: service.add(b, actor_id=aid,
                                              count_env_steps=count),
            host=cfg.serve_host,
            port=cfg.serve_transitions_port,
            secret=cfg.serve_secret or None,
            num_shards=cfg.ingest_shards,
            on_payload=(service.add_payload if cfg.ingest_shards > 1
                        else None),
            # crash-recovery plane: greet every connecting sender with the
            # live service generation; after a restart-and-restore, frames
            # encoded against the pre-crash service fence at admission
            generation=(lambda: service.generation),
        )
        # Weight plane (docs/architecture.md "Weight plane"): answers
        # BOTH wire protocols on one port — v1 full-snapshot pullers
        # (actor_main.py default) and v2 delta/quantized/fenced pullers
        # (--weight_codec) — with the serialized-frame memo shared.
        weight_server = WeightPlaneServer(weights, host=cfg.serve_host,
                                          port=cfg.serve_weights_port,
                                          secret=cfg.serve_secret or None,
                                          window=cfg.weight_window)
        print(f"serving: transitions :{receiver.port} weights :{weight_server.port}",
              flush=True)
    policy_server = None
    if cfg.serve_policy:
        # Serving plane (docs/architecture.md "Serving plane"): remote
        # actors launched with --policy_port stream obs batches here and
        # get greedy mu back from ONE fused dispatch per batching
        # window; the refresher adopts (generation, version) snapshots
        # from the same store the weight plane broadcasts, under the
        # declared staleness SLA.
        from d4pg_tpu.serving import PolicyInferenceServer

        policy_server = PolicyInferenceServer(
            config, weights, host=cfg.serve_host,
            port=cfg.serve_policy_port,
            secret=cfg.serve_secret or None,
            batch_window_s=cfg.serve_policy_window_s,
            max_batch_rows=cfg.serve_policy_max_rows,
            sla_staleness_s=cfg.serve_policy_sla_s)
        print(f"serving: policy :{policy_server.port}", flush=True)
    if cfg.actor_procs > 0:
        # Real process-level local parallelism (the reference's mp.Process
        # fan-out, main.py:399-405, done over the TCP plane): each process
        # steps its own env pool on the CPU backend and streams in
        # continuously, out of the learner's GIL entirely.
        import multiprocessing as mp

        from d4pg_tpu.actor_main import run_local_actor_process

        ctx = mp.get_context("spawn")
        connect_host = (
            "127.0.0.1" if cfg.serve_host in ("0.0.0.0", "127.0.0.1")
            else cfg.serve_host
        )
        def spawn_actor_proc(i: int, gen: int = 0):
            # stateless by design (replay + weights live with the learner),
            # so the supervisor can respawn with the same config/identity.
            # The seed varies per respawn GENERATION: a respawned child
            # reusing its seed would re-stream duplicate early
            # trajectories into replay (ADVICE r3).
            proc_cfg = dataclasses.replace(
                cfg, seed=aseed + 1000 * (i + 1) + 101 * gen, actor_procs=0,
                serve=False)
            p = ctx.Process(
                target=run_local_actor_process,
                args=(proc_cfg, connect_host, receiver.port,
                      weight_server.port, f"proc-{i}",
                      cfg.serve_secret or None,
                      # both sides are ours: read the generation greeting so
                      # a learner restart fences this child's stale frames
                      True),
                daemon=True,
            )
            p.start()
            return p

        for i in range(cfg.actor_procs):
            actor_processes.append(spawn_actor_proc(i))
        print(f"spawned {len(actor_processes)} actor processes", flush=True)
        if cfg.n_workers == 0:
            # no in-process actors: wait for the fleet to fill the warmup
            if not service.wait_until(cfg.warmup, timeout=300.0):
                raise RuntimeError("actor processes did not reach warmup")

    # --- the HER-paper loop (main.py:299-368), or the decoupled async
    # actor-learner architecture of the D4PG paper (--async_actors 1) ------
    # ``lstep`` mirrors the device step counter on the host (exact: we know
    # how many updates each dispatch performs), so beta/metrics never force
    # a device sync mid-pipeline.
    lstep = int(jax.device_get(state.step))

    # filled by the multi-learner block below (--learners N > 1); empty
    # means the legacy single-learner paths own the weight stream
    replicas: list = []
    mesh_group = None  # mesh-native replica group (collective transport)

    def publish():
        if replicas or mesh_group is not None:
            return  # the merge owns the version stream (one writer)
        p = state.actor_params if mesh is None else jax.device_get(state.actor_params)
        weights.publish(p, step=lstep, norm_stats=_norm_snapshot())

    if obs_norm is not None:
        if multi_host:
            # fold every host's warmup rows into the shared statistics
            # before anything trains or republishes (collective)
            obs_norm.sync()
        # warmup just populated the statistics; remote/spawned actors built
        # their FrozenNormalizer from the count-0 pre-warmup publish and
        # won't see a newer weight version until training publishes —
        # re-publish now so the fleet acts on real stats from step one
        publish()

    # Fused K-updates-per-dispatch path. With a mesh this composes with
    # data parallelism: batches are stacked [K, B, ...] with K replicated
    # (the scan axis) and B sharded over ``data``.
    K = max(1, cfg.updates_per_dispatch)
    if K > 1 and not fused:
        if mesh is not None:
            multi_update = make_sharded_multi_update(
                config, mesh, donate=True,
                use_is_weights=cfg.prioritized_replay)
        else:
            multi_update = make_multi_update(
                config, donate=True, use_is_weights=cfg.prioritized_replay)
    else:
        multi_update = None
    chunk_sharding = stacked_sharding(mesh) if mesh is not None else None

    # Fully-fused chunks (learner/fused.py): sample + gather + update +
    # priority write-back inside ONE scanned dispatch against the
    # device-resident ring and trees. The commit -> dispatch -> stage
    # schedule lives in learner/loop.FusedLoop — the SAME class a
    # LearnerReplica drives, so N=1-through-the-aggregator being bitwise
    # the legacy loop is a property of the code structure, not a test
    # that happened to pass once.
    fused_loop = (
        FusedLoop(
            config, buffer, k=K, batch_size=cfg.batch_size,
            prioritized=cfg.prioritized_replay, alpha=cfg.per_alpha,
            beta0=cfg.per_beta0, beta_steps=cfg.per_beta_steps,
            mesh=mesh, service=service, donate=True)
        if fused else None)

    # whole-tree on-device param copy in ONE dispatch (async publish below)
    copy_params = jax.jit(
        lambda p: jax.tree_util.tree_map(jnp.copy, p))

    # Wire-to-grad tracing (docs/architecture.md "Observability plane"):
    # arm the receiver-side span recorder; frames sampled by raw-codec
    # remote actors get their grad-consumption span stamped right after
    # each fused dispatch (FusedLoop.run calls mark_grad — the host-side
    # proxy for "a grad step consumed these rows"; the device runs async
    # and observing the kernel would cost the sync the plane exists to
    # avoid).
    from d4pg_tpu.obs.trace import RECORDER as trace_recorder

    if cfg.trace_sample > 0:
        trace_recorder.enable(cfg.trace_sample)

    def _publish_async(chunk_state, step):
        """Bounded staleness <= K without stalling the dispatch
        pipeline: an on-device param copy (async dispatch; the next
        chunk's donation would otherwise invalidate the buffers readers
        hold) instead of a blocking D2H pull. Multi-host actors act on
        host arrays (a replicated global array would pin the actor's
        jit to the global mesh), so there the pull is D2H."""
        if multi_host:
            weights.publish(jax.device_get(chunk_state.actor_params),
                            step=step, norm_stats=_norm_snapshot())
        else:
            weights.publish(copy_params(chunk_state.actor_params),
                            step=step, to_host=False,
                            norm_stats=_norm_snapshot())

    def train_steps_fused(n: int):
        """n fused updates through the extracted loop. The only host
        work per chunk is moving staged actor rows onto the device,
        overlapped by FusedLoop's commit/dispatch/stage schedule (≤ 1
        explicit H2D per chunk), so the learner never stalls on the
        tunnel. The cycle boundary still flushes everything: training
        each cycle sees all rows the collect phase produced."""
        nonlocal state, lstep

        def on_chunk(chunk_state, k):
            nonlocal lstep
            lstep += k
            if cfg.async_actors:
                _publish_async(chunk_state, lstep)

        state, metrics = fused_loop.run(state, n, on_chunk=on_chunk)
        if metrics is None:
            return None
        return {name: metrics[name][-1]
                for name in ("critic_loss", "actor_loss", "q_mean")}

    # Multi-host PER: all shards must normalize IS weights by the same
    # global max weight — refreshed once per train_steps call (a tiny
    # allgather; p_min drifts slowly within a cycle). None = local
    # normalizer (single-host, exact reference semantics).
    weight_base_cell: dict = {"z": None}

    def _refresh_weight_base():
        if multi_host and cfg.prioritized_replay:
            weight_base_cell["z"] = multihost.global_min_scalar(
                service.weight_base())

    def _sample_chunk():
        """One K-chunk: host tree walks pick [K, B] indices, ONE storage
        gather fetches the rows (device storage: rows stay in HBM)."""
        if cfg.prioritized_replay:
            batches, w, idx, gen = service.sample_chunk(
                K, cfg.batch_size, beta=beta.value(lstep),
                weight_base=weight_base_cell["z"])
            return (batches, w), (idx, gen)
        batches, _, _, _ = service.sample_chunk(K, cfg.batch_size)
        return (batches, None), None

    # Double-buffered host->device staging (SURVEY.md §7 "hard parts"):
    # while the device runs chunk t's scanned update, the host samples and
    # device_puts chunk t+1; PER priority staleness is bounded by (depth+1)K steps.
    # The pipeline itself lives in learner/pipeline.py, shared with bench.py
    # so the benchmarked loop IS the shipped loop.
    def _per_write_back(aux, td):
        idx, gen = aux
        for i in range(len(idx)):
            service.update_priorities(idx[i], td[i], generation=gen[i])

    pipeline = (
        ChunkPipeline(
            multi_update, _sample_chunk,
            write_back=_per_write_back if cfg.prioritized_replay else None,
            sharding=chunk_sharding,
            use_weights=cfg.prioritized_replay,
            # multi-host: stage chunks by assembling the global [K, B, ...]
            # array from each process's local sample, and pull back only
            # this host's td_error rows for its PER write-back
            put_fn=((lambda payload: multihost.make_global_chunk(payload, mesh))
                    if multi_host else None),
            fetch_td=((lambda m: multihost.local_rows(m["td_error"], axis=1))
                      if multi_host else None),
        )
        if K > 1 and not fused else None
    )

    def _on_chunk(chunk_state):
        """Per-dispatch step accounting + weight publishing. Publishes from
        the CHUNK's output state (the `state` closure variable is rebound
        only after pipeline.run returns — reading it here would ship params
        from before the whole run)."""
        nonlocal lstep
        lstep += K
        if cfg.async_actors:
            p = (chunk_state.actor_params if mesh is None
                 else jax.device_get(chunk_state.actor_params))
            weights.publish(p, step=lstep,  # bounded staleness: lag <= K
                            norm_stats=_norm_snapshot())

    def _stage_single(batch):
        """Place a host-local [B, ...] batch for the update: multi-host
        assembles the global array from every process's local rows (a
        host-local device_put cannot address other hosts' devices); a
        single-host mesh device_puts with the data sharding."""
        if multi_host:
            return multihost.make_global_batch(batch, mesh)
        if mesh is not None:
            return shard_batch(batch, mesh)
        return batch

    def train_single():
        nonlocal state, lstep
        if cfg.prioritized_replay:
            batch, w, idx, gen = service.sample(
                cfg.batch_size, beta=beta.value(lstep),
                weight_base=weight_base_cell["z"])
            batch = _stage_single(batch)
            w = _stage_single(np.asarray(w, np.float32))
            state, metrics = update(state, batch, w)
            lstep += 1
            # each host writes back only ITS rows of the (possibly
            # globally-sharded) td_error — they are the ones its local
            # buffer sampled
            td = (multihost.local_rows(metrics["td_error"], axis=0)
                  if multi_host else np.asarray(metrics["td_error"]))
            service.update_priorities(idx, np.abs(td) + 1e-6, generation=gen)
        else:
            batch = _stage_single(service.sample(cfg.batch_size))
            state, metrics = update(state, batch)
            lstep += 1
        return metrics

    def train_steps(n: int):
        """n updates: pipelined K-chunks, then single-dispatch remainder."""
        nonlocal state
        if mesh_group is not None:
            return train_steps_mesh(n)
        if replicas:
            return train_steps_multi(n)
        if fused:
            return train_steps_fused(n)
        _refresh_weight_base()
        metrics = None
        n_chunks, remainder = (n // K, n % K) if K > 1 else (0, n)
        if n_chunks:
            if not cfg.async_actors:
                # Sync mode just collected fresh episodes; drop a chunk
                # sampled before them so every cycle trains on the newest
                # distribution.
                pipeline.invalidate()
            state, metrics = pipeline.run(
                state, n_chunks, on_chunk=_on_chunk,
                final_prefetch=cfg.async_actors,
            )
        for _ in range(remainder):
            metrics = train_single()
        if metrics is None:
            return None
        # last step's scalars for logging (chunk metrics are stacked [K])
        return {
            name: (v if v.ndim == 0 else v[-1])
            for name, v in metrics.items()
            if name in ("critic_loss", "actor_loss", "q_mean")
        }

    # --- multi-learner plane (--learners N > 1) ----------------------------
    # N LearnerReplica threads, each owning a full D4PGState (its own
    # optimizer state + PRNG key), sample the shared ReplayService
    # concurrently; the Aggregator merges their version-stamped updates
    # into the ONE WeightStore stream with IMPACT-style staleness
    # weighting, so actors/relays keep seeing a single monotone
    # (generation, version) sequence (learner/aggregator.py).
    aggregator = None
    replica_failures: dict[int, int] = {}
    pacing_dealer = None  # the sample-on-ingest dealer, if one stands up
    if cfg.learners > 1 or cfg.sample_on_ingest:
        if fused:
            # Unreachable for the device-dealt arm (it forces fused=False
            # above); this guards the FusedLoop learner path proper.
            raise ValueError(
                "--learners > 1 / --sample_on_ingest need the host-sampled "
                "replay path (the FusedLoop learner is single-consumer by "
                "construction — pass --fused_replay off; device-resident "
                "sampling under --sample_on_ingest is --sampler "
                "scan/pallas, which owns its fused ring via the dealer)")
        # Merge transport (--agg_transport): 'collective' runs the
        # replicas mesh-native (learner/mesh_replicas.py — full states
        # stacked along the 'replica' mesh axis by partition rule, the
        # merge an on-device collective); 'socket' is the PR-10
        # host-thread plane over 0xD4AB frames and stays the cross-host
        # fallback. 'auto' picks collective exactly when the replicas
        # can share one single-host mesh.
        transport = cfg.agg_transport
        if transport == "auto":
            transport = ("collective"
                         if (mesh is not None and not multi_host
                             and cfg.learners > 1
                             and not cfg.sample_on_ingest)
                         else "socket")
        if transport == "collective":
            if mesh is None or multi_host:
                raise ValueError(
                    "--agg_transport collective needs the replicas on one "
                    "single-host device mesh (--data_parallel/"
                    "--model_parallel); across hosts the socket update "
                    "plane is the fallback")
            if cfg.sample_on_ingest:
                raise ValueError(
                    "--sample_on_ingest deals blocks to host-thread "
                    "replicas — pair it with --agg_transport socket")
            if cfg.learners < 2:
                raise ValueError(
                    "--agg_transport collective needs --learners > 1 "
                    "(with one learner the plain mesh path already "
                    "covers the device layout)")
        elif multi_host or mesh is not None:
            raise ValueError(
                "--agg_transport socket composes with single-host "
                "unmeshed learners only; replicas sharing a device mesh "
                "take --agg_transport collective (the mesh-native merge)")
        if cfg.sample_on_ingest and not cfg.prioritized_replay:
            raise ValueError(
                "--sample_on_ingest is the PER dealer — it needs "
                "--p_replay (dealt blocks carry IS weights)")
        from d4pg_tpu.replay.schedule import SharedBetaSchedule

        n_learners = max(1, cfg.learners)
        # one anneal clock for every sampler in the process: N replicas
        # at the same global step use the same beta (and the dealer
        # stamps it onto the blocks it deals)
        beta_sched = SharedBetaSchedule(beta0=cfg.per_beta0,
                                        beta_steps=cfg.per_beta_steps)
        if transport == "collective":
            from d4pg_tpu.learner.mesh_replicas import MeshReplicaGroup

            rstates = []
            for i in range(n_learners):
                # same replica construction as the socket path below:
                # identical nets, decorrelated keys, per-replica leaf
                # copies (the stacking device_put consumes its inputs)
                rstate = jax.tree_util.tree_map(jnp.copy, state)
                if i:
                    rstate = rstate._replace(
                        key=jax.random.fold_in(rstate.key, i))
                rstates.append(rstate)
            mesh_group = MeshReplicaGroup(
                config, rstates, k=K, batch_size=cfg.batch_size,
                mode=cfg.agg_mode, clip=cfg.agg_clip, store=weights,
                # actors pull acting params only, as with the aggregator
                extract=lambda tree: tree["actor_params"],
                norm_stats=_norm_snapshot,
                prioritized=cfg.prioritized_replay, alpha=cfg.per_alpha,
                beta0=cfg.per_beta0, beta_steps=cfg.per_beta_steps)
            print(f"learner plane: {n_learners} mesh-native replicas "
                  f"(collective merge), mode={cfg.agg_mode} "
                  f"clip={cfg.agg_clip}", flush=True)
        else:
            from d4pg_tpu.learner.aggregator import Aggregator
            from d4pg_tpu.learner.replica import LearnerReplica

            dealt_rings: list = []
            if cfg.sample_on_ingest:
                if dealt_arm in ("scan", "pallas"):
                    # device-dealt plane: the dealer runs the stratified
                    # descent on device fused behind the commit dispatch
                    # and deals device-resident blocks; rings delete
                    # dropped device blocks eagerly on clear (kill burst)
                    from d4pg_tpu.replay.device_sampler import (
                        DeviceSampleDealer)
                    from d4pg_tpu.replay.staging import DeviceDealtBlockRing

                    dealt_rings = [DeviceDealtBlockRing(4)
                                   for _ in range(n_learners)]
                    dealer = DeviceSampleDealer(
                        cfg.memory_size, dealt_rings, k=K,
                        batch_size=cfg.batch_size, alpha=cfg.per_alpha,
                        beta_schedule=beta_sched,
                        min_size=max(1, cfg.batch_size), seed=cfg.seed,
                        arm=dealt_arm)
                else:
                    from d4pg_tpu.replay.sampler import SampleDealer
                    from d4pg_tpu.replay.staging import DealtBlockRing

                    dealt_rings = [DealtBlockRing(4)
                                   for _ in range(n_learners)]
                    dealer = SampleDealer(
                        cfg.memory_size, dealt_rings,
                        n_shards=cfg.ingest_shards, k=K,
                        batch_size=cfg.batch_size, alpha=cfg.per_alpha,
                        beta_schedule=beta_sched,
                        min_size=max(1, cfg.batch_size), seed=cfg.seed)
                service.attach_dealer(dealer)
                pacing_dealer = dealer
            aggregator = Aggregator(
                weights, mode=cfg.agg_mode, clip=cfg.agg_clip,
                # actors pull acting params only; the full 4-subtree merge
                # tree stays between replicas and aggregator
                extract=lambda tree: tree["actor_params"],
                norm_stats=_norm_snapshot)
            for i in range(n_learners):
                # identical network init across replicas, decorrelated
                # sampling/noise keys (replica 0 keeps the original chain).
                # Every replica gets its OWN buffer copy: updates donate
                # their input state, and donated leaves shared between
                # replicas would be deleted under each other
                rstate = jax.tree_util.tree_map(jnp.copy, state)
                if i:
                    rstate = rstate._replace(
                        key=jax.random.fold_in(rstate.key, i))
                replicas.append(LearnerReplica(
                    i, config, aggregator, rstate, k=K,
                    batch_size=cfg.batch_size,
                    prioritized=cfg.prioritized_replay, alpha=cfg.per_alpha,
                    beta0=cfg.per_beta0, beta_steps=cfg.per_beta_steps,
                    service=service,
                    dealt_ring=dealt_rings[i] if dealt_rings else None,
                    beta_schedule=beta_sched))
            print(f"learner plane: {n_learners} replicas, "
                  f"mode={cfg.agg_mode} clip={cfg.agg_clip} "
                  f"sample_on_ingest={cfg.sample_on_ingest}"
                  + (f" sampler={dealt_arm}" if dealt_arm else ""),
                  flush=True)

    # --- Elastic traffic plane (docs/architecture.md "Elastic traffic
    # plane", --autoscale): the obs-driven control loop over whatever
    # capacity knobs this run stood up. Sensing is the obs-registry
    # export the planes already publish; actuation is each owner's
    # bounded live setter (top-level lock acquires only), so the loop
    # adds zero lock edges. Knobs without a wired actuator are still
    # decided and ledgered — the journal shows what the controller
    # WOULD have done on a fuller fleet.
    autoscaler = None
    # active-prefix replica scheduling: train_steps_multi fans each
    # cycle across replicas[:target] only. ``parked`` remembers which
    # replicas sat out a cycle so reactivation goes through respawn()
    # — the idle epoch is fenced and any in-flight submission from
    # before the scale-down bounces at the aggregator instead of
    # landing as a stale surprise.
    replica_target = {"n": max(1, len(replicas)), "parked": set()}
    if cfg.autoscale:
        from d4pg_tpu.elastic.autoscaler import Autoscaler, AutoscalerConfig

        elastic_actuators: dict = {
            "ingest_capacity": service.set_ingest_depth,
        }
        if policy_server is not None:
            elastic_actuators["serving_rows"] = (
                lambda v: policy_server.set_batch_limits(max_rows=v))
            elastic_actuators["serving_window_s"] = (
                lambda v: policy_server.set_batch_limits(window_s=v))
        if pacing_dealer is not None:
            elastic_actuators["dealer_deals"] = pacing_dealer.set_pacing
        if replicas:
            def _set_replica_target(n: int) -> None:
                # autoscaler-thread side records the bounded target
                # only; the train loop adopts it at the next cycle
                # boundary (activation touches the aggregator's epoch
                # table, which belongs to the round-owning thread)
                replica_target["n"] = max(1, min(len(replicas), int(n)))

            elastic_actuators["replicas"] = _set_replica_target
        autoscaler = Autoscaler(
            AutoscalerConfig(
                interval_s=cfg.autoscale_interval_s,
                # anchor the controller's set points at this run's
                # startup knobs so tick 0 is a no-op on a calm fleet
                serving_rows_init=cfg.serve_policy_max_rows,
                serving_rows_min=max(16, cfg.serve_policy_max_rows // 4),
                serving_rows_max=4 * cfg.serve_policy_max_rows,
                serving_window_cold_s=cfg.serve_policy_window_s,
                ingest_capacity_init=256,
                ingest_capacity_min=64,
                ingest_capacity_max=1024,
                replicas_init=max(1, len(replicas)),
                replicas_min=1,
                replicas_max=max(1, len(replicas)),
            ),
            actuators=elastic_actuators).start()
        print(f"elastic: autoscaler up, knobs="
              f"{sorted(elastic_actuators)}", flush=True)

    def train_steps_multi(n: int):
        """Fan the cycle's n grad steps across the replicas: each runs
        ONE basis-adopt -> ceil(n/N) steps -> version-stamped submit
        round on its own thread. Supervision mirrors the actor story: a
        crashed replica is fenced (so its in-flight update bounces at
        the aggregator) and respawned at the next epoch, with the same
        consecutive-failure cap."""
        nonlocal state, lstep
        # adopt the elastic replica target at this cycle boundary:
        # replicas past the prefix sit the cycle out (parked); a parked
        # replica coming back respawns first, fencing its idle epoch
        active = replicas[:replica_target["n"]]
        for r in replicas[len(active):]:
            replica_target["parked"].add(r.replica_id)
        for r in active:
            if r.replica_id in replica_target["parked"]:
                replica_target["parked"].discard(r.replica_id)
                r.respawn()
        per = -(-n // len(active))
        failed: dict[int, str] = {}

        def run_replica(r):
            try:
                r.run_round(per)
            except Exception as e:  # noqa: BLE001 — supervisor owns the verdict
                failed[r.replica_id] = traceback.format_exc()
                contained_crash(f"learner.replica{r.replica_id}", e)

        threads = [
            threading.Thread(target=run_replica, args=(r,), daemon=True)
            for r in active]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in active:
            if r.replica_id in failed:
                fails = replica_failures.get(r.replica_id, 0) + 1
                replica_failures[r.replica_id] = fails
                print(f"learner replica {r.replica_id} crashed "
                      f"({fails} consecutive):\n{failed[r.replica_id]}",
                      flush=True)
                if fails >= 5:
                    raise RuntimeError(
                        f"learner replica {r.replica_id} failed {fails} "
                        "cycles in a row; giving up")
                r.respawn()
            else:
                replica_failures[r.replica_id] = 0
        # replica 0's state stands in for `state` downstream (checkpoint,
        # eval lag accounting); the PUBLISHED params are the aggregate
        state = replicas[0].state
        lstep = max([lstep] + [r.steps_done for r in replicas])
        metrics = replicas[0].last_metrics
        if metrics is None:
            return None
        return {name: metrics[name][-1]
                for name in ("critic_loss", "actor_loss", "q_mean")}

    def train_steps_mesh(n: int):
        """The cycle's grad steps on the mesh-native replica group:
        every replica trains ceil(n/N) service-sampled steps against its
        own shard of the replica-stacked state — one [N, K, B, ...]
        dispatch per chunk — then the round closes with the on-device
        collective merge + publish. The socket path's per-round
        device→host pull, 0xD4AB frame and host→device push never
        happen; semantics stay round-synchronous (replica i's
        submission at lag i in async mode)."""
        nonlocal state, lstep
        per = -(-n // mesh_group.n)
        # one beta per round, shared by every replica's sampler — the
        # same anneal clock the thread replicas read
        beta_now = beta_sched.beta_at(beta_sched.current_step())
        metrics = None
        done = 0
        while done < per:
            k = min(K, per - done)
            if cfg.prioritized_replay:
                chunks = [service.sample_chunk(
                    k, cfg.batch_size, beta=beta_now,
                    weight_base=service.weight_base())
                    for _ in range(mesh_group.n)]
                batches = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *[c[0] for c in chunks])
                w = np.stack(
                    [np.asarray(c[1], np.float32) for c in chunks])
                metrics = mesh_group.step_host_chunks(batches, w)
                # [N, K, B] — replica i's td rows pay back the
                # priorities of the rows IT sampled
                td = np.asarray(metrics["td_error"])
                for i, c in enumerate(chunks):
                    service.update_priorities(
                        c[2], np.abs(td[i]) + 1e-6, generation=c[3])
            else:
                chunks = [service.sample_chunk(k, cfg.batch_size)
                          for _ in range(mesh_group.n)]
                batches = jax.tree_util.tree_map(
                    lambda *xs: np.stack(xs), *[c[0] for c in chunks])
                metrics = mesh_group.step_host_chunks(batches)
            done += k
        beta_sched.advance(per)
        mesh_group.merge()
        # replica 0's slice stands in for `state` downstream (checkpoint,
        # eval lag accounting); the PUBLISHED params are the merged tree
        state = mesh_group.state_slice(0)
        lstep = max(lstep, mesh_group.steps_done)
        if metrics is None:
            return None
        return {name: np.asarray(metrics[name])[0, -1]
                for name in ("critic_loss", "actor_loss", "q_mean")}

    stop_actors = threading.Event()
    actor_threads: dict[int, threading.Thread] = {}

    def actor_loop(actor):
        try:
            while not stop_actors.is_set():
                if cfg.her:
                    actor.run_episode(cfg.max_steps)
                else:
                    actor.run(50)
        except Exception as e:  # noqa: BLE001 — actor crash must not kill training
            # Log and EXIT the thread; the once-per-cycle supervisor
            # respawns it, which also rate-limits a permanently failing
            # actor to one attempt per cycle.
            print(f"actor {actor.actor_id} crashed:\n{traceback.format_exc()}",
                  flush=True)
            contained_crash(f"actor.{actor.actor_id}", e)

    def start_actor_thread(i: int):
        t = threading.Thread(target=actor_loop, args=(actors[i],), daemon=True)
        t.start()
        actor_threads[i] = t

    def supervise_actors():
        """Failure recovery (SURVEY.md §5 — the reference has none): actors
        are stateless-restartable, so a dead thread is simply respawned."""
        for i, t in list(actor_threads.items()):
            if not t.is_alive() and not stop_actors.is_set():
                print(f"supervisor: restarting actor thread {i}", flush=True)
                start_actor_thread(i)

    if cfg.async_actors:
        for i in range(len(actors)):
            start_actor_thread(i)

    timer = StepTimer()
    last_metrics: dict = {}
    n_saves = 0
    if multi_host:
        # align the first sharded update across processes (warmup and
        # io/eval setup take different time per role)
        multihost.barrier("train_start")
    for epoch in range(cfg.n_epochs):
        for cycle in range(cfg.n_cycles):
            cycle_t0 = time.monotonic()
            # collect (sync mode; async actors stream in the background)
            if not cfg.async_actors:
                for actor in actors:
                    if cfg.her:
                        for _ in range(cfg.episodes_per_cycle):
                            actor.run_episode(cfg.max_steps)
                    else:
                        ticks = cfg.episodes_per_cycle * cfg.max_steps // max(
                            1, cfg.num_envs)
                        actor.run(ticks)
                service.flush()
            if multi_host and obs_norm is not None:
                # collective: merge every host's normalizer delta so all
                # hosts standardize with identical statistics this cycle
                obs_norm.sync()
            # train (trace the first cycle when profiling is enabled)
            timer.start()
            if epoch == 0 and cycle == 0 and cfg.profile_dir:
                with xla_trace(cfg.profile_dir):
                    metrics = train_steps(cfg.train_steps_per_cycle)
            else:
                metrics = train_steps(cfg.train_steps_per_cycle)
            rate = timer.stop(cfg.train_steps_per_cycle)
            # weight staleness actors saw this cycle, measured before the
            # cycle-end publish (<= K in async mode, one cycle in sync mode)
            weight_lag = lstep - weights.step
            publish()
            # eval + log (main.py:309-353). Concurrent mode: request a fresh
            # eval against the just-published weights and log the most
            # recent COMPLETED one; the learner thread never waits.
            eval_seed = cfg.seed + epoch * 1000 + cycle
            if async_eval is not None:
                async_eval.request(cfg.eval_trials, seed=eval_seed)
                eval_metrics = async_eval.latest()
            elif evaluator is not None:
                eval_metrics = evaluator.evaluate(cfg.eval_trials,
                                                  seed=eval_seed)
            else:
                eval_metrics = None
            last_metrics = {
                "critic_loss": float(jax.device_get(metrics["critic_loss"])),
                "actor_loss": float(jax.device_get(metrics["actor_loss"])),
                "env_steps": service.env_steps,
                "weight_lag_steps": weight_lag,
            }
            if eval_metrics is not None:
                last_metrics.update({
                    "avg_test_reward": eval_metrics["avg_test_reward"],
                    "ewma_test_reward": eval_metrics["ewma_test_reward"],
                    "success_rate": eval_metrics["success_rate"],
                    "eval_lag_steps": lstep - eval_metrics["learner_step"],
                })
            if rate is not None:
                last_metrics["grad_steps_per_sec"] = round(rate, 2)
            if cfg.trace_sample > 0:
                # wire-to-grad headline onto the metrics bus: the p95 of
                # the end-to-end span over the recent trace window
                lat = trace_recorder.latency_block()
                if lat["wire_to_grad"]["n"]:
                    last_metrics["wire_to_grad_p95_ms"] = \
                        lat["wire_to_grad"]["p95"]
            last_metrics["cycle_time_s"] = round(time.monotonic() - cycle_t0, 4)
            # Failure detection/recovery (SURVEY.md §5): stale heartbeats
            # reach the metrics bus (not just stdout); dead spawned actor
            # PROCESSES are respawned like dead threads — they are
            # stateless, replay and weights live with the learner. Remote
            # actors (other machines) can only be observed, not respawned.
            # Heartbeat liveness is only meaningful for STREAMING actors
            # (async threads, spawned procs, remote fleets) — synchronous
            # in-process actors ingest exactly once per cycle, so any slow
            # cycle would trip the timeout spuriously.
            track_liveness = (cfg.async_actors or cfg.actor_procs > 0
                              or cfg.serve)
            dead = service.dead_actors() if track_liveness else []
            last_metrics["dead_actors"] = len(dead)
            if dead:
                print(f"WARNING: actors missing heartbeats: {dead}", flush=True)
            for i, p in enumerate(actor_processes):
                if p is None:  # slot retired after repeated crash-looping
                    continue
                if p.is_alive():
                    actor_proc_fails[i] = 0
                    continue
                # once-per-cycle cadence already rate-limits respawns; the
                # consecutive-failure cap stops a child that cannot start
                # at all (bad GL/env config) from crash-looping forever
                # (ADVICE r3)
                actor_proc_fails[i] += 1
                if actor_proc_fails[i] > 5:
                    print(f"supervisor: actor process {i} died "
                          f"{actor_proc_fails[i]} consecutive cycles "
                          f"(exitcode {p.exitcode}); giving up on this "
                          "slot", flush=True)
                    actor_processes[i] = None
                    continue
                actor_proc_gen[i] += 1
                print(f"supervisor: restarting actor process {i} "
                      f"(exitcode {p.exitcode}, respawn "
                      f"#{actor_proc_gen[i]})", flush=True)
                actor_processes[i] = spawn_actor_proc(i, actor_proc_gen[i])
            if cfg.async_actors:
                supervise_actors()
            bus.log(lstep, last_metrics)
            if (cycle + 1) % cfg.checkpoint_every == 0:
                n_saves += 1
                replay_due = (
                    cfg.checkpoint_replay
                    and n_saves % max(1, cfg.checkpoint_replay_every) == 0)
                if ckpt is not None:
                    extra_payload = {"env_steps": service.env_steps}
                    if obs_norm is not None:
                        extra_payload["obs_norm"] = obs_norm.state_dict()
                    ckpt.save(
                        state if mesh is None else jax.device_get(state),
                        extra=extra_payload,
                    )
                if replay_due:
                    if ckpt is not None:
                        # durability order: the state checkpoint commits
                        # BEFORE the sidecar rename (Orbax saves async) —
                        # a crash in this window must never leave a
                        # sidecar AHEAD of the latest durable state, which
                        # restore would refuse, emptying the buffer (the
                        # exact failure the sidecar exists to prevent)
                        ckpt.wait()
                    # every host's SERVICE snapshot goes to its step-stamped
                    # sidecar (process 0 included) at a coarser cadence than
                    # the state checkpoint — the ring snapshot holds the
                    # buffer lock and (device storage) pays a full D2H copy.
                    # A service snapshot (vs the old buffer-only dict) also
                    # carries the admission-ticket floor + generation, so a
                    # crash-restart fences pre-crash frames and resumes
                    # merge-ordered. Restore tolerates the resulting
                    # staleness; an Orbax extra payload would instead vanish
                    # whenever the retention window outran the replay
                    # cadence.
                    _save_host_replay(run_dir, jax.process_index(), lstep,
                                      service.snapshot(quiesce_timeout=2.0))
    stop_actors.set()
    for t in actor_threads.values():
        t.join(timeout=10.0)
    if async_eval is not None:
        # Drain the last requested eval so the returned metrics reflect the
        # final published weights, then log it.
        final_eval = async_eval.wait()
        async_eval.close()
        if final_eval is not None:
            last_metrics.update({
                "avg_test_reward": final_eval["avg_test_reward"],
                "ewma_test_reward": final_eval["ewma_test_reward"],
                "success_rate": final_eval["success_rate"],
                "eval_lag_steps": lstep - final_eval["learner_step"],
            })
            bus.log(lstep, last_metrics)
    if ckpt is not None:
        ckpt.wait()
    bus.close()
    for p in actor_processes:
        if p is not None:
            p.terminate()
    for p in actor_processes:
        if p is not None:
            p.join(timeout=5.0)
    if autoscaler is not None:
        # first: a tick firing mid-teardown would actuate knobs on
        # planes that are already half-closed below
        autoscaler.close()
    for r in replicas:
        r.close()
    if aggregator is not None:
        aggregator.close()
    if mesh_group is not None:
        mesh_group.close()
    if fused_loop is not None:
        fused_loop.close()
    if receiver is not None:
        receiver.close()
    if weight_server is not None:
        weight_server.close()
    if policy_server is not None:
        policy_server.close()
    service.close()
    for actor in actors:
        if cfg.her:
            actor.env.close()
        else:
            actor.pool.close()
    if multi_host:
        # align exits: a process leaving while a peer still drains eval/
        # checkpoints trips the jax.distributed shutdown barrier
        multihost.barrier("train_end")
    return last_metrics


def main(argv=None):
    cfg = parse_args(argv)
    if cfg.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif cfg.platform == "auto" and not cfg.coordinator:
        # The tunnel to a remote accelerator can wedge so that backend init
        # hangs forever (not raises — unkillable from in-process). Probe it
        # in a subprocess with a timeout, exactly like the driver entry
        # points (__graft_entry__.py), and fall back to CPU so a training
        # run never hangs before its first log line.
        from d4pg_tpu.probe import describe, ensure_backend

        status = ensure_backend(timeout=90.0)
        if status != "accel":
            print(f"{describe(status)}; using the CPU backend", flush=True)
    if cfg.coordinator:
        # Join the multi-host runtime BEFORE any backend init; after this,
        # jax.devices() spans every process and --data_parallel can cover
        # the global device count (parallel/multihost.py). Each host runs
        # this same command with its own --process_id.
        from d4pg_tpu.parallel import multihost

        multihost.initialize(cfg.coordinator, cfg.num_processes,
                             cfg.process_id)
        # create the collective context NOW, while processes are in
        # lockstep (per-role io/eval setup later skews them past the
        # context-init timeout)
        multihost.barrier("startup")
        print(f"joined multi-host runtime: process {cfg.process_id}/"
              f"{cfg.num_processes}, {len(jax.devices())} global devices",
              flush=True)
    result = train(cfg)
    print("final:", result)


if __name__ == "__main__":
    main()
