"""Distributed compute: device meshes and the sharded data-parallel learner.

The reference's only parallelism is asynchronous hogwild data parallelism
over OS shared memory (``ddpg.py:104-108``, ``shared_adam.py``,
``main.py:384-405`` — SURVEY.md §2 "Parallelism strategies"). The TPU-native
replacement is synchronous data parallelism over the ICI mesh: params and
optimizer state replicated, the batch sharded over a ``data`` axis, and the
gradient all-reduce inserted by XLA from sharding constraints (or explicit
``psum`` under ``shard_map``). A ``model`` axis is laid out from day one so
the pixel-encoder config can shard activations later (SURVEY.md §2 mandate).
"""

from d4pg_tpu.parallel.mesh import MeshSpec, make_mesh, replica_mesh
from d4pg_tpu.parallel import partition
from d4pg_tpu.parallel.data_parallel import (
    make_sharded_multi_update,
    make_sharded_update,
    replicate_state,
    shard_batch,
    shard_stacked,
    stacked_sharding,
)

__all__ = [
    "MeshSpec",
    "make_mesh",
    "make_sharded_multi_update",
    "make_sharded_update",
    "partition",
    "replica_mesh",
    "replicate_state",
    "shard_batch",
    "shard_stacked",
    "stacked_sharding",
]
