"""Device mesh construction.

One place decides the mesh geometry for the whole framework: a ``data`` axis
for batch sharding (the D4PG learner's axis) and a ``model`` axis reserved
for activation/weight sharding of larger trunks (SURVEY.md §2: "the mesh
axis layout should be designed in from day one"). On a real slice the mesh
axes ride ICI; under ``xla_force_host_platform_device_count`` the same code
runs on virtual CPU devices for tests and the driver's multichip dry-run.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh


DATA_AXIS = "data"
MODEL_AXIS = "model"
# Mesh-native learner replicas: an [N, ...]-stacked tree of per-replica
# states is split along this axis and the aggregator's merge runs as an
# on-device collective over it (learner/mesh_replicas.py).
REPLICA_AXIS = "replica"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Mesh geometry: data_parallel x model_parallel devices."""

    data_parallel: int = -1  # -1: all remaining devices
    model_parallel: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        mp = max(1, self.model_parallel)
        dp = self.data_parallel
        if dp == -1:
            if n_devices % mp:
                raise ValueError(f"{n_devices} devices not divisible by model_parallel={mp}")
            dp = n_devices // mp
        if dp * mp != n_devices:
            raise ValueError(
                f"mesh {dp}x{mp} != {n_devices} devices; fix MeshSpec"
            )
        return dp, mp


def make_mesh(spec: MeshSpec = MeshSpec(), devices=None) -> Mesh:
    """Build the (data, model) mesh over the given (default: all) devices."""
    devices = list(jax.devices()) if devices is None else list(devices)
    dp, mp = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(dp, mp)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replica_mesh(n_replicas: int, devices=None) -> Mesh:
    """(replica, data, model) mesh for mesh-native learner replicas: one
    device per replica, with singleton data/model axes so the partition
    rules resolve on the same axis vocabulary as the learner mesh (any
    rule spec stays satisfiable over a size-1 axis)."""
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_replicas < 1:
        raise ValueError(f"n_replicas={n_replicas} must be >= 1")
    if n_replicas > len(devices):
        raise ValueError(
            f"replica mesh needs {n_replicas} devices, have {len(devices)}")
    arr = np.asarray(devices[:n_replicas]).reshape(n_replicas, 1, 1)
    return Mesh(arr, (REPLICA_AXIS, DATA_AXIS, MODEL_AXIS))
