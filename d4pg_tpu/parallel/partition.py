"""Partition rules: the single source of sharding truth.

Every ``PartitionSpec``/``NamedSharding`` in the framework is built HERE
(jaxlint family 15, ``sharding-rule-bypass``, rejects construction
anywhere else). Two layers:

**Layout helpers** — the fixed data-plane layouts the learner dispatches
use (batch over ``data``, [K, B] stacks with the scan axis replicated,
replica-stacked trees over ``replica``). Callers say what the array IS
(``batch_sharding(mesh)``) instead of hand-wiring axis tuples at every
jit site.

**Regex partition rules** — for *named parameter/optimizer trees* the
layout is decided by a rule table: ``(pattern, spec)`` pairs matched
against '/'-joined tree paths (the SAME names the weight codec's
flattened keys use — ``named_flat`` here is what the weight and update
planes serialize, so the wire naming and the sharding naming cannot
drift). Matching semantics, pinned by ``tests/test_partition.py``:

- scalar leaves (ndim 0 or size 1 — ``step``, Adam ``count``, PRNG key)
  are NEVER partitioned, before any rule is consulted;
- first match wins (``re.search``, table order = precedence);
- a leaf no rule matches fails LOUDLY with the resolved table in the
  message — silent replication is how layouts rot.

``D4PG_RULES`` is the production table: the pixel conv encoder is the
first ``model``-axis tenant (kernels/biases split over out-channels —
the SURVEY §2 mandate the axis was reserved for), everything else
replicated. The rules apply identically to params and to the Adam
moments that mirror them, because ``re.search`` finds the param path
inside the optimizer path (``actor_opt_state/0/mu/params/...``).
"""

from __future__ import annotations

import re
from typing import Any, Callable

import numpy as np

from d4pg_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, REPLICA_AXIS

from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

__all__ = [
    "PS", "D4PG_RULES", "named_tree_map", "tree_names",
    "match_partition_rules", "format_rules", "spec", "sharding",
    "replicated", "batch_sharding", "stacked_sharding", "replica_sharding",
    "batch_spec", "replicated_spec", "stacked_spec", "replica_spec",
    "per_tree_spec", "dealt_block_spec", "per_tree_sharding",
    "dealt_block_sharding",
    "data_spec", "shardings_for", "state_specs", "state_shardings",
    "replica_stack_shardings", "make_shard_and_gather_fns",
    "named_flat", "named_unflat",
]


# --------------------------------------------------------------------------
# fixed data-plane layouts
# --------------------------------------------------------------------------


def spec(*axes) -> PS:
    """A raw ``PartitionSpec`` — the one sanctioned constructor for
    layouts the helpers below don't name (e.g. per-call shard_map
    in_specs). Prefer the named helpers where one fits."""
    return PS(*axes)


def sharding(mesh: Mesh, *axes) -> NamedSharding:
    """``NamedSharding`` over ``mesh`` for an explicit axis layout."""
    return NamedSharding(mesh, PS(*axes))


def replicated_spec() -> PS:
    return PS()


def batch_spec() -> PS:
    """[B, ...] batches: leading dim split over ``data``."""
    return PS(DATA_AXIS)


# alias: shard_map call sites read better as "the data-axis spec"
data_spec = batch_spec


def stacked_spec() -> PS:
    """[K, B, ...] chunk stacks: K replicated (the scan axis), B split
    over ``data``."""
    return PS(None, DATA_AXIS)


def replica_spec() -> PS:
    """[N, ...] replica-stacked trees: leading dim split over
    ``replica`` (the mesh-native learner-replica layout)."""
    return PS(REPLICA_AXIS)


def per_tree_spec() -> PS:
    """[2·cap] device PER sum/min trees (``replay/device_per.PerTrees``):
    REPLICATED. The stratified descent is a root-to-leaf pointer chase —
    every query touches every level, so splitting the tree over any mesh
    axis would turn each of the log2(cap) gathers into a collective.
    Keeping the tree replicated keeps the jitted deal dispatch at zero
    all-to-alls (the ReshardSentinel pin in bench.py's device-dealt
    block) at a memory cost of 8 bytes/slot/device."""
    return PS()


def dealt_block_spec() -> PS:
    """[K, B, ...] device-dealt gathers (rows, weights, idx, gen out of
    ``DeviceSampleDealer.deal_fn``): same layout as the chunk stacks
    they feed — K replicated (the scan axis), B split over ``data``.
    With the tree replicated (``per_tree_spec``) the gather itself needs
    no resharding to land here."""
    return stacked_spec()


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def stacked_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, stacked_spec())


def replica_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replica_spec())


def per_tree_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, per_tree_spec())


def dealt_block_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, dealt_block_spec())


# --------------------------------------------------------------------------
# named trees: one naming scheme for rules AND the wire codecs
# --------------------------------------------------------------------------


def named_tree_map(fn: Callable[[str, Any], Any], tree: Any,
                   sep: str = "/") -> Any:
    """Structure-preserving map with the leaf's '/'-joined path name.

    Handles the shapes that actually occur in a ``D4PGState``: dicts
    (flax param trees — key names), NamedTuples (the state itself, optax
    ``ScaleByAdamState``... — field names), plain lists/tuples (optax
    ``chain`` — indices). ``None`` leaves pass through (optax uses them
    as empty slots). Dict naming matches flax's ``flatten_dict(sep='/')``
    exactly — the weight codec's key grammar.
    """

    def join(prefix: str, part: str) -> str:
        return f"{prefix}{sep}{part}" if prefix else part

    def walk(prefix: str, node: Any) -> Any:
        if isinstance(node, PS):
            # PartitionSpec subclasses tuple on some jax versions —
            # always a leaf here (spec trees map through this fn too)
            return fn(prefix, node)
        if isinstance(node, dict):
            return {k: walk(join(prefix, str(k)), v) for k, v in node.items()}
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*[walk(join(prefix, f), getattr(node, f))
                                for f in node._fields])
        if isinstance(node, (list, tuple)):
            vals = [walk(join(prefix, str(i)), v) for i, v in enumerate(node)]
            return vals if isinstance(node, list) else tuple(vals)
        if node is None:
            return None
        return fn(prefix, node)

    return walk("", tree)


def tree_names(tree: Any, sep: str = "/") -> list[str]:
    """The '/'-joined leaf names of ``tree``, in traversal order."""
    names: list[str] = []
    named_tree_map(lambda name, leaf: names.append(name) or leaf, tree,
                   sep=sep)
    return names


def named_flat(params: Any) -> dict[str, np.ndarray]:
    """Flatten a nested dict pytree to ``{'a/b/c': array}`` — THE wire
    naming: the weight plane's codec keys and the update plane's
    submission payloads are exactly these names, and the rule table
    above matches against them. Uses flax's own param-dict flattening so
    key semantics match Flax exactly."""
    from flax.traverse_util import flatten_dict

    return {k: np.asarray(v)
            for k, v in flatten_dict(params, sep="/").items()}


def named_unflat(flat: dict[str, np.ndarray]) -> Any:
    """Invert :func:`named_flat`."""
    from flax.traverse_util import unflatten_dict

    return unflatten_dict(dict(flat), sep="/")


# --------------------------------------------------------------------------
# the rule engine
# --------------------------------------------------------------------------

# (pattern, spec): first match wins. The pixel conv encoder is the
# model-axis tenant — kernels [3, 3, in, out] and biases [out] split
# over out-channels (channel counts are MXU-friendly multiples of the
# model degree); everything else — MLP trunks, LayerNorm scales, Adam
# moments of all of the above — replicated.
D4PG_RULES: tuple[tuple[str, PS], ...] = (
    (r"encoder/conv\d+/kernel", PS(None, None, None, MODEL_AXIS)),
    (r"encoder/conv\d+/bias", PS(MODEL_AXIS)),
    (r".*", PS()),
)


def _is_scalar(leaf: Any) -> bool:
    shape = tuple(getattr(leaf, "shape", ()))
    return len(shape) == 0 or int(np.prod(shape)) == 1


def format_rules(rules=D4PG_RULES) -> str:
    """The resolved rule table, one ``pattern -> spec`` row per line —
    what ``check_mesh_compatible`` and the unmatched-key error print."""
    width = max(len(p) for p, _ in rules)
    return "\n".join(f"  {p:<{width}}  ->  {s}" for p, s in rules)


def match_partition_rules(rules, tree: Any) -> Any:
    """Resolve ``tree`` to a structure-matching tree of PartitionSpecs.

    Scalar leaves (ndim 0 or size 1) are never partitioned; otherwise
    the first ``re.search`` match in table order decides. A leaf nothing
    matches raises with the leaf's name and the table."""

    def resolve(name: str, leaf: Any) -> PS:
        if _is_scalar(leaf):
            return PS()
        for pattern, s in rules:
            if re.search(pattern, name):
                return s
        raise ValueError(
            f"no partition rule matches leaf {name!r}; resolved table:\n"
            f"{format_rules(rules)}")

    return named_tree_map(resolve, tree)


def shardings_for(mesh: Mesh, tree: Any, rules=D4PG_RULES) -> Any:
    """Rule-resolved ``NamedSharding`` tree for ``tree`` over ``mesh``."""
    return named_tree_map(
        lambda name, s: NamedSharding(mesh, s),
        match_partition_rules(rules, tree))


def state_specs(config, rules=D4PG_RULES) -> Any:
    """Rule-resolved PartitionSpec tree for a ``D4PGState`` of this
    config — structure derived via ``eval_shape`` (no arrays built)."""
    import jax

    from d4pg_tpu.learner.state import init_state

    shapes = jax.eval_shape(
        lambda: init_state(config, jax.random.key(0)))
    return match_partition_rules(rules, shapes)


def state_shardings(config, mesh: Mesh, rules=D4PG_RULES) -> Any:
    """Rule-resolved ``NamedSharding`` tree for a ``D4PGState`` — the
    in/out_shardings the sharded update factories pass to jit."""
    return named_tree_map(lambda name, s: NamedSharding(mesh, s),
                          state_specs(config, rules))


def replica_stack_shardings(mesh: Mesh, tree: Any,
                            rules=D4PG_RULES) -> Any:
    """Rule specs with the ``replica`` axis prepended: the layout of an
    [N, ...]-stacked tree of per-replica states on a replica mesh (the
    inner axes keep their rule-resolved placement; on the
    ``replica_mesh`` geometry those axes are singleton, so every rule
    stays satisfiable)."""
    return named_tree_map(
        lambda name, s: NamedSharding(mesh, PS(REPLICA_AXIS, *s)),
        match_partition_rules(rules, tree))


def make_shard_and_gather_fns(shardings: Any) -> tuple[Any, Any]:
    """Per-leaf shard/gather callables for a ``NamedSharding`` tree:
    ``shard_fns`` place host leaves (``device_put`` with the leaf's
    sharding), ``gather_fns`` pull them back to host numpy. Apply with
    ``jax.tree_util.tree_map(lambda f, x: f(x), fns, tree)``."""
    import jax

    def shard_fn(s):
        return lambda leaf: jax.device_put(leaf, s)

    def gather_fn(_s):
        return lambda leaf: np.asarray(jax.device_get(leaf))

    shard_fns = jax.tree_util.tree_map(
        shard_fn, shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    gather_fns = jax.tree_util.tree_map(
        gather_fn, shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    return shard_fns, gather_fns
