"""Multi-host learner startup: one logical device mesh across processes.

The reference cannot cross hosts at all — its "distributed backend" is
``torch.multiprocessing`` + OS shared memory on one machine (``main.py:12,
386-388``, SURVEY.md C18). The TPU-native equivalent is ``jax.distributed``:
every host starts the same program, ``initialize()`` forms the global
runtime over DCN, and the SAME sharded update compiled in
``data_parallel.py`` runs SPMD over the union of all hosts' chips with
XLA-inserted collectives (ICI within a slice, DCN across).

Simulated multi-host (SURVEY.md §4: "multi-host tests via jax.distributed-
under-simulation") runs N local processes with virtual CPU devices — see
``multihost_check.py`` and ``tests/test_multihost.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from d4pg_tpu.parallel import partition
from d4pg_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

# Partition specs come from the rule core; P survives only as the type
# annotation for make_global_batch's optional spec argument.
P = partition.PS


def initialize(coordinator: str, num_processes: int, process_id: int,
               local_device_ids: Optional[list[int]] = None) -> None:
    """Join the multi-process JAX runtime. MUST run before anything
    initializes a backend (train.py calls it straight after arg parsing).

    ``coordinator``: ``host:port`` of process 0 (the reference has no
    analog; this replaces nothing and adds the cross-host capability).
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def barrier(name: str) -> None:
    """Cross-process sync point. Call once right after :func:`initialize`
    (while all processes are still in lockstep) so the collective context
    (gloo on the CPU-simulation backend) is created well inside its
    ~30 s init timeout — per-role setup (TensorBoard import, Orbax,
    evaluator) skews processes by more than that otherwise — and again
    before the training loop to align the first sharded update."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def global_mesh(model_parallel: int = 1) -> Mesh:
    """(data, model) mesh over ALL devices of ALL processes. Device order
    from ``jax.devices()`` is process-contiguous, so the data axis maps
    host-local batches to host-local devices (DCN only carries gradient
    all-reduce, not batch rows)."""
    devices = np.array(jax.devices())
    if devices.size % model_parallel:
        raise ValueError(
            f"{devices.size} devices not divisible by model_parallel={model_parallel}")
    return Mesh(devices.reshape(-1, model_parallel), (DATA_AXIS, MODEL_AXIS))


def make_global_batch(local_batch, mesh: Mesh, spec: P | None = None):
    """Assemble a globally-sharded batch pytree from each process's local
    shard: process p contributes rows [p*B_local, (p+1)*B_local) of the
    global batch along the ``data`` axis. Each host samples from its OWN
    replay shard (the Ape-X sharded-replay layout); rows never cross hosts.

    ``spec`` defaults to ``partition.data_spec()`` (plain [B, ...]
    batches); pass ``partition.stacked_spec()`` for [K, B, ...] chunks.
    """
    spec = partition.data_spec() if spec is None else spec
    sharding = partition.sharding(mesh, *spec)
    axis = list(spec).index(DATA_AXIS)

    def to_global(x):
        x = np.asarray(x)
        global_shape = list(x.shape)
        global_shape[axis] *= jax.process_count()
        return jax.make_array_from_process_local_data(
            sharding, x, tuple(global_shape))

    return jax.tree_util.tree_map(to_global, local_batch)


def make_global_chunk(local_chunk, mesh: Mesh):
    """:func:`make_global_batch` for stacked [K, B, ...] chunks (the K scan
    axis replicated, B sharded over ``data``)."""
    return make_global_batch(local_chunk, mesh,
                             spec=partition.stacked_spec())


def local_rows(global_array, axis: int = 0) -> np.ndarray:
    """This process's contribution of a data-axis-sharded array (the
    inverse of :func:`make_global_batch`), as host numpy — e.g. the local
    slice of the global ``td_error`` that feeds this host's PER
    write-back. ``axis`` MUST be the sharded (data) axis: 0 for [B]
    arrays, 1 for stacked [K, B] chunk outputs — deduplication keys on
    the shard start index along that axis, so passing a replicated axis
    would silently collapse everything to one shard. Non-addressable
    shards are never touched."""
    seen = {}
    for s in global_array.addressable_shards:
        start = s.index[axis].start or 0
        if start not in seen:
            seen[start] = np.asarray(s.data)
    return np.concatenate(
        [seen[k] for k in sorted(seen)], axis=axis)


def global_min_scalar(x: float) -> float:
    """Min of a host scalar across all processes (one tiny allgather) —
    e.g. the PER IS-weight base ``z = p_min_frac * N``: normalizing every
    host's weights by the same global ``z ** -beta`` keeps gradient
    contributions consistently scaled across shards (a per-host normalizer
    would bias hosts whose buffers hold smaller minimum priorities)."""
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(x, np.float64))
    return float(np.min(gathered))


def replicate_state_global(init_fn, mesh: Mesh):
    """Build the train state replicated across ALL processes' devices.

    A host-local ``device_put`` cannot address other hosts' devices, so the
    state is constructed INSIDE jit with replicated out_shardings — every
    process traces the same ``init_fn`` (same config, same seed) and XLA
    materializes identical replicas everywhere.
    """
    repl = partition.replicated(mesh)
    # one-shot by design: jit is the only mechanism that can materialize
    # state on other processes' devices, and this runs once at startup
    return jax.jit(init_fn, out_shardings=repl)()  # jaxlint: disable=recompile-hazard
