"""Synchronous data-parallel learner over the device mesh.

Replaces the reference's entire distributed-update machinery — grad aliasing
into shared tensors (``ddpg.py:104-108``), racy ``SharedAdam.step()`` from N
processes (``shared_adam.py``), weight pull-back (``ddpg.py:118-120``) and
the 1/n_workers lr rescale (``main.py:384-385``) — with the GSPMD
formulation: the train state carries rule-resolved shardings (replicated
except where the partition table says otherwise — the pixel encoder's
``model``-axis tenancy), the batch is sharded over the ``data`` axis, and
the SAME ``update_step`` used single-chip is jit'd with those shardings.
``jnp.mean`` over the global batch inside the loss becomes an XLA
all-reduce over ICI; every replica then applies an identical Adam update —
synchronous, deterministic, race-free by construction (SURVEY.md §5).

Every sharding here comes from ``parallel/partition.py`` — the single
source of sharding truth (jaxlint ``sharding-rule-bypass`` enforces it).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.learner.update import multi_update_step, update_step
from d4pg_tpu.replay.uniform import TransitionBatch

from d4pg_tpu.parallel import partition

# Re-exported for the training loop's chunk staging (the [K, B, ...]
# layout helper used to live here; partition.py owns it now).
stacked_sharding = partition.stacked_sharding


def replicate_state(state: D4PGState, mesh: Mesh) -> D4PGState:
    """Place the train state over the mesh by partition rule — fully
    replicated for MLP configs; pixel configs put the conv encoder's
    kernels/biases on the ``model`` axis (``partition.D4PG_RULES``)."""
    return jax.device_put(state, partition.shardings_for(mesh, state))


def shard_batch(batch: TransitionBatch, mesh: Mesh) -> TransitionBatch:
    """Shard a host batch over the ``data`` axis (leading dim split across
    the mesh's data dimension). The batch size must divide evenly."""
    return jax.device_put(batch, partition.batch_sharding(mesh))


def shard_stacked(batches, mesh: Mesh):
    """Shard a [K, B, ...] stack of batches: the scan axis K stays
    replicated, B splits over ``data``. Works on any pytree whose leaves
    carry the [K, B, ...] layout (TransitionBatch stacks, weight stacks)."""
    return jax.device_put(batches, partition.stacked_sharding(mesh))


def check_mesh_compatible(config: D4PGConfig) -> None:
    """The Pallas projection kernel has no GSPMD partitioning rule — under
    a sharded jit it would fail to compile or silently all-gather the
    batch onto every device. Mesh learners must use the einsum
    formulation (which shards trivially); fail loudly rather than either,
    and print the rule table the mesh layout WOULD resolve to, so the fix
    (and what it buys) is in the error itself."""
    if config.projection in ("pallas", "pallas_ce"):
        raise ValueError(
            f"--projection {config.projection} is single-device only "
            "(pallas_call does not partition under a sharded jit); use "
            "--projection einsum with a device mesh. Resolved partition "
            "rules for this mesh:\n" + partition.format_rules()
        )


def make_sharded_update(
    config: D4PGConfig,
    mesh: Mesh,
    donate: bool = True,
    use_is_weights: bool = True,
):
    """jit the D4PG update with explicit shardings over ``mesh``.

    in: state by partition rule, batch + IS weights sharded over
    ``data``. out: state by the same rules, scalar metrics replicated,
    per-sample ``td_error`` sharded over ``data`` (it flows back to the
    host PER priority update, ``ddpg.py:252-255``).
    """
    check_mesh_compatible(config)
    repl = partition.replicated(mesh)
    shard = partition.batch_sharding(mesh)
    state_sh = partition.state_shardings(config, mesh)

    # Shardings as pytree prefixes: a single sharding broadcasts to the
    # tree; the state's is a full rule-resolved tree.
    in_shardings: tuple
    out_metrics = {
        "critic_loss": repl,
        "actor_loss": repl,
        "q_mean": repl,
        "td_error": shard,
    }
    if use_is_weights:
        fn = lambda state, batch, w: update_step(config, state, batch, w)
        in_shardings = (state_sh, shard, shard)
    else:
        fn = lambda state, batch: update_step(config, state, batch, None)
        in_shardings = (state_sh, shard)
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=(state_sh, out_metrics),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_multi_update(
    config: D4PGConfig,
    mesh: Mesh,
    donate: bool = True,
    use_is_weights: bool = True,
):
    """jit the K-step scanned update with explicit shardings over ``mesh`` —
    the production configuration: dispatch amortization (K ``lax.scan``
    steps per device round trip) COMBINED with data parallelism (each step's
    [B, ...] batch split over the ``data`` axis, gradients all-reduced by
    XLA-inserted collectives over ICI).

    in: state by partition rule, batches [K, B, ...] + weights [K, B]
    sharded ``stacked_spec()``. out: state by the same rules, scalar
    metrics stacked [K] replicated, ``td_error`` [K, B] sharded like the
    batches.
    """
    check_mesh_compatible(config)
    repl = partition.replicated(mesh)
    stacked = partition.stacked_sharding(mesh)
    state_sh = partition.state_shardings(config, mesh)
    out_metrics = {
        "critic_loss": repl,
        "actor_loss": repl,
        "q_mean": repl,
        "td_error": stacked,
    }
    if use_is_weights:
        fn = lambda state, batches, w: multi_update_step(config, state, batches, w)
        in_shardings: tuple = (state_sh, stacked, stacked)
    else:
        fn = lambda state, batches: multi_update_step(config, state, batches)
        in_shardings = (state_sh, stacked)
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=(state_sh, out_metrics),
        donate_argnums=(0,) if donate else (),
    )
