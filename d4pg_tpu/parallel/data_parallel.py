"""Synchronous data-parallel learner over the device mesh.

Replaces the reference's entire distributed-update machinery — grad aliasing
into shared tensors (``ddpg.py:104-108``), racy ``SharedAdam.step()`` from N
processes (``shared_adam.py``), weight pull-back (``ddpg.py:118-120``) and
the 1/n_workers lr rescale (``main.py:384-385``) — with the GSPMD
formulation: the train state carries a replicated sharding, the batch is
sharded over the ``data`` axis, and the SAME ``update_step`` used single-chip
is jit'd with those shardings. ``jnp.mean`` over the global batch inside the
loss becomes an XLA all-reduce over ICI; every replica then applies an
identical Adam update — synchronous, deterministic, race-free by
construction (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.learner.update import multi_update_step, update_step
from d4pg_tpu.replay.uniform import TransitionBatch

from d4pg_tpu.parallel.mesh import DATA_AXIS


def _replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def stacked_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [K, B, ...] chunk stacks: K replicated (the scan axis),
    B split over ``data``. The single source of truth for the stacked
    layout — used by ``make_sharded_multi_update`` and by the training
    loop's chunk staging."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicate_state(state: D4PGState, mesh: Mesh) -> D4PGState:
    """Place the train state fully replicated over the mesh."""
    return jax.device_put(state, _replicated(mesh))


def shard_batch(batch: TransitionBatch, mesh: Mesh) -> TransitionBatch:
    """Shard a host batch over the ``data`` axis (leading dim split across
    the mesh's data dimension). The batch size must divide evenly."""
    return jax.device_put(batch, _batch_sharding(mesh))


def shard_stacked(batches, mesh: Mesh):
    """Shard a [K, B, ...] stack of batches: the scan axis K stays
    replicated, B splits over ``data``. Works on any pytree whose leaves
    carry the [K, B, ...] layout (TransitionBatch stacks, weight stacks)."""
    return jax.device_put(batches, stacked_sharding(mesh))


def check_mesh_compatible(config: D4PGConfig) -> None:
    """The Pallas projection kernel has no GSPMD partitioning rule — under
    a sharded jit it would fail to compile or silently all-gather the
    batch onto every device. Mesh learners must use the einsum
    formulation (which shards trivially); fail loudly rather than either."""
    if config.projection in ("pallas", "pallas_ce"):
        raise ValueError(
            f"--projection {config.projection} is single-device only "
            "(pallas_call does not partition under a sharded jit); use "
            "--projection einsum with a device mesh"
        )


def make_sharded_update(
    config: D4PGConfig,
    mesh: Mesh,
    donate: bool = True,
    use_is_weights: bool = True,
):
    """jit the D4PG update with explicit shardings over ``mesh``.

    in: state replicated, batch + IS weights sharded over ``data``.
    out: state replicated, scalar metrics replicated, per-sample
    ``td_error`` sharded over ``data`` (it flows back to the host PER
    priority update, ``ddpg.py:252-255``).
    """
    check_mesh_compatible(config)
    repl = _replicated(mesh)
    shard = _batch_sharding(mesh)

    # Shardings as pytree prefixes: a single sharding broadcasts to the tree.
    in_shardings: tuple
    out_metrics = {
        "critic_loss": repl,
        "actor_loss": repl,
        "q_mean": repl,
        "td_error": shard,
    }
    if use_is_weights:
        fn = lambda state, batch, w: update_step(config, state, batch, w)
        in_shardings = (repl, shard, shard)
    else:
        fn = lambda state, batch: update_step(config, state, batch, None)
        in_shardings = (repl, shard)
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=(repl, out_metrics),
        donate_argnums=(0,) if donate else (),
    )


def make_sharded_multi_update(
    config: D4PGConfig,
    mesh: Mesh,
    donate: bool = True,
    use_is_weights: bool = True,
):
    """jit the K-step scanned update with explicit shardings over ``mesh`` —
    the production configuration: dispatch amortization (K ``lax.scan``
    steps per device round trip) COMBINED with data parallelism (each step's
    [B, ...] batch split over the ``data`` axis, gradients all-reduced by
    XLA-inserted collectives over ICI).

    in: state replicated, batches [K, B, ...] + weights [K, B] sharded
    ``P(None, 'data')``. out: state replicated, scalar metrics stacked [K]
    replicated, ``td_error`` [K, B] sharded ``P(None, 'data')``.
    """
    check_mesh_compatible(config)
    repl = _replicated(mesh)
    stacked = stacked_sharding(mesh)
    out_metrics = {
        "critic_loss": repl,
        "actor_loss": repl,
        "q_mean": repl,
        "td_error": stacked,
    }
    if use_is_weights:
        fn = lambda state, batches, w: multi_update_step(config, state, batches, w)
        in_shardings: tuple = (repl, stacked, stacked)
    else:
        fn = lambda state, batches: multi_update_step(config, state, batches)
        in_shardings = (repl, stacked)
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=(repl, out_metrics),
        donate_argnums=(0,) if donate else (),
    )
