"""Scripted multi-host check: N local processes form ONE mesh and run the
full sharded D4PG update (SURVEY.md §4 "multi-host tests via
jax.distributed-under-simulation"; VERDICT r1 #8).

Every process runs this same program (SPMD), e.g. for two processes:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python -m d4pg_tpu.parallel.multihost_check \
        --coordinator 127.0.0.1:29781 --num_processes 2 --process_id 0 &
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python -m d4pg_tpu.parallel.multihost_check \
        --coordinator 127.0.0.1:29781 --num_processes 2 --process_id 1

Each process contributes its local virtual CPU devices, samples its OWN
local half of the global batch, and the jit'd update all-reduces gradients
across the 8-device global mesh. Success prints ``multihost_check OK`` on
every process with the same loss (replicas agree bit-for-bit).
"""

from __future__ import annotations

import argparse
from functools import partial

import numpy as np


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="d4pg_tpu.parallel.multihost_check")
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num_processes", type=int, required=True)
    ap.add_argument("--process_id", type=int, required=True)
    ap.add_argument("--cpu", type=int, default=1,
                    help="force the CPU backend (simulation mode)")
    ap.add_argument("--fused", type=int, default=0,
                    help="exercise the sharded fused replay data plane "
                         "(replay/sharded_per.py + learner/fused.py) "
                         "instead of the host-batch sharded update")
    ns = ap.parse_args(argv)

    import jax

    if ns.cpu:
        jax.config.update("jax_platforms", "cpu")

    from d4pg_tpu.parallel import multihost

    multihost.initialize(ns.coordinator, ns.num_processes, ns.process_id)
    assert jax.process_count() == ns.num_processes

    from d4pg_tpu.learner import D4PGConfig, init_state
    from d4pg_tpu.parallel.data_parallel import make_sharded_update
    from d4pg_tpu.replay.uniform import TransitionBatch

    mesh = multihost.global_mesh()
    n_global = len(jax.devices())
    obs_dim, act_dim = 6, 2
    local_b = 2 * len(jax.local_devices())

    config = D4PGConfig(obs_dim=obs_dim, act_dim=act_dim, v_min=-5.0,
                        v_max=0.0, n_atoms=11, hidden=(16, 16))
    # identical seed on every process -> identical replicated state
    state = multihost.replicate_state_global(
        partial(init_state, config, jax.random.key(0)), mesh)
    update = make_sharded_update(config, mesh, donate=True,
                                 use_is_weights=False)

    # each process samples ITS shard of the global batch
    rng = np.random.default_rng(100 + ns.process_id)
    done = np.zeros(local_b, np.float32)
    local = TransitionBatch(
        obs=rng.standard_normal((local_b, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (local_b, act_dim)).astype(np.float32),
        reward=rng.standard_normal(local_b).astype(np.float32),
        next_obs=rng.standard_normal((local_b, obs_dim)).astype(np.float32),
        done=done,
        discount=(0.99 * (1.0 - done)).astype(np.float32),
    )
    losses = []
    if ns.fused:
        # The fused sharded replay data plane across hosts: each host
        # drains ITS rows into its local shards (collective insert), then
        # both run the fused chunk — sample + update + priority write-back
        # all inside one SPMD dispatch over the global mesh.
        from d4pg_tpu.learner.fused import make_sharded_fused_chunk
        from d4pg_tpu.replay.sharded_per import ShardedFusedReplay

        buf = ShardedFusedReplay(256, obs_dim, act_dim, mesh, alpha=0.6)
        for _ in range(4):
            buf.add(local)
            buf.drain()
        fn = make_sharded_fused_chunk(config, mesh, k=2, batch_size=16,
                                      alpha=0.6, donate=False)
        trees = buf.trees
        for _ in range(2):
            state, trees, metrics = fn(state, trees, buf.storage, buf.size)
            losses.append(float(jax.device_get(metrics["critic_loss"][-1])))
        # per-host checkpoint payload survives a roundtrip into a fresh
        # buffer (the multi-host sidecar resume path)
        buf.trees = trees
        snap = buf.state_dict()
        buf2 = ShardedFusedReplay(256, obs_dim, act_dim, mesh, alpha=0.6)
        buf2.load_state_dict(snap)
        assert len(buf2) == len(buf) > 0
        assert int(jax.device_get(state.step)) == 4
    else:
        for _ in range(2):
            batch = multihost.make_global_batch(local, mesh)
            state, metrics = update(state, batch)
            losses.append(float(jax.device_get(metrics["critic_loss"])))
        assert int(jax.device_get(state.step)) == 2
    assert all(np.isfinite(losses))
    print(
        f"multihost_check OK: process {ns.process_id}/{ns.num_processes}, "
        f"mesh {n_global} devices "
        f"({len(jax.local_devices())} local), losses {losses[0]:.6f} "
        f"{losses[1]:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
