"""Version-compat shims for JAX APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` (where the
replication-check kwarg is ``check_rep``) to ``jax.shard_map`` (where it
was renamed ``check_vma``). Callers in this repo use the modern spelling;
this shim translates for older jax."""

from __future__ import annotations

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check_vma})
