"""Gradient/update aggregator: N learner replicas, ONE versioned stream.

The multi-learner plane's merge point (ROADMAP direction #1, IMPACT —
arXiv 1912.00167). Each ``LearnerReplica`` computes updates against a
**basis version** it pulled from here, stamps that version on its
submission, and the aggregator merges the result into the single
authoritative parameter tree, publishing every merge through the
versioned ``WeightStore`` so actors, relays and the whole PR-9 weight
plane keep seeing one monotone (generation, version) stream — replicas
are invisible downstream.

Two aggregation modes (config choice, not architecture — the
"21 minutes" paper's synchronous alternative, arXiv 1801.02852):

- ``async`` (IMPACT-style): a submission computed against basis version
  ``b`` arriving when the aggregate is at version ``v`` has staleness
  ``lag = v - b``. It is applied as an importance-weighted correction

      params <- params + w * (submitted - params),
      w = max(1 / (1 + lag), 1 / clip)

  i.e. the natural ``1/(1+lag)`` staleness discount, clipped from
  below at ``1/clip`` so a very stale (but live) replica keeps a
  bounded vote instead of starving (``clip >= 1``, configurable; the
  **clip rate** — how often the bound engages — is exported). At
  ``lag == 0`` the submission IS the next aggregate and is adopted
  wholesale — an exact identity fast-path, NOT ``params + 1.0 *
  (new - params)``, whose float round-trip would break the N=1
  bitwise-equivalence oracle the tier-1 suite pins.

- ``sync``: a plain N-way averaging barrier. Submissions accumulate
  until every live replica has contributed, the trees are averaged
  (sole contributor: adopted exactly), published once, and all waiters
  release. A replica fenced mid-round is dropped from the barrier so a
  kill never wedges the survivors.

**Fencing** (the PR-7 idiom at replica granularity): every replica is
registered with an **epoch**; ``fence_replica`` bumps it, so an
in-flight update from a killed replica — stamped with the dead epoch —
is counted and discarded on arrival, never applied. The published
version stream cannot rewind: versions come from ``WeightStore.publish``
(monotone by construction) and the ledger oracle double-checks it.

Locking: everything lives under ONE declared-tier condition
(``agg`` = 34 > ``wstore`` = 24 — publishing while holding it descends;
a replica may hold its ``replica``-tier lock while submitting). The
aggregator registers the obs registry's ``learner`` provider:
per-replica lag/epoch/fence tallies, clip rate, staleness percentiles.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Optional

import jax
import numpy as np

from d4pg_tpu.core.locking import TieredCondition
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import REGISTRY, percentile_summary

_tree_map = jax.tree_util.tree_map

MODES = ("async", "sync")


def _blend(cur: np.ndarray, new: np.ndarray, w: float) -> np.ndarray:
    """One leaf of the stale-update correction, dtype-preserving."""
    cur = np.asarray(cur)
    out = cur + np.asarray(w, dtype=np.float32) * (np.asarray(new) - cur)
    return out.astype(cur.dtype, copy=False)


class Aggregator:
    """Merges per-replica updates into one versioned ``WeightStore``.

    ``extract`` maps the merged tree to what the store publishes (e.g.
    ``lambda t: t["actor"]`` when replicas submit actor+critic trees —
    actors only pull acting params); default publishes the whole tree.
    ``norm_stats`` is the optional obs-normalizer snapshot hook the
    legacy publish path threads through (``train._norm_snapshot``)."""

    def __init__(
        self,
        store,
        *,
        mode: str = "async",
        clip: float = 8.0,
        extract: Optional[Callable[[Any], Any]] = None,
        norm_stats: Optional[Callable[[], tuple | None]] = None,
        sync_timeout: float = 30.0,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown aggregation mode {mode!r}")
        if clip < 1.0:
            raise ValueError(
                f"clip={clip} would weight stale updates ABOVE fresh ones; "
                "the bound is a floor 1/clip <= 1, so clip >= 1")
        self._store = store
        self.mode = mode
        self.clip = float(clip)
        self._extract = extract
        self._norm_stats = norm_stats
        self._sync_timeout = float(sync_timeout)
        self._agg_cond = TieredCondition("agg")
        # -- merge state (all under _agg_cond) ------------------------------
        self._params: Any = None
        self._version = int(getattr(store, "version", 0))
        self._step = 0
        self._epochs: dict[int, int] = {}       # live epoch per replica
        self._next_epoch: dict[int, int] = {}   # monotone per replica id
        self._per_replica: dict[int, dict] = {}
        self._lags: deque = deque(maxlen=4096)
        self._applied = 0
        self._fenced = 0
        self._clipped = 0
        self._ledger: list[tuple[int, int]] = []  # published (gen, version)
        # -- sync barrier ----------------------------------------------------
        self._round: dict[int, tuple] = {}       # id -> (params, basis, step)
        self._round_seq = 0
        self._sync_results: dict[int, dict] = {}
        REGISTRY.register_provider("learner", self._snapshot)

    # -- replica lifecycle ---------------------------------------------------
    def register(self, replica_id: int, params: Any = None,
                 step: int = 0) -> int:
        """Admit (or re-admit after a kill) a replica; returns its live
        epoch. The FIRST registration may seed the aggregate with the
        replica's initial params (version 0 basis) so ``basis()`` has
        something to serve before any submit lands."""
        with self._agg_cond:
            epoch = self._next_epoch.get(replica_id, 0) + 1
            self._next_epoch[replica_id] = epoch
            self._epochs[replica_id] = epoch
            stats = self._per_replica.setdefault(
                replica_id, {"submits": 0, "fenced": 0, "lag": None,
                             "weight": None, "last_version": 0})
            stats["epoch"] = epoch
            if params is not None and self._params is None:
                self._params = params
                self._step = int(step)
            self._maybe_complete_round_locked()
            self._agg_cond.notify_all()
            return epoch

    def fence_replica(self, replica_id: int) -> None:
        """Kill-path fence: bump the replica out of its epoch so any
        in-flight contribution it had on the wire is discarded on
        arrival (counted, never applied), and drop it from a pending
        sync barrier so the survivors' round can complete."""
        with self._agg_cond:
            self._epochs.pop(replica_id, None)
            self._round.pop(replica_id, None)
            record_event("replica_fenced", replica=replica_id)
            self._maybe_complete_round_locked()
            self._agg_cond.notify_all()

    def live_epoch(self, replica_id: int) -> Optional[int]:
        """The replica's live epoch, or None once fenced — the wire
        server's zero-decode header check reads this before paying for
        payload decode."""
        with self._agg_cond:
            return self._epochs.get(replica_id)

    # -- basis pulls ---------------------------------------------------------
    def current(self) -> tuple[int, Any]:
        """(version, merged params) — params None before any seed."""
        with self._agg_cond:
            return self._version, self._params

    def basis(self, replica_id: int) -> tuple[int, Any]:
        """The basis a replica should compute its next update against.
        Returns ``(version, params)`` with ``params=None`` when nothing
        newer than the replica's OWN last applied submission exists —
        the sole-replica case, where re-adopting its own round-tripped
        params would break bitwise equivalence with the legacy loop."""
        with self._agg_cond:
            stats = self._per_replica.get(replica_id)
            last = stats["last_version"] if stats else 0
            if self._params is None or self._version <= last:
                return self._version, None
            return self._version, self._params

    # -- submission ----------------------------------------------------------
    def submit(self, replica_id: int, epoch: int, params: Any,
               basis_version: int, step: int = 0,
               generation: int | None = None) -> dict:
        """Merge one replica update computed against ``basis_version``.
        Returns ``{"status": "applied"|"fenced", "version", "lag",
        "weight", "clipped"}`` (sync mode blocks until the barrier
        round completes or times out)."""
        with self._agg_cond:
            stats = self._per_replica.setdefault(
                replica_id, {"submits": 0, "fenced": 0, "lag": None,
                             "weight": None, "last_version": 0})
            live = self._epochs.get(replica_id)
            if live != epoch or (generation is not None and
                                 generation != self._store.generation):
                self._fenced += 1
                stats["fenced"] += 1
                record_event("update_fenced", replica=replica_id,
                             epoch=epoch, live_epoch=live)
                return {"status": "fenced", "version": self._version,
                        "lag": None, "weight": 0.0, "clipped": False}
            lag = self._version - int(basis_version)
            if lag < 0:
                # basis from the future: protocol breach (a replica can
                # only have pulled a version this aggregator published)
                self._fenced += 1
                stats["fenced"] += 1
                return {"status": "fenced", "version": self._version,
                        "lag": lag, "weight": 0.0, "clipped": False}
            if self.mode == "sync":
                return self._submit_sync_locked(
                    replica_id, params, lag, step, stats)
            raw_w = 1.0 / (1.0 + lag)
            w = max(raw_w, 1.0 / self.clip)
            clipped = raw_w < w
            if clipped:
                self._clipped += 1
            if lag == 0 or self._params is None:
                # exact identity fast-path (bitwise — see module doc)
                self._params = params
            else:
                self._params = _tree_map(
                    lambda c, n: _blend(c, n, w), self._params, params)
            self._step = int(step)
            version = self._publish_locked()
            self._applied += 1
            self._lags.append(float(lag))
            stats["submits"] += 1
            stats["lag"] = lag
            stats["weight"] = round(w, 6)
            stats["last_version"] = version
            return {"status": "applied", "version": version, "lag": lag,
                    "weight": w, "clipped": clipped}

    def _submit_sync_locked(self, replica_id: int, params: Any, lag: int,
                            step: int, stats: dict) -> dict:
        self._round[replica_id] = (params, lag, int(step))
        seq = self._round_seq
        self._maybe_complete_round_locked()
        deadline_ok = self._agg_cond.wait_for(
            lambda: self._round_seq != seq
            or self._epochs.get(replica_id) is None,
            timeout=self._sync_timeout)
        if self._epochs.get(replica_id) is None:
            self._fenced += 1
            stats["fenced"] += 1
            return {"status": "fenced", "version": self._version,
                    "lag": lag, "weight": 0.0, "clipped": False}
        if not deadline_ok:
            # leave the contribution staged; a late barrier can still
            # complete it, but this caller reports the stall
            return {"status": "barrier_timeout", "version": self._version,
                    "lag": lag, "weight": 0.0, "clipped": False}
        return self._sync_results.pop(replica_id)

    def _maybe_complete_round_locked(self) -> None:
        if (self.mode != "sync" or not self._epochs
                or not self._round
                or set(self._round) < set(self._epochs)):
            return
        contributions = [self._round[rid] for rid in sorted(self._round)]
        n = len(contributions)
        if n == 1:
            merged = contributions[0][0]  # sole contributor: exact
        else:
            merged = _tree_map(
                lambda *leaves: (
                    np.sum(np.stack([np.asarray(x) for x in leaves], 0),
                           axis=0, dtype=np.float64) / n
                ).astype(np.asarray(leaves[0]).dtype),
                *[c[0] for c in contributions])
        self._params = merged
        self._step = max(c[2] for c in contributions)
        version = self._publish_locked()
        self._applied += n
        w = 1.0 / n
        for rid in list(self._round):
            _params, lag, _step = self._round.pop(rid)
            st = self._per_replica[rid]
            st["submits"] += 1
            st["lag"] = lag
            st["weight"] = round(w, 6)
            st["last_version"] = version
            self._lags.append(float(lag))
            self._sync_results[rid] = {
                "status": "applied", "version": version, "lag": lag,
                "weight": w, "clipped": False}
        self._round_seq += 1
        self._agg_cond.notify_all()

    def _publish_locked(self) -> int:
        pub = self._extract(self._params) if self._extract else self._params
        norm = self._norm_stats() if self._norm_stats else None
        # holding _agg_cond (34) while taking _store_lock (24): descends
        version = self._store.publish(pub, step=self._step, to_host=False,
                                      norm_stats=norm)
        self._version = version
        self._ledger.append((self._store.generation, version))
        return version

    # -- oracles / obs -------------------------------------------------------
    @property
    def version(self) -> int:
        with self._agg_cond:
            return self._version

    def ledger(self) -> list[tuple[int, int]]:
        with self._agg_cond:
            return list(self._ledger)

    def ledger_monotone(self) -> bool:
        """The never-rewinds oracle: across everything this aggregator
        ever published, generation never decreases and version strictly
        increases within a generation."""
        prev = (-1, -1)
        for gen, version in self.ledger():
            if gen < prev[0] or (gen == prev[0] and version <= prev[1]):
                return False
            prev = (gen, version)
        return True

    def counters(self) -> dict:
        with self._agg_cond:
            return {"applied": self._applied, "fenced": self._fenced,
                    "clipped": self._clipped,
                    "published": len(self._ledger)}

    def _snapshot(self) -> dict:
        """obs registry ``learner`` provider: per-replica lag + fence
        tallies, clip rate, staleness percentiles. Same consistency
        contract as every provider — one pass under the owner's lock."""
        with self._agg_cond:
            applied = self._applied
            return {
                "mode": self.mode,
                "clip": self.clip,
                "version": self._version,
                "replicas": {
                    str(rid): dict(stats)
                    for rid, stats in self._per_replica.items()},
                "live_replicas": len(self._epochs),
                "applied": applied,
                "fenced": self._fenced,
                "clip_rate": (round(self._clipped / applied, 4)
                              if applied else 0.0),
                "staleness": percentile_summary(list(self._lags)),
            }

    def close(self) -> None:
        REGISTRY.unregister_provider("learner", self._snapshot)
