"""D4PG train state: one pytree carrying everything the update needs.

Replaces the reference's scattered mutable state — actor/critic + target
copies as four nn.Modules (``ddpg.py:57-64``), two (dead) local Adams
(``ddpg.py:67-68``), the global ``SharedAdam`` pair living in OS shared
memory (``shared_adam.py:3-17``, ``main.py:384-385``), and the shared step
counter (``main.py:386``) — with a single immutable pytree that is donated
through the jit'd update and checkpointed atomically by Orbax.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import Array

from d4pg_tpu.core.distribution import CategoricalSupport
from d4pg_tpu.core.updates import hard_update, tie_encoder
from d4pg_tpu.models.actor import Actor
from d4pg_tpu.models.critic import CategoricalCritic, MixtureOfGaussianCritic
from d4pg_tpu.models.encoder import PixelActor, PixelCategoricalCritic


@dataclasses.dataclass(frozen=True)
class D4PGConfig:
    """Static (hashable) configuration closed over by the jit'd update.

    Defaults mostly mirror the reference's (``main.py:33-49``,
    ``ddpg.py:81-87``): tau 0.001, gamma 0.99, 51 atoms. DOCUMENTED
    DIVERGENCE: the reference runs Adam with betas (0.9, 0.9) at lr 1e-3
    (``shared_adam.py:4``, ``main.py:384``). The fast-decaying second moment
    makes effective steps so large the tanh actor slams into saturation and
    its gradient vanishes (verified: on a known-optimum bandit the actor
    sticks at a=1.0 and never recovers; with b2=0.999 it converges). We
    default to standard b2=0.999 and actor lr 1e-4; set
    ``adam_b2=0.9, lr_actor=1e-3`` for strict reference parity.
    ``critic_family`` selects the distribution head: 'categorical' (live in
    the reference) or 'mog' (its empty TODO stub, implemented for real
    here).
    """

    obs_dim: int
    act_dim: int
    v_min: float = -300.0
    v_max: float = 0.0
    n_atoms: int = 51
    hidden: Sequence[int] = (256, 256, 256)
    critic_family: str = "categorical"  # 'categorical' | 'mog'
    n_components: int = 5  # MoG components
    lr_actor: float = 1e-4
    lr_critic: float = 1e-3
    adam_b1: float = 0.9
    adam_b2: float = 0.999
    tau: float = 0.001
    gamma: float = 0.99
    # HER-recipe action-L2 penalty coefficient on the actor loss (0 = the
    # reference's plain expected-Q objective)
    action_l2: float = 0.0
    pixels: bool = False  # conv-encoder path (BASELINE.md config #4)
    obs_shape: tuple = ()  # [H, W, C] when pixels=True
    encoder_channels: tuple = (32, 32, 32, 32)  # conv widths (pixels only)
    # batch augmentation inside the jit'd update (pixels only): 'none' or
    # 'shift' (DrQ random shift, ops/augment.py — the standard antidote to
    # conv-encoder overfitting at small replay scales)
    augment: str = "none"
    augment_pad: int = 4  # DrQ's +-4px shift radius
    # Share the conv encoder between critic and actor (pixels only): the
    # encoder is trained by the CRITIC loss alone; the actor consumes it
    # through a stop-gradient and its own encoder subtree is hard-tied to
    # the critic's after every critic step. This is the SAC-AE/DrQ result
    # that makes pixel control work at small data scales — actor-gradient
    # -trained conv encoders optimize their losses while greedy returns
    # stay at the random-policy level (measured: docs/evidence/dmc-pixels/).
    # Param-tree layout is unchanged (the actor still CARRIES an encoder
    # subtree, it is just tied), so acting, weight publishing, checkpoints
    # and resume are oblivious; a run can even flip the flag mid-stream.
    share_encoder: bool = False
    mog_samples: int = 32
    # MXU compute dtype for the network matmuls ('float32' | 'bfloat16').
    # Params, optimizer state, losses and the projection stay float32;
    # bf16 matmuls measure ~1.5x the fused-dispatch update throughput.
    compute_dtype: str = "float32"
    # Categorical-projection implementation: 'einsum' (dense MXU
    # interpolation-weight matmul, core/distribution.py — the default; XLA
    # fuses it fully on-chip), 'pallas' (the VMEM-resident projection
    # kernel, ops/projection.py — measured ~1.2-1.7x slower at A=51
    # because pallas_call dispatch dominates at this op size), or
    # 'pallas_ce' (projection FUSED into the cross-entropy reduction with
    # a custom VJP, ops/projection_ce.py — removes the proj round trip in
    # both passes; see README "Projection kernels"). Categorical family
    # only; ignored by MoG. This field is jit-static and must be CONCRETE:
    # the experiment-level '--projection auto' default resolves to one of
    # these via the startup micro-autotuner BEFORE building this config
    # (config.ExperimentConfig.learner_config -> ops/autotune.py).
    projection: str = "einsum"

    def __post_init__(self):
        object.__setattr__(self, "hidden", tuple(self.hidden))
        object.__setattr__(self, "obs_shape", tuple(self.obs_shape))
        object.__setattr__(self, "encoder_channels",
                           tuple(self.encoder_channels))
        if self.critic_family not in ("categorical", "mog"):
            raise ValueError(f"unknown critic_family {self.critic_family!r}")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"unknown compute_dtype {self.compute_dtype!r}")
        if self.projection not in ("einsum", "pallas", "pallas_ce"):
            raise ValueError(f"unknown projection {self.projection!r}")
        if self.augment not in ("none", "shift"):
            raise ValueError(f"unknown augment {self.augment!r}")
        if self.augment != "none" and not self.pixels:
            raise ValueError(
                "--augment is an image augmentation; it requires the "
                "pixel (conv-encoder) observation path")
        if self.augment != "none" and self.augment_pad < 1:
            raise ValueError(
                f"--augment {self.augment} with augment_pad="
                f"{self.augment_pad} would silently train UNaugmented; "
                "set a positive shift radius (or --augment none)")
        if self.share_encoder and not (
                self.pixels and self.critic_family == "categorical"):
            raise ValueError(
                "--share_encoder ties the actor's conv encoder to the "
                "critic's; it requires the pixel path with the "
                "categorical critic")

    @property
    def _dtype(self):
        return jnp.bfloat16 if self.compute_dtype == "bfloat16" else jnp.float32

    @property
    def support(self) -> CategoricalSupport:
        return CategoricalSupport(self.v_min, self.v_max, self.n_atoms)

    @property
    def obs_spec(self) -> int | tuple:
        """Replay/folder storage spec: [H, W, C] for pixels, else obs_dim."""
        return tuple(self.obs_shape) if self.pixels else self.obs_dim

    def build_actor(self) -> nn.Module:
        if self.pixels:
            # share_encoder => the policy loss must not train the (tied)
            # encoder: stop the gradient at the latent. Same param tree.
            return PixelActor(self.act_dim, channels=self.encoder_channels,
                              hidden=self.hidden, dtype=self._dtype,
                              detach_encoder=self.share_encoder)
        return Actor(self.act_dim, hidden=self.hidden, dtype=self._dtype)

    def build_critic(self) -> nn.Module:
        if self.critic_family == "mog":
            return MixtureOfGaussianCritic(
                self.n_components, hidden=self.hidden, dtype=self._dtype
            )
        if self.pixels:
            return PixelCategoricalCritic(
                self.n_atoms, channels=self.encoder_channels,
                hidden=self.hidden, dtype=self._dtype
            )
        return CategoricalCritic(self.n_atoms, hidden=self.hidden, dtype=self._dtype)

    def optimizer(self, lr: float) -> optax.GradientTransformation:
        return optax.adam(lr, b1=self.adam_b1, b2=self.adam_b2)

    def dummy_obs(self) -> Array:
        shape = self.obs_shape if self.pixels else (self.obs_dim,)
        return jnp.zeros((1,) + tuple(shape), jnp.float32)


class D4PGState(NamedTuple):
    """The complete learner state; a pure pytree (jit/donate/checkpoint-able)."""

    actor_params: Any
    critic_params: Any
    target_actor_params: Any
    target_critic_params: Any
    actor_opt_state: Any
    critic_opt_state: Any
    key: Array  # PRNG key threaded through MoG sampling / any stochastic op
    step: Array  # int32 learner step counter (replaces shared global_count)


def init_state(config: D4PGConfig, key: Array) -> D4PGState:
    """Initialize networks, targets (hard-copied, ``ddpg.py:92-94``) and
    optimizer states."""
    k_actor, k_critic, k_state = jax.random.split(key, 3)
    obs = config.dummy_obs()
    act = jnp.zeros((1, config.act_dim), jnp.float32)
    actor_params = config.build_actor().init(k_actor, obs)
    critic_params = config.build_critic().init(k_critic, obs, act)
    if config.share_encoder:
        # the tie holds from step 0: otherwise the target actor starts as
        # a hard copy of an UNRELATED random encoder and the mismatch only
        # decays at (1-tau)^t through the soft updates (~thousands of
        # early bootstrap targets through a wrong encoder/MLP pairing)
        actor_params = tie_encoder(actor_params, critic_params)
    return D4PGState(
        actor_params=actor_params,
        critic_params=critic_params,
        target_actor_params=hard_update(None, actor_params),
        target_critic_params=hard_update(None, critic_params),
        actor_opt_state=config.optimizer(config.lr_actor).init(actor_params),
        critic_opt_state=config.optimizer(config.lr_critic).init(critic_params),
        key=k_state,
        step=jnp.zeros((), jnp.int32),
    )
