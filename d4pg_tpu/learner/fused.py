"""Fully-fused replay+learn chunk: K grad steps in ONE device dispatch.

The hot-loop endgame of the TPU redesign. The reference's per-step
protocol (``ddpg.py:200-255``) is sample -> nets -> projection -> Adam ->
priority write-back, with the replay machinery on the host. The
host-pipelined chunk path (``learner/pipeline.py``) already overlaps host
sampling with device compute, but still pays per-chunk dispatches and
host<->device latency — which dominates on a tunneled/PCIe-attached
accelerator (measured: ~1-3 ms per dispatch, ~60 ms per blocking sync,
vs ~15 us of per-step compute).

With the transition ring (``replay/device_ring.py``) AND the PER trees
(``replay/device_per.py``) resident in HBM, the whole protocol becomes
pure jnp inside one ``lax.scan``:

    per step: stratified PER sample -> ring gather -> IS weights ->
              D4PG update -> priority write-back

so one dispatch carries K full steps with ZERO host round trips and ZERO
priority staleness (fresher than the reference: within a chunk, step
t+1's sampling distribution already reflects step t's TD errors — the
host-pipelined path bounds staleness at ~2K instead). The host's only
jobs left are draining actor transitions into the ring between chunks
and fetching metrics when it wants them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.learner.update import update_step
from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.uniform import TransitionBatch


def fused_chunk_step(
    config: D4PGConfig,
    state: D4PGState,
    trees: dper.PerTrees | None,
    storage: TransitionBatch,
    size,
    *,
    k: int,
    batch_size: int,
    alpha: float = 0.6,
    beta0: float = 0.4,
    beta_steps: int = 100_000,
):
    """K fused sample+update steps. Pure; jit via :func:`make_fused_chunk`.

    ``trees=None`` compiles the uniform-replay variant (device-side
    ``randint`` sampling, no IS weights). ``storage`` is the device ring's
    [capacity, ...] arrays; ``size`` the live row count (traced int32).

    Returns ``(state, trees, metrics)`` with per-step metrics stacked [K]
    (plus ``td_error``/``idx`` [K, B] for observability and the priority
    tests).
    """

    def body(carry, _):
        state, trees = carry
        k_sample, k_rest = jax.random.split(state.key)
        state = state._replace(key=k_rest)
        if trees is not None:
            idx = dper.sample(trees, k_sample, batch_size, size)
            beta = dper.beta_schedule(state.step, beta0, beta_steps)
            w = dper.is_weights(trees, idx, beta, size)
        else:
            idx = jax.random.randint(k_sample, (batch_size,), 0,
                                     jnp.maximum(size, 1))
            w = None
        batch = TransitionBatch(*[arr[idx] for arr in storage])
        state, metrics = update_step(config, state, batch, w)
        if trees is not None:
            trees = dper.update_from_td(trees, idx, metrics["td_error"],
                                        alpha)
        metrics["idx"] = idx
        return (state, trees), metrics

    (state, trees), metrics = jax.lax.scan(
        body, (state, trees), None, length=k)
    return state, trees, metrics


def make_fused_chunk(
    config: D4PGConfig,
    *,
    k: int,
    batch_size: int,
    prioritized: bool = True,
    alpha: float = 0.6,
    beta0: float = 0.4,
    beta_steps: int = 100_000,
    donate: bool = True,
):
    """jit the fused chunk. PER: ``fn(state, trees, storage, size) ->
    (state, trees, metrics)``; uniform: ``fn(state, storage, size) ->
    (state, metrics)``. ``state`` and ``trees`` are donated (updated in
    place in HBM); the ring is read-only and never copied."""
    if prioritized:
        def fn(state, trees, storage, size):
            return fused_chunk_step(
                config, state, trees, storage, size, k=k,
                batch_size=batch_size, alpha=alpha, beta0=beta0,
                beta_steps=beta_steps)

        return jax.jit(fn, donate_argnums=(0, 1) if donate else ())

    def fn(state, storage, size):
        state, _, metrics = fused_chunk_step(
            config, state, None, storage, size, k=k, batch_size=batch_size)
        return state, metrics

    return jax.jit(fn, donate_argnums=(0,) if donate else ())
