"""Fully-fused replay+learn chunk: K grad steps in ONE device dispatch.

The hot-loop endgame of the TPU redesign. The reference's per-step
protocol (``ddpg.py:200-255``) is sample -> nets -> projection -> Adam ->
priority write-back, with the replay machinery on the host. The
host-pipelined chunk path (``learner/pipeline.py``) already overlaps host
sampling with device compute, but still pays per-chunk dispatches and
host<->device latency — which dominates on a tunneled/PCIe-attached
accelerator (measured: ~1-3 ms per dispatch, ~60 ms per blocking sync,
vs ~15 us of per-step compute).

With the transition ring (``replay/device_ring.py``) AND the PER trees
(``replay/device_per.py``) resident in HBM, the whole protocol becomes
pure jnp inside one ``lax.scan``:

    per step: stratified PER sample -> ring gather -> IS weights ->
              D4PG update -> priority write-back

so one dispatch carries K full steps with ZERO host round trips and ZERO
priority staleness (fresher than the reference: within a chunk, step
t+1's sampling distribution already reflects step t's TD errors — the
host-pipelined path bounds staleness at (depth+1)K instead). The host's only
jobs left are draining actor transitions into the ring between chunks
and fetching metrics when it wants them.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.learner.update import update_step
from d4pg_tpu.replay import device_per as dper
from d4pg_tpu.replay.uniform import TransitionBatch


def fused_chunk_step(
    config: D4PGConfig,
    state: D4PGState,
    trees: dper.PerTrees | None,
    storage: TransitionBatch,
    size,
    *,
    k: int,
    batch_size: int,
    alpha: float = 0.6,
    beta0: float = 0.4,
    beta_steps: int = 100_000,
):
    """K fused sample+update steps. Pure; jit via :func:`make_fused_chunk`.

    ``trees=None`` compiles the uniform-replay variant (device-side
    ``randint`` sampling, no IS weights). ``storage`` is the device ring's
    [capacity, ...] arrays; ``size`` the live row count (traced int32).

    Returns ``(state, trees, metrics)`` with per-step metrics stacked [K]
    (plus ``td_error``/``idx`` [K, B] for observability and the priority
    tests).
    """

    def body(carry, _):
        state, trees = carry
        k_sample, k_rest = jax.random.split(state.key)
        state = state._replace(key=k_rest)
        if trees is not None:
            idx = dper.sample(trees, k_sample, batch_size, size)
            beta = dper.beta_schedule(state.step, beta0, beta_steps)
            w = dper.is_weights(trees, idx, beta, size)
        else:
            idx = jax.random.randint(k_sample, (batch_size,), 0,
                                     jnp.maximum(size, 1))
            w = None
        batch = TransitionBatch(*[arr[idx] for arr in storage])
        state, metrics = update_step(config, state, batch, w)
        if trees is not None:
            trees = dper.update_from_td(trees, idx, metrics["td_error"],
                                        alpha)
        metrics["idx"] = idx
        return (state, trees), metrics

    (state, trees), metrics = jax.lax.scan(
        body, (state, trees), None, length=k)
    return state, trees, metrics


def make_fused_chunk(
    config: D4PGConfig,
    *,
    k: int,
    batch_size: int,
    prioritized: bool = True,
    alpha: float = 0.6,
    beta0: float = 0.4,
    beta_steps: int = 100_000,
    donate: bool = True,
):
    """jit the fused chunk. PER: ``fn(state, trees, storage, size) ->
    (state, trees, metrics)``; uniform: ``fn(state, storage, size) ->
    (state, metrics)``. ``state`` and ``trees`` are donated (updated in
    place in HBM); the ring is read-only and never copied."""
    if prioritized:
        def fn(state, trees, storage, size):
            return fused_chunk_step(
                config, state, trees, storage, size, k=k,
                batch_size=batch_size, alpha=alpha, beta0=beta0,
                beta_steps=beta_steps)

        return jax.jit(fn, donate_argnums=(0, 1) if donate else ())

    def fn(state, storage, size):
        state, _, metrics = fused_chunk_step(
            config, state, None, storage, size, k=k, batch_size=batch_size)
        return state, metrics

    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def make_sharded_fused_chunk(
    config: D4PGConfig,
    mesh,
    *,
    k: int,
    batch_size: int,
    prioritized: bool = True,
    alpha: float = 0.6,
    beta0: float = 0.4,
    beta_steps: int = 100_000,
    donate: bool = True,
):
    """The fused chunk over a data-parallel mesh — the production
    configuration with the replay data plane ON the mesh.

    Rejects ``projection='pallas'`` (no GSPMD partitioning rule — mesh
    learners use the einsum formulation, which shards trivially).

    Storage/trees come from ``replay/sharded_per.ShardedFusedReplay``
    (leading axis = shard, sharded over ``data``). Per step, a
    ``shard_map`` prologue lets every device sample B/N rows from ITS
    ring shard (stratified across shards by construction) and compute IS
    weights with a GLOBAL max-weight normalizer (``lax.pmin`` over the
    data axis — per-shard normalizers would bias gradient scale, the
    same correction the multi-host host-tree path makes with its
    allgather). The update itself is the ordinary ``update_step`` under
    GSPMD: the batch emerges from the prologue already sharded
    ``P('data')``, so the loss mean turns into the usual ICI all-reduce.
    A second ``shard_map`` writes each shard's TD errors back into its
    own trees. Batch rows never cross devices; only gradients do.

    PER: ``fn(state, trees, storage, size) -> (state, trees, metrics)``;
    uniform: ``fn(state, storage, size) -> (state, metrics)``. ``size``
    is the per-shard live-row count [n_shards].
    """
    from d4pg_tpu.parallel.compat import shard_map

    from d4pg_tpu.parallel import partition
    from d4pg_tpu.parallel.data_parallel import check_mesh_compatible
    from d4pg_tpu.parallel.mesh import DATA_AXIS
    from d4pg_tpu.replay.sharded_per import ShardedPerTrees

    check_mesh_compatible(config)

    n_shards = int(mesh.shape[DATA_AXIS])
    if batch_size % n_shards:
        raise ValueError(
            f"batch_size {batch_size} not divisible by data axis {n_shards}")
    b_local = batch_size // n_shards
    Pd, Pr = partition.data_spec(), partition.replicated_spec()

    def _local_trees(trees):
        return dper.PerTrees(trees.sum_tree[0], trees.min_tree[0],
                             trees.max_priority[0])

    def _local_sample_per(trees, storage, size, key, beta):
        ax = jax.lax.axis_index(DATA_AXIS)
        t = _local_trees(trees)
        idx = dper.sample(t, jax.random.fold_in(key, ax), b_local, size[0])
        batch = TransitionBatch(*[arr[0][idx] for arr in storage])
        # per-draw probability of row i: q_i = (1/N_shards) * p_i/total_h.
        # The reference weight is (N_rows * q)^-beta / (N_rows * q_min)^-beta
        # — N_rows cancels, so no psum of sizes is needed; only the global
        # minimum per-draw probability crosses shards (one pmin scalar).
        total = jnp.maximum(t.sum_tree[1], 1e-30)
        q = t.sum_tree[t.capacity + idx] / total / n_shards
        q_min = jax.lax.pmin(t.min_tree[1] / total / n_shards, DATA_AXIS)
        w = (q / q_min) ** (-beta)
        return batch, w.astype(jnp.float32), idx.astype(jnp.int32)

    def _local_sample_uniform(storage, size, key):
        ax = jax.lax.axis_index(DATA_AXIS)
        idx = jax.random.randint(
            jax.random.fold_in(key, ax), (b_local,), 0,
            jnp.maximum(size[0], 1))
        batch = TransitionBatch(*[arr[0][idx] for arr in storage])
        return batch, idx.astype(jnp.int32)

    def _local_write_back(trees, idx, td):
        t = dper.update_from_td(_local_trees(trees), idx, td, alpha)
        return ShardedPerTrees(t.sum_tree[None], t.min_tree[None],
                               t.max_priority[None])

    sample_per = shard_map(
        _local_sample_per, mesh=mesh,
        in_specs=(Pd, Pd, Pd, Pr, Pr), out_specs=(Pd, Pd, Pd),
        check_vma=False)
    sample_uniform = shard_map(
        _local_sample_uniform, mesh=mesh,
        in_specs=(Pd, Pd, Pr), out_specs=(Pd, Pd), check_vma=False)
    write_back = shard_map(
        _local_write_back, mesh=mesh,
        in_specs=(Pd, Pd, Pd), out_specs=Pd, check_vma=False)

    def chunk(state, trees, storage, size):
        def body(carry, _):
            state, trees = carry
            k_sample, k_rest = jax.random.split(state.key)
            state = state._replace(key=k_rest)
            if prioritized:
                beta = dper.beta_schedule(state.step, beta0, beta_steps)
                batch, w, idx = sample_per(trees, storage, size,
                                           k_sample, beta)
            else:
                batch, idx = sample_uniform(storage, size, k_sample)
                w = None
            state, metrics = update_step(config, state, batch, w)
            if prioritized:
                trees = write_back(trees, idx, metrics["td_error"])
            metrics["idx"] = idx
            return (state, trees), metrics

        (state, trees), metrics = jax.lax.scan(
            body, (state, trees), None, length=k)
        return state, trees, metrics

    repl = partition.replicated(mesh)
    shard = partition.batch_sharding(mesh)
    state_sh = partition.state_shardings(config, mesh)
    out_metrics_shard = partition.stacked_sharding(mesh)
    out_metrics = {
        "critic_loss": repl, "actor_loss": repl, "q_mean": repl,
        "td_error": out_metrics_shard, "idx": out_metrics_shard,
    }
    if prioritized:
        return jax.jit(
            chunk,
            in_shardings=(state_sh, shard, shard, shard),
            out_shardings=(state_sh, shard, out_metrics),
            donate_argnums=(0, 1) if donate else (),
        )

    def chunk_u(state, storage, size):
        state, _, metrics = chunk(state, None, storage, size)
        return state, metrics

    return jax.jit(
        chunk_u,
        in_shardings=(state_sh, shard, shard),
        out_shardings=(state_sh, {"critic_loss": repl, "actor_loss": repl,
                                  "q_mean": repl,
                                  "td_error": out_metrics_shard,
                                  "idx": out_metrics_shard}),
        donate_argnums=(0,) if donate else (),
    )
