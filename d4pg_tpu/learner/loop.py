"""The fused training loop, extracted from ``train.py``.

One class owns the commit -> dispatch -> stage schedule that used to
live inline in ``train.train_steps_fused`` so the legacy single-learner
path and the multi-learner ``LearnerReplica`` (``learner/replica.py``)
run the SAME implementation instead of a fork — which is what makes the
N=1-replica ⇔ legacy-loop bitwise-equivalence oracle a property of the
code structure rather than a test that merely passed once.

Schedule per fused chunk t (``learner/pipeline.IngestOverlap``):

    ingest.commit()     # block t's ring write+tree insert (async jitted
                        # dispatch, no transfer)
    dispatch chunk t    # K scanned grad steps in ONE device dispatch
    ingest.stage()      # ONE device_put of block t+1, riding under
                        # chunk t's compute
    trace mark_grad     # traces committed before this dispatch are now
                        # consumed (wire-to-grad span terminal)

giving ≤ 1 explicit H2D per chunk in steady state. The jitted chunk
fns are cached per remainder size k (the final sub-K chunk of an ``n``
not divisible by K compiles once and is reused).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from d4pg_tpu.learner.pipeline import IngestOverlap
from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.obs.trace import RECORDER as _trace_recorder


class FusedLoop:
    """Drives fused replay+learn chunks against a device-resident buffer.

    ``buffer`` is a ``FusedDeviceReplay``/``ShardedFusedReplay`` (needs
    ``.storage``, ``.size`` and — prioritized — ``.trees``). ``service``
    is the owning ``ReplayService`` when actor rows stream in between
    chunks (the loop claims the service's single ingest-dispatch slot
    via ``IngestOverlap``); ``None`` runs the loop against a statically
    filled buffer (tests, the N=1 oracle)."""

    def __init__(
        self,
        config: D4PGConfig,
        buffer,
        *,
        k: int,
        batch_size: int,
        prioritized: bool = True,
        alpha: float = 0.6,
        beta0: float = 0.4,
        beta_steps: int = 100_000,
        mesh=None,
        service=None,
        donate: bool = True,
    ):
        self._config = config
        self._buffer = buffer
        self.k = max(1, int(k))
        self._batch_size = int(batch_size)
        self._prioritized = bool(prioritized)
        self._alpha = float(alpha)
        self._beta0 = float(beta0)
        self._beta_steps = int(beta_steps)
        self._mesh = mesh
        self._donate = bool(donate)
        self._fns: dict[int, object] = {}
        self.ingest = IngestOverlap(service) if service is not None else None
        self.steps_done = 0
        self.chunks = 0

    def fused_for(self, k: int):
        """The jitted fused-chunk fn for chunk length ``k`` (cached)."""
        if k not in self._fns:
            from d4pg_tpu.learner.fused import (
                make_fused_chunk,
                make_sharded_fused_chunk,
            )

            kwargs = dict(
                k=k, batch_size=self._batch_size,
                prioritized=self._prioritized, alpha=self._alpha,
                beta0=self._beta0, beta_steps=self._beta_steps,
                donate=self._donate)
            self._fns[k] = (
                make_sharded_fused_chunk(self._config, self._mesh, **kwargs)
                if self._mesh is not None
                else make_fused_chunk(self._config, **kwargs))
        return self._fns[k]

    def run(
        self,
        state: D4PGState,
        n: int,
        on_chunk: Optional[Callable[[D4PGState, int], None]] = None,
    ):
        """``n`` fused grad steps from ``state``; returns ``(state,
        metrics)`` with the LAST chunk's metrics stacked [k] (``None``
        when ``n <= 0``). ``on_chunk(state, k)`` fires after each
        dispatch — step accounting and weight publishing live with the
        caller, which is what lets the legacy path and a replica share
        this loop while publishing through different stores."""
        buffer = self._buffer
        metrics = None
        done = 0
        if self.ingest is not None:
            # cycle boundary: every staged row lands before training
            self.ingest.flush()
        while done < n:
            k = min(self.k, n - done)
            fn = self.fused_for(k)
            if self.ingest is not None:
                self.ingest.commit()
            if self._prioritized:
                state, buffer.trees, metrics = fn(
                    state, buffer.trees, buffer.storage, buffer.size)
            else:
                state, metrics = fn(state, buffer.storage, buffer.size)
            if self.ingest is not None:
                self.ingest.stage()
            # traces whose rows committed before this dispatch are now
            # consumed; near-free no-op when nothing is pending
            _trace_recorder.mark_grad()
            done += k
            self.steps_done += k
            self.chunks += 1
            if on_chunk is not None:
                on_chunk(state, k)
        return state, metrics

    def close(self) -> None:
        """Release the service's ingest-dispatch slot so a successor
        consumer (a respawned replica) can claim it."""
        if self.ingest is not None:
            self.ingest.release()


class DealtLoop:
    """Drives pre-sampled dealt blocks from a ``DealtBlockRing`` — the
    consumer half of the sample-on-ingest plane (``replay/sampler.py``).

    Mirrors ``FusedLoop.run``'s contract (state in, ``(state, metrics)``
    out, ``on_chunk`` callback) so ``LearnerReplica`` treats both
    pre-sampled paths uniformly. Per block:

        ring.pop()                  # leaf-tier wait — NO buffer lock
        dispatch K scanned steps    # block rows + dealer IS weights
        service.queue_writeback()   # TD priorities, gen-fenced, drained
                                    # by the owning ingest shard
        trace mark_grad             # deal->grad span terminal

    The grad loop never acquires the buffer lock: sampling already
    happened on the commit thread, and the write-back only enqueues
    under the ``sampler`` tier. ``stop`` (an ``Event``) lets the owning
    replica abandon a blocked pop mid-round on kill.

    Device-dealt blocks (``replay/device_sampler.DeviceSampleDealer``)
    arrive with ``batches``/``weights``/``idx``/``gen`` as DEVICE
    arrays: the rows feed ``update_fn`` with no host round-trip, and
    the loop materializes only ``idx``/``gen`` (``[K, B]`` int arrays,
    not sampled rows) on the host at write-back time — the one
    deliberate D2H on the grad side, synced here so the cost is
    attributed to the write-back and not hidden inside the dealer's
    settle. ``td_error`` comes back from the update anyway; the same
    ``np.asarray`` covers both paths.
    """

    def __init__(self, update_fn, ring, service, *,
                 stop=None, pop_timeout: float = 0.2):
        self._update = update_fn
        self._ring = ring
        self._service = service
        self._stop = stop
        self._pop_timeout = float(pop_timeout)
        self.steps_done = 0
        self.blocks = 0

    def run(
        self,
        state: D4PGState,
        n: int,
        on_chunk: Optional[Callable[[D4PGState, int], None]] = None,
    ):
        """At least ``n`` grad steps from dealt blocks (blocks arrive in
        dealer-sized chunks of K, so the final block may overshoot);
        returns ``(state, metrics)`` with the LAST block's stacked-[k]
        metrics (``None`` when nothing was consumed — closed ring)."""
        metrics = None
        done = 0
        while done < n and (self._stop is None or not self._stop.is_set()):
            block = self._ring.pop(timeout=self._pop_timeout)
            if block is None:
                if self._ring.closed:
                    break
                continue
            state, metrics = self._update(
                state, block.batches, block.weights)
            td = np.abs(np.asarray(metrics["td_error"])) + 1e-6
            # One explicit host sync for device-dealt blocks (no-op
            # copies for host blocks): [K, B] ints, never sampled rows.
            idx = np.asarray(block.idx)
            gen = np.asarray(block.gen)
            self._service.queue_writeback(idx, td, gen)
            _trace_recorder.mark_grad()
            k = int(idx.shape[0])
            done += k
            self.steps_done += k
            self.blocks += 1
            if on_chunk is not None:
                on_chunk(state, k)
        return state, metrics
