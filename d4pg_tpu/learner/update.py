"""The D4PG update and action functions, as pure jittable transforms.

Parity map to the reference's ``DDPG.train`` (``ddpg.py:200-255``, SURVEY.md
S2), all fused into one XLA computation:

  - target dist ``Z'(s', pi'(s'))``          ``ddpg.py:205-206``
  - Bellman projection onto the support      ``ddpg.py:214`` (host numpy
    there; MXU einsum here, ``core/distribution.py``)
  - cross-entropy critic loss                ``ddpg.py:217``
  - per-sample TD error for PER              ``ddpg.py:220-222``
  - critic Adam step                         ``ddpg.py:229-232``
  - policy loss ``-E[Z(s, pi(s))]``          ``ddpg.py:236-238``
  - actor Adam step                          ``ddpg.py:241-244``
  - soft target update (tau)                 ``ddpg.py:250, 110-116``
  - step counter increment                   ``main.py:307``

The hogwild machinery (``copy_gradients`` aliasing ``ddpg.py:104-108``,
``sync_local_global`` ``ddpg.py:118-120``, ``SharedAdam``) has no equivalent:
under pjit the gradients are all-reduced synchronously across the mesh's
``data`` axis by XLA-inserted collectives, so every replica applies the same
deterministic update (SURVEY.md §5 race-detection note).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import Array

from d4pg_tpu.core import mog as mog_ops
from d4pg_tpu.core.distribution import categorical_projection
from d4pg_tpu.core.losses import (
    categorical_td_loss,
    expected_q,
    weighted_mean,
)
from d4pg_tpu.core.updates import soft_update, tie_encoder
from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.replay.uniform import TransitionBatch


def _pallas_backend(flag: str) -> str | None:
    """Resolve whether a ``--projection pallas*`` choice can run on the
    current backend, with the shared trace-time warnings (fire once per
    compile, not per step): interpret-mode emulation on CPU is for kernel
    verification only — a silent orders-of-magnitude slowdown in a real
    CPU training run (ADVICE r3) — and backends with no Pallas lowering
    (e.g. CUDA) fall back to the einsum formulation. Returns the backend
    name to run the kernel on, or None for the einsum fallback."""
    import warnings

    backend = jax.default_backend()
    if backend == "cpu":
        warnings.warn(
            f"--projection {flag} on the CPU backend runs the kernel in "
            "interpret (emulation) mode — orders of magnitude slower than "
            "the einsum projection; use it for kernel verification only",
            stacklevel=3)
    if backend in ("tpu", "cpu"):
        return backend
    warnings.warn(
        f"--projection {flag} has no {backend} path; using the einsum "
        "formulation", stacklevel=3)
    return None


def _project(
    config: D4PGConfig, target_probs: Array, rewards: Array, discounts: Array
) -> Array:
    """Bellman projection through the configured implementation: the MXU
    einsum (default) or the fused Pallas kernel (``--projection pallas``;
    interpret mode keeps it runnable on the CPU backend for tests)."""
    if config.projection == "pallas":
        backend = _pallas_backend("pallas")
        if backend is not None:
            from d4pg_tpu.ops.projection import projection_pallas

            return projection_pallas(
                config.support, target_probs, rewards, discounts,
                backend == "cpu",
            )
    return categorical_projection(config.support, target_probs, rewards, discounts)


def _critic_loss_fn(
    config: D4PGConfig,
    critic_params: Any,
    state: D4PGState,
    batch: TransitionBatch,
    is_weights: Array | None,
    key: Array,
) -> tuple[Array, Array]:
    """Returns (scalar critic loss, per-sample TD error)."""
    actor = config.build_actor()
    critic = config.build_critic()
    next_action = actor.apply(state.target_actor_params, batch.next_obs)

    if config.critic_family == "mog":
        target_params = critic.apply(
            state.target_critic_params, batch.next_obs, next_action
        )
        target = mog_ops.mog_target(target_params, batch.reward, batch.discount)
        pred = critic.apply(critic_params, batch.obs, batch.action)
        return mog_ops.mog_td_loss(
            pred, target, key, n_samples=config.mog_samples, weights=is_weights
        )

    target_probs = critic.apply(
        state.target_critic_params, batch.next_obs, next_action
    )
    pred_probs = critic.apply(critic_params, batch.obs, batch.action)
    if config.projection == "pallas_ce":
        backend = _pallas_backend("pallas_ce")
        if backend is not None:
            # fully-fused projection + cross-entropy (ops/projection_ce.py):
            # the interpolation weights AND the projected target live only
            # in VMEM, forward and backward. Kernel contract == the
            # stop_gradient(projection) semantics below.
            from d4pg_tpu.ops.projection_ce import projection_ce_pallas

            td = projection_ce_pallas(
                config.support, jax.lax.stop_gradient(target_probs),
                batch.reward, batch.discount, pred_probs,
                backend == "cpu")
            return weighted_mean(td, is_weights), td
    proj = jax.lax.stop_gradient(
        _project(config, target_probs, batch.reward, batch.discount)
    )
    return categorical_td_loss(proj, pred_probs, weights=is_weights)


def _actor_loss_fn(
    config: D4PGConfig,
    actor_params: Any,
    critic_params: Any,
    batch: TransitionBatch,
) -> Array:
    """Negative expected Q through the (fixed) critic (``ddpg.py:236-238``),
    plus the HER recipe's optional action-L2 penalty (``action_l2 *
    mean(a^2)`` over all elements — the OpenAI-baselines normalization, so
    published Fetch coefficients transfer regardless of act_dim)
    discouraging saturated tanh actions on sparse-reward manipulation
    tasks. With ``action_l2 > 0`` the reported ``actor_loss`` / ``q_mean``
    metrics include the penalty term."""
    # With share_encoder the actor module stops the gradient at the
    # latent (PixelActor.detach_encoder — SAC-AE/DrQ: the policy loss
    # trains ONLY the actor MLP; the tied encoder learns from the critic
    # loss alone, see the tie in update_step).
    actor = config.build_actor()
    critic = config.build_critic()
    action = actor.apply(actor_params, batch.obs)
    penalty = config.action_l2 * jnp.mean(jnp.square(action))
    if config.critic_family == "mog":
        params = critic.apply(critic_params, batch.obs, action)
        return -jnp.mean(mog_ops.mog_mean(params)) + penalty
    probs = critic.apply(critic_params, batch.obs, action)
    return -jnp.mean(expected_q(config.support, probs)) + penalty


def update_step(
    config: D4PGConfig,
    state: D4PGState,
    batch: TransitionBatch,
    is_weights: Array | None = None,
) -> tuple[D4PGState, dict[str, Array]]:
    """One full D4PG update. Pure; jit with config static.

    Returns the new state and a metrics dict containing scalar ``critic_loss``
    / ``actor_loss`` / ``q_mean`` and the per-sample ``td_error`` vector (the
    PER priority signal, ``ddpg.py:252-255``).
    """
    key, sub = jax.random.split(state.key)

    if config.augment == "shift":
        # DrQ random shift on the sampled rows (ops/augment.py): both
        # losses see the same augmented view; obs and next_obs get
        # independent shifts (DrQ's convention — the target should not
        # share the online view's crop)
        sub, k_obs, k_next = jax.random.split(sub, 3)
        from d4pg_tpu.ops.augment import random_shift

        batch = batch._replace(
            obs=random_shift(k_obs, batch.obs, config.augment_pad),
            next_obs=random_shift(k_next, batch.next_obs,
                                  config.augment_pad),
        )

    # --- critic step -----------------------------------------------------
    (critic_loss, td_error), critic_grads = jax.value_and_grad(
        lambda p: _critic_loss_fn(config, p, state, batch, is_weights, sub),
        has_aux=True,
    )(state.critic_params)
    critic_updates, critic_opt_state = config.optimizer(config.lr_critic).update(
        critic_grads, state.critic_opt_state, state.critic_params
    )
    critic_params = optax.apply_updates(state.critic_params, critic_updates)

    # --- shared-encoder tie (SAC-AE/DrQ): the actor's encoder subtree IS
    # the critic's, refreshed right after the critic step. Done on the
    # params the actor step reads, and RE-asserted after apply_updates
    # below, so the invariant holds even when the actor Adam carries
    # nonzero encoder moments — e.g. a run that flipped --share_encoder
    # on when resuming an unshared checkpoint (stale moments keep
    # emitting decaying updates for many steps; overwriting, not
    # masking, makes that unobservable). The TARGET actor's encoder is
    # likewise tied to the TARGET critic's in the soft-update step — a
    # no-op for a shared-from-init run (identical EMA sequences) that
    # makes the mid-run flip exact rather than (1-tau)^t-transient.
    actor_params_in = (
        tie_encoder(state.actor_params, critic_params)
        if config.share_encoder else state.actor_params)

    # --- actor step. Documented divergence: the policy loss here flows
    # through the critic params the critic Adam step just produced. The
    # reference computes it with its LOCAL critic, which at that point
    # still predates the global optimizer step (``ddpg.py:236-249`` —
    # ``sync_local_global`` pulls the stepped weights back only at
    # ``ddpg.py:247``), i.e. the pre-update critic. Both are standard
    # D4PG variants; one-step-fresher critic is the natural fit for a
    # single fused XLA computation (like the (0.9, 0.999) Adam-b2 default,
    # ``learner/state.py:34-41``). -----------------------------------------
    actor_loss, actor_grads = jax.value_and_grad(
        lambda p: _actor_loss_fn(config, p, critic_params, batch)
    )(actor_params_in)
    actor_updates, actor_opt_state = config.optimizer(config.lr_actor).update(
        actor_grads, state.actor_opt_state, actor_params_in
    )
    actor_params = optax.apply_updates(actor_params_in, actor_updates)
    if config.share_encoder:
        actor_params = tie_encoder(actor_params, critic_params)

    # --- soft target updates (tau, ``ddpg.py:110-116``) -------------------
    target_actor_params = soft_update(
        state.target_actor_params, actor_params, config.tau
    )
    target_critic_params = soft_update(
        state.target_critic_params, critic_params, config.tau
    )
    if config.share_encoder:
        target_actor_params = tie_encoder(
            target_actor_params, target_critic_params)
    new_state = D4PGState(
        actor_params=actor_params,
        critic_params=critic_params,
        target_actor_params=target_actor_params,
        target_critic_params=target_critic_params,
        actor_opt_state=actor_opt_state,
        critic_opt_state=critic_opt_state,
        key=key,
        step=state.step + 1,
    )
    metrics = {
        "critic_loss": critic_loss,
        "actor_loss": actor_loss,
        "q_mean": -actor_loss,
        "td_error": td_error,
    }
    return new_state, metrics


def make_update(config: D4PGConfig, donate: bool = True, use_is_weights: bool = True):
    """jit-compile the update with ``config`` closed over statically.

    ``donate=True`` donates the input state's buffers so XLA updates
    parameters in place (HBM-frugal). ``use_is_weights=False`` compiles the
    uniform-replay variant without the weights operand.
    """
    if use_is_weights:
        fn = lambda state, batch, w: update_step(config, state, batch, w)
    else:
        fn = lambda state, batch: update_step(config, state, batch, None)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def multi_update_step(
    config: D4PGConfig,
    state: D4PGState,
    batches: TransitionBatch,
    weights: Array | None = None,
):
    """K sequential updates via ``lax.scan`` over stacked batches — the pure
    function behind :func:`make_multi_update` and the mesh-sharded variant
    (``parallel.data_parallel.make_sharded_multi_update``).

    Inputs carry a leading K axis: batch fields [K, B, ...], weights
    [K, B]. Returns ``(state, metrics)`` with metrics stacked along K
    (``td_error`` [K, B] feeds the batched priority write-back).
    """
    def body(s, xs):
        if weights is not None:
            b, w = xs
            return update_step(config, s, b, w)
        return update_step(config, s, xs, None)

    xs = (batches, weights) if weights is not None else batches
    return jax.lax.scan(body, state, xs)


def make_multi_update(
    config: D4PGConfig, donate: bool = True, use_is_weights: bool = True
):
    """jit :func:`multi_update_step` (K updates per device dispatch).

    The single-step update is dispatch-bound on TPU (measured ~4.2k
    steps/sec single vs ~69k at K=16 on one v5e chip, batch 256): each
    step's compute is ~15us while the Python->device round trip costs
    ~240us. Scanning K steps amortizes the dispatch. Semantically identical
    to K sequential ``update_step`` calls (the PRNG chain threads through
    the carried state); for PER the K priority updates land after the scan,
    i.e. with staleness < K (standard in high-throughput actor-learner
    pipelines).
    """
    if use_is_weights:
        fn = lambda state, batches, w: multi_update_step(config, state, batches, w)
    else:
        fn = lambda state, batches: multi_update_step(config, state, batches)
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


@partial(jax.jit, static_argnums=(0,))
def act(
    config: D4PGConfig,
    actor_params: Any,
    obs: Array,
    key: Array,
    epsilon: Array | float = 0.3,
) -> Array:
    """Exploratory action: ``clip(pi(s) + eps * N(0, I), -1, 1)``
    (``main.py:145-146`` with the Gaussian noise of ``random_process.py:16-18``).

    Batched: obs [B, obs_dim] -> actions [B, act_dim]; one key for the whole
    batch (split upstream per actor for decorrelation).
    """
    action = config.build_actor().apply(actor_params, obs)
    noise = jax.random.normal(key, action.shape) * epsilon
    return jnp.clip(action + noise, -1.0, 1.0)


@partial(jax.jit, static_argnums=(0,))
def act_deterministic(config: D4PGConfig, actor_params: Any, obs: Array) -> Array:
    """Greedy action for evaluation (``main.py:121-130``)."""
    return config.build_actor().apply(actor_params, obs)


@partial(jax.jit, static_argnums=(0,))
def act_ou(
    config: D4PGConfig,
    actor_params: Any,
    obs: Array,
    ou_state,
    key: Array,
    epsilon: Array | float = 1.0,
    theta: float = 0.25,
    mu: float = 0.0,
    sigma: float = 0.05,
    dt: float = 0.01,
):
    """Exploratory action with Ornstein-Uhlenbeck noise, fused into one jit:
    greedy forward + OU state advance + clip in a single dispatch (the
    temporally-correlated process of ``random_process.py:23-45``, which the
    reference constructs nowhere live — SURVEY.md C6).

    Returns ``(actions, new_ou_state)``; thread the state through the acting
    loop and zero rows at episode boundaries.
    """
    from d4pg_tpu.core.noise import ou

    greedy = config.build_actor().apply(actor_params, obs)
    new_state, noise = ou.sample(ou_state, key, theta=theta, mu=mu,
                                 sigma=sigma, dt=dt)
    action = jnp.clip(greedy + epsilon * noise, -1.0, 1.0)
    return action, new_state
