"""Pipelined K-chunk learner loop — the shipped hot path.

One place implements the sample -> stage -> scanned-update -> priority
write-back pipeline so ``train.py`` and ``bench.py`` measure and ship the
SAME loop (the reference scope per step is ``ddpg.py:200-255``: sample,
nets, projection, optimizer, priorities). Schedule per chunk t:

  1. take the staged chunk t (sampled/device_put while t-1 computed),
     and immediately stage chunk t+1 (host work, overlaps device),
  2. dispatch the K-step scanned update for chunk t (async),
  3. once more than ``depth`` chunks are in flight, write back the
     oldest chunk's PER priorities (its td_error D2H copy was started at
     dispatch time, so the flush rarely blocks).

PER priorities therefore land with staleness <= (depth + 1) * K grad
steps (Ape-X-style bounded lag); ``updates_per_dispatch=1`` in the config
restores exact per-step write-back semantics via the non-pipelined path
in ``train.py``. (The fused device path, ``learner/fused.py``, does not
need any of this — its write-back happens inside the dispatch.)
"""

from __future__ import annotations

import threading
import weakref
from contextlib import contextmanager
from typing import Any, Callable, Optional

import numpy as np

from d4pg_tpu.replay.staging import DeviceStager


class IngestDispatchError(RuntimeError):
    """A second consumer raced the service's single ingest-dispatch slot
    (two live ``IngestOverlap`` owners, or concurrent commit/stage/flush
    calls on one). The double-buffer schedule is single-consumer by
    construction — a silent second dispatcher would interleave ring
    writes and corrupt replay, so this fails loudly instead."""


class IngestOverlap:
    """Double-buffers actor→ring ingest against the in-flight fused chunk.

    The fused path's only host job is moving staged actor rows into the
    device ring between chunks (``replay/fused_buffer.py``). Done naively
    (a full synchronous drain before every dispatch) the H2D transfer
    serializes with the chunk; this schedule overlaps them:

        ingest.commit()        # block t's ring write+tree insert (async
                               # jitted dispatch, no transfer) — rows are
                               # samplable by the chunk dispatched next
        dispatch fused chunk t
        ingest.stage()         # ONE device_put of block t+1 — the H2D
                               # rides under chunk t's compute

    giving a hard bound of ≤ 1 explicit H2D per chunk in steady state
    (verified by ``TransferSentinel`` in bench.py and
    tests/test_ingest.py). Backpressure is structural: at most
    ``block_rows`` rows land per chunk; a deeper backlog drains at cycle
    boundaries (``flush``), and the staging ring drops oldest beyond its
    bound. Works against ``ReplayService`` (whose ``ingest_stage`` falls
    back to a full drain for buffers without the block API).

    **Single-consumer, enforced.** The commit/stage handoff mutates the
    service's ONE staged-block slot; two dispatchers would interleave
    ring writes and silently corrupt replay. Construction therefore
    claims the service's ingest-dispatch slot (a weakly-held owner
    token — a dropped overlap releases it via GC, an explicit successor
    calls ``release()``), and every dispatch holds a non-blocking busy
    token so a concurrent commit/stage/flush — the shape a second
    learner replica would produce — raises ``IngestDispatchError``
    instead of corrupting. Multi-replica learners (``--learners N>1``)
    must use the host-sampled path, which is why ``LearnerReplica``
    only builds a ``FusedLoop`` when it is the sole consumer.
    """

    def __init__(self, service):
        owner_ref = getattr(service, "_ingest_overlap_owner", None)
        owner = owner_ref() if owner_ref is not None else None
        if owner is not None:
            raise IngestDispatchError(
                "ReplayService already has a live IngestOverlap consumer "
                f"({owner!r}); the fused ingest handoff is single-consumer "
                "— release() the current owner first, or use the "
                "host-sampled path for concurrent learner replicas")
        dealer = getattr(service, "_dealer", None)
        if dealer is not None and getattr(dealer, "owns_commit", False):
            # Device-dealt mode: the attached dealer drains the staged
            # slot itself inside every ingest's buffer-lock window (the
            # deal must see the block it just committed). A second
            # commit/stage driver would interleave with those drains and
            # corrupt the handoff, so refuse up front instead of racing.
            raise IngestDispatchError(
                "ReplayService has a device-dealt sampler attached "
                f"({type(dealer).__name__}); its commit thread owns the "
                "ingest dispatch — dealt replicas consume from their "
                "rings, no IngestOverlap")
        service._ingest_overlap_owner = weakref.ref(self)
        self._service = service
        # busy token, held across each dispatch into the service: plain
        # non-blocking Lock — contention IS the defect being detected,
        # so the loser raises instead of waiting
        self._busy = threading.Lock()
        self.rows_committed = 0
        self.rows_staged = 0
        self.blocks = 0

    @contextmanager
    def _dispatch(self, op: str):
        if not self._busy.acquire(blocking=False):
            raise IngestDispatchError(
                f"concurrent IngestOverlap.{op}() while another dispatch "
                "is in flight — the double-buffer handoff is "
                "single-consumer")
        try:
            owner_ref = getattr(self._service, "_ingest_overlap_owner", None)
            if owner_ref is None or owner_ref() is not self:
                raise IngestDispatchError(
                    f"IngestOverlap.{op}() after ownership moved to another "
                    "consumer (release()d, or a successor claimed the slot)")
            yield
        finally:
            self._busy.release()

    def commit(self) -> int:
        with self._dispatch("commit"):
            n = self._service.ingest_commit()
            self.rows_committed += n
            self.blocks += 1 if n else 0
            return n

    def stage(self) -> int:
        with self._dispatch("stage"):
            n = self._service.ingest_stage()
            self.rows_staged += n
            return n

    def flush(self) -> int:
        """Synchronous full drain (cycle boundary / checkpoint): every
        staged row lands before the next sample."""
        with self._dispatch("flush"):
            n = self._service.drain_device()
            self.rows_committed += n
            return n

    def release(self) -> None:
        """Give up the service's ingest-dispatch slot (idempotent) so a
        successor consumer — e.g. a respawned replica — can claim it."""
        owner_ref = getattr(self._service, "_ingest_overlap_owner", None)
        if owner_ref is not None and owner_ref() is self:
            self._service._ingest_overlap_owner = None


class ChunkPipeline:
    """Drives ``multi_update`` over prefetched chunks.

    ``sample_fn() -> ((batches, weights), aux)``: host-side sample of one
    [K, B, ...] chunk; ``weights``/``aux`` are None for uniform replay.
    ``write_back(aux, td)``: PER priority update, td shaped [K, B].
    ``sharding``: optional NamedSharding for the staged chunk (mesh path).
    """

    def __init__(
        self,
        update_fn: Callable,
        sample_fn: Callable[[], tuple],
        write_back: Optional[Callable[[Any, np.ndarray], None]] = None,
        sharding=None,
        use_weights: bool = True,
        fetch_td: Optional[Callable] = None,
        put_fn: Optional[Callable] = None,
        depth: int = 2,
    ):
        self._update = update_fn
        self._write_back = write_back
        self._use_weights = use_weights
        # How to pull td_error to the host. Default: full fetch. Multi-host
        # passes a local-shard extractor (a host can only read its own rows
        # of the globally-sharded [K, B] td_error).
        self._fetch_td = fetch_td or (lambda m: np.asarray(m["td_error"]))
        # put_fn: custom staging (multi-host global-array assembly);
        # default is device_put onto ``sharding``.
        self._stager = DeviceStager(sample_fn, device=sharding,
                                    with_aux=True, put_fn=put_fn)
        # In-flight dispatch depth: the PER write-back for chunk t blocks
        # on t's td_error, i.e. on t's whole dispatch — on a high-latency
        # (tunneled/PCIe) link that sync dominates. Keeping up to `depth`
        # chunks in flight amortizes it; priority staleness grows to
        # <= (depth + 1) * K steps (Ape-X-style bounded lag).
        self._depth = max(1, int(depth))

    def invalidate(self) -> None:
        """Drop the staged chunk (sync-mode cycle boundary: train only on
        post-collect samples)."""
        self._stager.invalidate()

    def run(
        self,
        state,
        n_chunks: int,
        on_chunk: Optional[Callable] = None,
        final_prefetch: bool = True,
    ):
        """Run ``n_chunks`` pipelined dispatches; returns (state, metrics of
        the last chunk, stacked [K]). ``on_chunk(state)`` fires after each
        dispatch (step accounting, weight publishing). Pass
        ``final_prefetch=False`` when the caller will ``invalidate()``
        before the next run (avoids staging a chunk only to discard it)."""
        metrics = None
        pending: list = []
        for i in range(n_chunks):
            prefetch = final_prefetch or (i + 1 < n_chunks)
            (batches, w), aux = self._stager.next(prefetch=prefetch)
            if self._use_weights:
                state, metrics = self._update(state, batches, w)
            else:
                state, metrics = self._update(state, batches)
            td = metrics.get("td_error") if self._write_back else None
            if td is not None and getattr(td, "is_fully_addressable", False):
                # start the D2H copy now; by flush time the bytes are
                # already local and np.asarray doesn't pay the round trip
                td.copy_to_host_async()
            pending.append((aux, metrics))
            while len(pending) > self._depth:
                self._flush(pending.pop(0))
            if on_chunk is not None:
                on_chunk(state)
        for p in pending:
            self._flush(p)
        return state, metrics

    def _flush(self, pending) -> None:
        aux, metrics = pending
        if aux is None or self._write_back is None:
            return
        td = np.abs(self._fetch_td(metrics)) + 1e-6
        self._write_back(aux, td)
