"""One learner replica: owns its shard of training state, nothing else.

A ``LearnerReplica`` is the unit the multi-learner plane scales
(``--learners N``): it holds a FULL ``D4PGState`` — network params plus
its OWN optimizer state and PRNG key — but the network params are only a
working copy of the aggregator's authoritative tree. Each round it

    1. pulls a **basis** from the aggregator (version-stamped; params
       arrive only when someone else advanced the aggregate — a replica
       never re-adopts its own round-tripped submission),
    2. runs ``n`` grad steps against replay (fused device loop when it
       is the sole consumer, host-sampled chunks otherwise),
    3. submits its resulting params stamped with the basis version, so
       the aggregator can weight the update by how stale it is
       (``learner/aggregator.py``).

Optimizer state and key deliberately do NOT flow through the aggregator:
IMPACT-style correction is defined on parameters; each replica's Adam
moments chase its own trajectory (standard in async SGD — see the
module doc in ``aggregator.py``).

Two sampling modes, chosen by what the replica is given:

- **fused** (``buffer`` passed; ``service`` optionally rides along for
  the ingest overlap): the extracted ``FusedLoop`` —
  commit/dispatch/stage against a device-resident buffer. Single
  consumer by construction (``IngestOverlap`` enforces it), so train.py
  only builds fused replicas at N=1 — which is exactly the
  configuration the bitwise legacy-equivalence oracle pins.
- **host** (``service`` passed): ``ReplayService.sample_chunk`` under
  the service's own buffer lock (thread-safe for N concurrent
  replicas) + ``make_multi_update`` K-scanned dispatch + deferred PER
  priority write-back with the generation guard.
- **dealt** (``dealt_ring`` passed with ``service``): the
  sample-on-ingest plane (``replay/sampler.py``) — the replica pops
  ready-to-train blocks (rows + IS weights, pre-sampled by the commit
  thread's dealer) from its bounded ring and feeds TD priorities back
  through ``service.queue_writeback``. The sample path acquires the
  ring leaf lock and the ``sampler`` tier ONLY — never the buffer
  lock, which is the whole point. The replica is agnostic to WHERE
  the dealer sampled: host blocks (``SampleDealer``, numpy rows) and
  device blocks (``replay/device_sampler.DeviceSampleDealer``,
  device-resident gathers that flow into ``update_fn`` with no host
  round-trip) ride the same ring and the same ``DealtLoop`` — the
  commit thread owns every device handle in the device-dealt mode, so
  nothing here changes per variant.

PER beta annealing: with N replicas each replica annealing off its own
``steps_done`` would scale the anneal rate with N (the PR-10 defect) —
pass one shared ``replay/schedule.SharedBetaSchedule`` as
``beta_schedule`` and every replica reads the same global clock. When
omitted, a private schedule reproduces the legacy single-replica
behavior bitwise.

Locking: ``_replica_lock`` (tier ``replica`` = 36) guards ONLY control
state — counters, epoch, stop flag. It is never held across sampling,
the grad loop, or ``submit`` — replay's buffer lock sits ABOVE it
(``buffer`` = 40), so holding it into a sample would be an ascent the
runtime sentinels reject at the first acquisition. Holding it into
``submit`` would be legal (``agg`` = 34 descends) but pointless.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import numpy as np

from d4pg_tpu.core.locking import TieredLock
from d4pg_tpu.learner.loop import FusedLoop
from d4pg_tpu.learner.state import D4PGConfig, D4PGState

PARAM_FIELDS = ("actor_params", "critic_params",
                "target_actor_params", "target_critic_params")


def params_of(state: D4PGState, to_host: bool = True) -> dict:
    """The aggregation tree: all four network-param subtrees (targets
    included — averaging live nets but not targets would tear the
    distributional TD bootstrap apart across replicas)."""
    tree = {f: getattr(state, f) for f in PARAM_FIELDS}
    return jax.device_get(tree) if to_host else tree


def adopt_params(state: D4PGState, params: dict) -> D4PGState:
    """A new basis from the aggregator, keeping THIS replica's optimizer
    state, PRNG key and step counter."""
    return state._replace(**{f: params[f] for f in PARAM_FIELDS})


class LearnerReplica:
    """See module doc. ``agg`` is anything with the ``Aggregator`` duck
    type (register/basis/submit) — the in-process aggregator in train.py,
    or an ``update_plane.UpdateClient`` speaking the wire protocol."""

    def __init__(
        self,
        replica_id: int,
        config: D4PGConfig,
        agg,
        state: D4PGState,
        *,
        k: int,
        batch_size: int,
        prioritized: bool = True,
        alpha: float = 0.6,
        beta0: float = 0.4,
        beta_steps: int = 100_000,
        buffer=None,
        service=None,
        dealt_ring=None,
        beta_schedule=None,
        mesh=None,
        donate: bool = True,
    ):
        if buffer is None and service is None:
            raise ValueError(
                "need buffer= (fused mode, sole consumer; service= "
                "optionally adds the ingest overlap) or service= alone "
                "(host-sampled mode, N-replica safe; add dealt_ring= "
                "for the sample-on-ingest dealt mode)")
        if dealt_ring is not None and (buffer is not None or service is None):
            raise ValueError("dealt mode needs service= (for the priority "
                             "write-back) and no fused buffer=")
        if dealt_ring is not None and not prioritized:
            raise ValueError(
                "dealt mode is PER-only: dealt blocks carry IS weights")
        self.replica_id = int(replica_id)
        self._config = config
        self._agg = agg
        self._state = state
        if buffer is not None:
            self.mode = "fused"
        elif dealt_ring is not None:
            self.mode = "dealt"
        else:
            self.mode = "host"
        self.k = max(1, int(k))
        self._batch_size = int(batch_size)
        self._prioritized = bool(prioritized)
        self._beta0 = float(beta0)
        self._beta_steps = int(beta_steps)
        self._service = service
        self._dealt_ring = dealt_ring
        # shared anneal clock (see module doc); private fallback keeps
        # the legacy single-replica anneal bitwise
        from d4pg_tpu.replay.schedule import SharedBetaSchedule
        self._beta_sched = beta_schedule or SharedBetaSchedule(
            beta0=self._beta0, beta_steps=self._beta_steps)
        self._loop = None
        self._update = None
        if self.mode == "fused":
            self._loop = FusedLoop(
                config, buffer, k=self.k, batch_size=batch_size,
                prioritized=prioritized, alpha=alpha, beta0=beta0,
                beta_steps=beta_steps, mesh=mesh, service=service,
                donate=donate)
        else:
            from d4pg_tpu.learner.update import make_multi_update
            self._update = make_multi_update(
                config, donate=donate, use_is_weights=prioritized)
        # control state ONLY under this lock (see module doc)
        self._replica_lock = TieredLock("replica")
        self._stop = threading.Event()
        self._dealt_loop = None
        if self.mode == "dealt":
            from d4pg_tpu.learner.loop import DealtLoop
            self._dealt_loop = DealtLoop(
                self._update, dealt_ring, service, stop=self._stop)
        self.epoch = agg.register(self.replica_id,
                                  params=params_of(state), step=0)
        self.steps_done = 0
        self.last_metrics = None  # last chunk's stacked-[k] metrics dict
        self.rounds = 0
        self.applied = 0
        self.fenced = 0
        self.last_lag: Optional[int] = None
        self.last_status = "idle"

    # -- sampling/update paths ----------------------------------------------
    def _host_steps(self, n: int) -> None:
        svc = self._service
        done = 0
        # ONE clock read for the whole call: beta is constant across the
        # call's chunks (the legacy per-chunk ``_beta()`` was too, since
        # ``steps_done`` only advanced after the loop) and two replicas
        # at the same global step compute the identical value.
        beta = self._beta_sched.beta_at(self._beta_sched.current_step())
        while done < n and not self._stop.is_set():
            k = min(self.k, n - done)
            if self._prioritized:
                batches, w, idx, gen = svc.sample_chunk(
                    k, self._batch_size, beta=beta,
                    weight_base=svc.weight_base())
                self._state, metrics = self._update(self._state, batches, w)
                td = np.abs(np.asarray(metrics["td_error"])) + 1e-6
                svc.update_priorities(idx, td, generation=gen)
            else:
                batches, _w, _idx, _gen = svc.sample_chunk(
                    k, self._batch_size)
                self._state, metrics = self._update(self._state, batches)
            self.last_metrics = metrics
            done += k
        if done:
            self._beta_sched.advance(done)
        self.steps_done += done

    def _dealt_steps(self, n: int) -> None:
        """Consume pre-sampled blocks through the extracted ``DealtLoop``
        (``learner/loop.py``): pop, K-chunk update, queue the TD
        write-back. No buffer-lock acquisition anywhere on this path —
        the ring pop is a leaf-tier wait and the write-back enqueues
        under the ``sampler`` tier (beta already rode in with the block,
        annealed by the dealer's shared clock)."""
        before = self._dealt_loop.steps_done
        self._state, metrics = self._dealt_loop.run(self._state, n)
        if metrics is not None:
            self.last_metrics = metrics
        self.steps_done += self._dealt_loop.steps_done - before

    def _fused_steps(self, n: int) -> None:
        self._state, metrics = self._loop.run(self._state, n)
        if metrics is not None:
            self.last_metrics = metrics
        self.steps_done += n

    # -- the replica round ---------------------------------------------------
    def run_round(self, n: int, generation: int | None = None) -> dict:
        """One basis-adopt -> n grad steps -> version-stamped submit
        cycle; returns the aggregator's verdict (applied/fenced + lag +
        weight). No replica lock is held across any of it."""
        basis_version, basis = self._agg.basis(self.replica_id)
        if basis is not None:
            self._state = adopt_params(self._state, basis)
        if self.mode == "fused":
            self._fused_steps(n)
        elif self.mode == "dealt":
            self._dealt_steps(n)
        else:
            self._host_steps(n)
        result = self._agg.submit(
            self.replica_id, self.epoch, params_of(self._state),
            basis_version, step=self.steps_done, generation=generation)
        with self._replica_lock:
            self.rounds += 1
            self.last_status = result["status"]
            self.last_lag = result.get("lag")
            if result["status"] == "applied":
                self.applied += 1
            elif result["status"] == "fenced":
                self.fenced += 1
        return result

    def run(self, rounds: int, steps_per_round: int) -> None:
        """Supervisor-thread entry: rounds until done or stopped."""
        for _ in range(rounds):
            if self._stop.is_set():
                return
            self.run_round(steps_per_round)

    def respawn(self) -> int:
        """Supervisor path after a crash: fence the dead epoch (so an
        in-flight submission from the corpse bounces on arrival), then
        re-register at the next epoch. The replica keeps its state —
        it is the thread that died, not the params."""
        self._agg.fence_replica(self.replica_id)
        self.epoch = self._agg.register(self.replica_id)
        self._stop.clear()
        return self.epoch

    # -- control -------------------------------------------------------------
    def stop(self) -> None:
        self._stop.set()

    def stopped(self) -> bool:
        return self._stop.is_set()

    @property
    def state(self) -> D4PGState:
        return self._state

    def stats(self) -> dict:
        with self._replica_lock:
            return {"replica": self.replica_id, "mode": self.mode,
                    "epoch": self.epoch, "steps": self.steps_done,
                    "rounds": self.rounds, "applied": self.applied,
                    "fenced": self.fenced, "lag": self.last_lag,
                    "status": self.last_status}

    def close(self) -> None:
        self._stop.set()
        if self._loop is not None:
            self._loop.close()
