"""Mesh-native learner replicas: N replicas on ONE mesh, collective merge.

The PR-10 multi-learner plane scales replicas as host threads exchanging
params through the socket aggregator (``learner/aggregator.py`` +
``distributed/update_plane.py``) — correct across hosts, but when the
replicas share one device mesh every round pays a device→host pull, a
0xD4AB frame, host-numpy merge math and a host→device push for data
that never needed to leave the accelerator. This module is the
mesh-native formulation (the "21 minutes" blueprint, arXiv 1801.02852):

- each replica's FULL ``D4PGState`` — params, Adam moments, PRNG key —
  lives as one [N, ...]-stacked tree sharded along the ``replica`` mesh
  axis by partition rule (``partition.replica_stack_shardings``);
- the grad engine is the SAME pure ``fused_chunk_step`` the legacy
  FusedLoop jits, run under ``shard_map`` over the replica axis, so
  each replica trains against its own ring shard with its own key —
  N independent learners in one dispatch;
- the per-round basis pull is device-local: replicas adopt the merged
  params without the tree ever visiting the host;
- the merge itself is a device computation over the replica-sharded
  stack (XLA inserts the gather — no sockets, no host math), with the
  SAME semantics as the host aggregator:

  * ``async`` (IMPACT, arXiv 1912.00167): round-synchronous submissions
    in replica order have lag_i = i, so the fold adopts replica 0
    wholesale and blends replica i at ``w = max(1/(1+i), 1/clip)`` —
    exactly the sequence of ``_blend`` steps the host aggregator applies
    to same-basis submissions arriving in order.
  * ``sync``: N-way average in the widest available dtype (float64 when
    x64 is enabled; the host aggregator always sums in float64, so on
    x64-disabled backends equivalence is tolerance-, not bitwise-grade).
  * N == 1: the merge is a Python-static exact identity — no arithmetic
    touches the params, which is what lets the N=1-through-the-mesh-path
    oracle stay BITWISE against the legacy FusedLoop
    (``tests/test_mesh_replicas.py``).

The socket path remains the cross-host fallback (``--agg_transport``);
this module is for replicas that share a mesh.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.learner.fused import fused_chunk_step
from d4pg_tpu.learner.replica import PARAM_FIELDS
from d4pg_tpu.learner.state import D4PGConfig, D4PGState
from d4pg_tpu.parallel import partition, replica_mesh
from d4pg_tpu.parallel.compat import shard_map

_tree_map = jax.tree_util.tree_map

MODES = ("async", "sync")


def make_collective_merge(n: int, mode: str, clip: float = 8.0):
    """The on-device merge over an [N, ...]-stacked param tree. Pure;
    jit at the call site (the group jits it once with replicated
    out_shardings). Semantics mirror ``Aggregator`` — see module doc."""
    if mode not in MODES:
        raise ValueError(f"unknown aggregation mode {mode!r}")
    if clip < 1.0:
        raise ValueError(f"clip={clip} must be >= 1 (floor 1/clip <= 1)")

    def merge(params: Any) -> Any:
        if n == 1:
            # exact identity — no arithmetic (the N=1 bitwise oracle)
            return _tree_map(lambda x: x[0], params)
        if mode == "sync":
            wide = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            return _tree_map(
                lambda x: (jnp.sum(x.astype(wide), axis=0) / n
                           ).astype(x.dtype),
                params)
        # async: round-synchronous submissions in replica order → lag_i=i
        merged = _tree_map(lambda x: x[0], params)
        for i in range(1, n):
            w = np.float32(max(1.0 / (1.0 + i), 1.0 / clip))
            merged = _tree_map(
                lambda m, x: (m + w * (x[i] - m)).astype(m.dtype),
                merged, params)
        return merged

    return merge


class MeshReplicaGroup:
    """N learner replicas as one replica-sharded program on one mesh.

    ``states`` are the per-replica initial ``D4PGState``s (identical
    nets, decorrelated keys — the same construction train.py uses for
    thread replicas). ``store`` is an optional ``WeightStore``: each
    round's merged params are published through it (``extract`` /
    ``norm_stats`` as in ``Aggregator``), keeping the downstream
    (generation, version) stream identical to the socket path's.

    The fused engine needs ``load(buffer)`` — a host-filled
    ``FusedDeviceReplay`` whose ring/trees are broadcast to every
    replica (each then samples with its OWN key and anneals its OWN
    priorities, the same semantics as N thread replicas over a shared
    service). ``step_host_chunks`` is the service-sampled engine for
    train.py's streaming path.
    """

    def __init__(
        self,
        config: D4PGConfig,
        states: list[D4PGState],
        *,
        k: int,
        batch_size: int,
        mode: str = "async",
        clip: float = 8.0,
        store=None,
        extract: Optional[Callable[[Any], Any]] = None,
        norm_stats: Optional[Callable[[], tuple | None]] = None,
        prioritized: bool = True,
        alpha: float = 0.6,
        beta0: float = 0.4,
        beta_steps: int = 100_000,
        devices=None,
    ):
        self.n = len(states)
        if self.n < 1:
            raise ValueError("need at least one replica state")
        self.mesh = replica_mesh(self.n, devices)
        self._config = config
        self.k = max(1, int(k))
        self._batch_size = int(batch_size)
        self.mode = mode
        self.clip = float(clip)
        self._store = store
        self._extract = extract
        self._norm_stats = norm_stats
        self._prioritized = bool(prioritized)
        self._alpha = float(alpha)
        self._beta0 = float(beta0)
        self._beta_steps = int(beta_steps)

        self._state_sh = partition.replica_stack_shardings(
            self.mesh, states[0])
        self._state = jax.device_put(
            _tree_map(lambda *xs: jnp.stack(xs), *states), self._state_sh)
        self._storage = None
        self._trees = None
        self._sizes = None
        self._chunk_fns: dict[int, Any] = {}
        self._update_fn = None

        repl = partition.replicated(self.mesh)
        self._merge_fn = jax.jit(
            make_collective_merge(self.n, mode, clip), out_shardings=repl)
        if self.n > 1:
            def adopt(state, merged):
                tiled = {
                    f: _tree_map(
                        lambda x: jnp.broadcast_to(x[None],
                                                   (self.n, *x.shape)),
                        merged[f])
                    for f in PARAM_FIELDS}
                return state._replace(**tiled)

            self._adopt_fn = jax.jit(
                adopt, out_shardings=self._state_sh, donate_argnums=(0,))
        else:
            self._adopt_fn = None

        self.steps_done = 0        # per-replica grad steps
        self.rounds = 0
        self.last_merge_s: Optional[float] = None
        self.last_metrics = None
        self._merged = None        # last merged param tree (device)
        self._versions: list[int] = []

    # -- replay engines ------------------------------------------------------
    def load(self, buffer) -> None:
        """Broadcast a host-filled ``FusedDeviceReplay``'s ring + PER
        trees to every replica ([cap, ...] → [N, cap, ...] sharded over
        ``replica``). The broadcast is one jitted device computation —
        rows are copied over ICI, never through the host."""
        buffer.drain()
        n = self.n
        payload = (buffer.storage, buffer.trees) if self._prioritized \
            else (buffer.storage,)
        out_sh = partition.replica_stack_shardings(self.mesh, payload)
        # one-shot per load (startup / test fill): jit-with-out_shardings
        # is what materializes the broadcast on every replica's device
        placed = jax.jit(  # jaxlint: disable=recompile-hazard
            lambda t: _tree_map(
                lambda x: jnp.broadcast_to(x[None], (n, *x.shape)), t),
            out_shardings=out_sh)(payload)
        if self._prioritized:
            self._storage, self._trees = placed
        else:
            (self._storage,) = placed
        self._sizes = jax.device_put(
            jnp.full((n,), int(buffer.size), jnp.int32),
            partition.replica_sharding(self.mesh))

    def _chunk_for(self, k: int):
        """The shard_map'd fused chunk for length ``k`` (cached): every
        replica runs the SAME pure ``fused_chunk_step`` the legacy
        FusedLoop jits, against its own shard of the stacked state."""
        if k in self._chunk_fns:
            return self._chunk_fns[k]
        config, bsz = self._config, self._batch_size
        alpha, beta0, beta_steps = self._alpha, self._beta0, self._beta_steps
        R = partition.replica_spec()

        def local(tree):
            return _tree_map(lambda x: x[0], tree)

        def expand(tree):
            return _tree_map(lambda x: x[None], tree)

        if self._prioritized:
            def body(state, trees, storage, size):
                s, t, m = fused_chunk_step(
                    config, local(state), local(trees), local(storage),
                    size[0], k=k, batch_size=bsz, alpha=alpha,
                    beta0=beta0, beta_steps=beta_steps)
                return expand(s), expand(t), expand(m)

            fn = shard_map(body, mesh=self.mesh,
                           in_specs=(R, R, R, R), out_specs=(R, R, R),
                           check_vma=False)
            jitted = jax.jit(fn, donate_argnums=(0, 1))
        else:
            def body_u(state, storage, size):
                s, _t, m = fused_chunk_step(
                    config, local(state), None, local(storage), size[0],
                    k=k, batch_size=bsz)
                return expand(s), expand(m)

            fn = shard_map(body_u, mesh=self.mesh,
                           in_specs=(R, R, R), out_specs=(R, R),
                           check_vma=False)
            jitted = jax.jit(fn, donate_argnums=(0,))
        self._chunk_fns[k] = jitted
        return jitted

    def _fused_steps(self, n: int) -> None:
        if self._storage is None:
            raise RuntimeError("fused engine not loaded — call load(buffer)")
        done = 0
        while done < n:
            k = min(self.k, n - done)
            fn = self._chunk_for(k)
            if self._prioritized:
                self._state, self._trees, self.last_metrics = fn(
                    self._state, self._trees, self._storage, self._sizes)
            else:
                self._state, self.last_metrics = fn(
                    self._state, self._storage, self._sizes)
            done += k
        self.steps_done += done

    def step_host_chunks(self, batches, weights=None):
        """The service-sampled engine: one [N, K, B, ...] stack of host
        chunks (replica i trains on ``batches[i]``) through the scanned
        multi-update under ``shard_map``. Returns the stacked metrics
        ([N, K] scalars, [N, K, B] ``td_error`` for the PER write-back).
        """
        from d4pg_tpu.learner.update import multi_update_step

        if self._update_fn is None:
            config = self._config
            R = partition.replica_spec()

            def local(tree):
                return _tree_map(lambda x: x[0], tree)

            def expand(tree):
                return _tree_map(lambda x: x[None], tree)

            use_w = weights is not None
            if use_w:
                def body(state, batches, w):
                    s, m = multi_update_step(
                        config, local(state), local(batches), local(w))
                    return expand(s), expand(m)
                specs = (R, R, R)
            else:
                def body(state, batches):
                    s, m = multi_update_step(
                        config, local(state), local(batches))
                    return expand(s), expand(m)
                specs = (R, R)
            fn = shard_map(body, mesh=self.mesh, in_specs=specs,
                           out_specs=(R, R), check_vma=False)
            self._update_fn = jax.jit(fn, donate_argnums=(0,))
        stack_sh = partition.replica_sharding(self.mesh)
        batches = jax.device_put(batches, stack_sh)
        if weights is not None:
            weights = jax.device_put(weights, stack_sh)
            self._state, metrics = self._update_fn(
                self._state, batches, weights)
        else:
            self._state, metrics = self._update_fn(self._state, batches)
        self.steps_done += int(batches[0].shape[1])  # [N, K, B, ...] → K
        self.last_metrics = metrics
        return metrics

    # -- the round -----------------------------------------------------------
    def merge(self) -> Any:
        """Run the collective merge over the current per-replica params;
        adopt the result as every replica's next basis (device-local —
        the socket path's per-round pull/push never happens); publish
        through the store when one is attached. Returns the merged
        param tree (device, replicated)."""
        t0 = time.perf_counter()
        stacked = {f: getattr(self._state, f) for f in PARAM_FIELDS}
        merged = self._merge_fn(stacked)
        if self.n > 1:
            # N=1 skips adoption entirely: the merged tree IS replica
            # 0's params, and re-threading it through a device round
            # trip is pointless (the bitwise oracle pins this)
            self._state = self._adopt_fn(self._state, merged)
        jax.block_until_ready(merged)
        self.last_merge_s = time.perf_counter() - t0
        self._merged = merged
        self.rounds += 1
        if self._store is not None:
            pub = self._extract(merged) if self._extract else merged
            norm = self._norm_stats() if self._norm_stats else None
            step = int(np.max(np.asarray(jax.device_get(self._state.step))))
            version = self._store.publish(pub, step=step, to_host=False,
                                          norm_stats=norm)
            self._versions.append(version)
        return merged

    def run_round(self, n: int) -> dict:
        """One round: ``n`` fused grad steps per replica, then the
        collective merge — the mesh-native analog of N thread replicas
        each doing basis-adopt → n steps → submit."""
        self._fused_steps(n)
        self.merge()
        return {"rounds": self.rounds, "steps": self.steps_done,
                "merge_s": self.last_merge_s,
                "version": self._versions[-1] if self._versions else None}

    # -- inspection ----------------------------------------------------------
    def merged_params(self, to_host: bool = True) -> Any:
        """The last merged param tree (None before the first merge)."""
        if self._merged is None:
            return None
        return jax.device_get(self._merged) if to_host else self._merged

    def state_slice(self, i: int) -> D4PGState:
        """Replica ``i``'s state view (device arrays) — oracle tests
        compare its param trees against the legacy loop's."""
        return _tree_map(lambda x: x[i], self._state)

    @property
    def versions(self) -> list[int]:
        return list(self._versions)

    def stats(self) -> dict:
        return {"n": self.n, "mode": self.mode, "rounds": self.rounds,
                "steps": self.steps_done, "merge_s": self.last_merge_s}

    def close(self) -> None:
        self._chunk_fns.clear()
        self._update_fn = None
