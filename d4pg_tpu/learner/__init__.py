"""Learner core: train state + the single jit'd D4PG update.

The reference's hot loop (``ddpg.py:200-255``, call stack SURVEY.md S2) spans
torch autograd, a host-side numpy projection round-trip, shared-memory
optimizers and python parameter loops. Here the entire update — target
forward, Bellman projection, both losses, gradients, Adam, soft target
update, TD-error outputs for PER — is ONE jit'd XLA computation; only replay
sampling and priority writes stay on host.
"""

from d4pg_tpu.learner.state import D4PGConfig, D4PGState, init_state
from d4pg_tpu.learner.update import (
    act,
    act_deterministic,
    act_ou,
    make_multi_update,
    make_update,
    update_step,
)
from d4pg_tpu.learner.fused import make_fused_chunk, make_sharded_fused_chunk

__all__ = [
    "D4PGConfig",
    "D4PGState",
    "init_state",
    "act",
    "act_deterministic",
    "act_ou",
    "make_multi_update",
    "make_update",
    "update_step",
    "make_fused_chunk",
    "make_sharded_fused_chunk",
]
