"""Running observation normalization (the HER recipe's other half).

The reference never normalizes observations — workable for Pendulum-scale
state vectors, but goal-conditioned manipulation (Fetch/Hand, BASELINE.md
config #5) mixes gripper positions (~1e-1 m), velocities and object poses
whose scales differ by orders of magnitude; DDPG-family learners plateau
without per-dimension standardization (the HER paper normalizes both obs
and goals).

Design for THIS framework's data plane: the ``ReplayService`` drain
thread is the SINGLE writer — every actor (in-process, spawned or
remote) streams RAW rows; the drain folds them into the statistics and
inserts them normalized, so the jit'd learner update, the fused device
path and the sharded data plane are untouched — normalization is a
data-plane concern, not a model concern. Actors and the evaluator hold
read-only views for the policy input: in-process components share the
live ``RunningMeanStd``; remote/spawned actors get a
:class:`FrozenNormalizer` refreshed from (mean, std) piggybacked on the
weight channel. Old replay rows keep the statistics they were written
with (bounded drift, standard for replay normalizers à la VecNormalize);
the estimator state rides the checkpoint ``extra`` payload for exact
resume.

Thread-safe: the drain thread updates concurrently with actor/eval reads.
"""

from __future__ import annotations

import threading

import numpy as np


class RunningMeanStd:
    """Numerically-stable streaming mean/variance (Chan et al. parallel
    Welford merge), vectorized over feature dimensions."""

    def __init__(self, dim: int, clip: float = 5.0, eps: float = 1e-2):
        self.dim = int(dim)
        self.clip = float(clip)
        self.eps = float(eps)
        self._lock = threading.Lock()
        self._count = 0.0
        self._mean = np.zeros(dim, np.float64)
        self._m2 = np.zeros(dim, np.float64)

    def update(self, batch: np.ndarray) -> None:
        """Fold a [B, dim] batch into the running statistics."""
        batch = np.asarray(batch, np.float64).reshape(-1, self.dim)
        n = batch.shape[0]
        if n == 0:
            return
        b_mean = batch.mean(axis=0)
        b_m2 = ((batch - b_mean) ** 2).sum(axis=0)
        self.merge(n, b_mean, b_m2)

    def merge(self, count: float, mean: np.ndarray, m2: np.ndarray) -> None:
        """Chan-merge another estimator's (count, mean, M2) moments into
        this one — the same parallel-Welford combine ``update`` uses for a
        batch, exposed for cross-host statistic aggregation."""
        if count <= 0:
            return
        with self._lock:
            total = self._count + count
            delta = np.asarray(mean, np.float64) - self._mean
            self._mean = self._mean + delta * (count / total)
            self._m2 = (self._m2 + np.asarray(m2, np.float64)
                        + delta**2 * (self._count * count / total))
            self._count = total

    def stats(self) -> tuple[np.ndarray, np.ndarray]:
        """(mean, std) snapshot; std is floored at ``eps`` (HER paper) so
        constant dimensions don't blow up."""
        with self._lock:
            mean = self._mean.copy()
            var = (self._m2 / self._count) if self._count > 0 else np.ones_like(self._m2)
        return mean, np.sqrt(np.maximum(var, self.eps**2))

    def normalize(self, x: np.ndarray) -> np.ndarray:
        """Standardize and clip to ±clip; returns float32."""
        mean, std = self.stats()
        out = (np.asarray(x, np.float64) - mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    # -- checkpoint payload -------------------------------------------------
    def state_dict(self) -> dict:
        with self._lock:
            return {
                "count": float(self._count),
                "mean": self._mean.copy(),
                "m2": self._m2.copy(),
                "clip": self.clip,
                "eps": self.eps,
            }

    def load_state_dict(self, d: dict) -> None:
        with self._lock:
            self._count = float(d["count"])
            self._mean = np.asarray(d["mean"], np.float64).copy()
            self._m2 = np.asarray(d["m2"], np.float64).copy()
            self.clip = float(d.get("clip", self.clip))
            self.eps = float(d.get("eps", self.eps))


class SyncedRunningMeanStd(RunningMeanStd):
    """Multi-host variant (the HER paper's MPI-averaged normalization, as
    one allgather): each host's replay drain folds ONLY into a local
    *delta* estimator; :meth:`sync` — called at a point every process
    reaches in lockstep (the cycle boundary) — allgathers the deltas and
    merges them into the global statistics in process order, leaving all
    hosts with bitwise-identical stats. ``normalize``/``stats``/checkpoint
    payload read the global estimator, so replay rows and acting inputs
    are standardized identically on every host (stats at most one cycle
    stale, same drift bound as the single-host replay normalizer)."""

    def __init__(self, dim: int, clip: float = 5.0, eps: float = 1e-2):
        super().__init__(dim, clip, eps)
        self._delta = RunningMeanStd(dim, clip, eps)

    def update(self, batch: np.ndarray) -> None:
        self._delta.update(batch)

    def sync(self) -> None:
        """Collective: every process MUST call this at the same point."""
        from jax.experimental import multihost_utils

        d = self._delta
        with d._lock:
            payload = np.concatenate(
                [[d._count], d._mean, d._m2]).astype(np.float64)
            d._count = 0.0
            d._mean = np.zeros(self.dim, np.float64)
            d._m2 = np.zeros(self.dim, np.float64)
        gathered = np.asarray(multihost_utils.process_allgather(payload))
        for row in gathered.reshape(-1, 1 + 2 * self.dim):  # process order
            self.merge(row[0], row[1:1 + self.dim], row[1 + self.dim:])


class FrozenNormalizer:
    """Read-only (mean, std) view for actors that receive statistics over
    the weight channel instead of sharing the learner's estimator —
    refreshed via :meth:`set` on each weight pull."""

    def __init__(self, mean: np.ndarray, std: np.ndarray, clip: float = 5.0):
        self.clip = float(clip)
        self.set(mean, std)

    def set(self, mean: np.ndarray, std: np.ndarray,
            clip: float | None = None) -> None:
        self._mean = np.asarray(mean, np.float64)
        self._std = np.maximum(np.asarray(std, np.float64), 1e-8)
        if clip is not None:
            self.clip = float(clip)

    def normalize(self, x: np.ndarray) -> np.ndarray:
        out = (np.asarray(x, np.float64) - self._mean) / self._std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)
