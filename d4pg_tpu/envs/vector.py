"""Vectorized environment pool.

The reference steps ONE env with batch-1 actor inference per step
(``main.py:142-152``, SURVEY.md S3 "hot loop characteristics"). On TPU that
wastes the chip: the pool steps E envs in lockstep so the policy runs one
batched jit'd forward per tick, and observations arrive as contiguous
[E, obs_dim] arrays ready for ``device_put``.

Autoreset semantics: when an env terminates or truncates, the pool resets it
immediately and returns the *reset* observation in ``obs``, with the true
final observation in ``final_obs`` — the shape the n-step folder and replay
need (gymnasium's own autoreset changed across versions; owning it here
keeps the contract stable).
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import numpy as np

from d4pg_tpu.envs.wrappers import rescale_action


class PoolStep(NamedTuple):
    obs: np.ndarray  # [E, obs_dim] next obs (post-autoreset)
    reward: np.ndarray  # [E]
    terminated: np.ndarray  # [E] bool
    truncated: np.ndarray  # [E] bool
    final_obs: np.ndarray  # [E, obs_dim] pre-reset obs (== obs where not done)


class EnvPool:
    """Synchronous pool of E gymnasium-API envs with batched IO."""

    def __init__(self, env_fns: list[Callable[[], object]], seed: int = 0):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        space = self.envs[0].action_space
        self._low = np.asarray(space.low, np.float32)
        self._high = np.asarray(space.high, np.float32)
        self._seed = seed
        self._ep_return = np.zeros(self.num_envs, np.float64)
        self._ep_length = np.zeros(self.num_envs, np.int64)
        self.episode_returns: list[float] = []
        self.episode_lengths: list[int] = []

    @staticmethod
    def _stack(obs_list: list) -> np.ndarray:
        """Stack observations, downcasting floats to float32 but keeping
        integer dtypes (uint8 pixel frames) untouched."""
        out = np.stack(obs_list)
        if np.issubdtype(out.dtype, np.floating) and out.dtype != np.float32:
            out = out.astype(np.float32)
        return out

    def reset(self) -> np.ndarray:
        obs = [e.reset(seed=self._seed + i)[0] for i, e in enumerate(self.envs)]
        self._ep_return[:] = 0.0
        self._ep_length[:] = 0
        return self._stack(obs)

    def step(self, actions: np.ndarray) -> PoolStep:
        """actions in tanh range (-1,1); rescaled per-env to [low, high]."""
        actions = rescale_action(np.asarray(actions), self._low, self._high)
        obs_l, rew_l, term_l, trunc_l, final_l = [], [], [], [], []
        for i, env in enumerate(self.envs):
            obs, r, term, trunc, _ = env.step(actions[i])
            self._ep_return[i] += r
            self._ep_length[i] += 1
            final_l.append(obs)
            if term or trunc:
                self.episode_returns.append(float(self._ep_return[i]))
                self.episode_lengths.append(int(self._ep_length[i]))
                self._ep_return[i] = 0.0
                self._ep_length[i] = 0
                obs, _ = env.reset()
            obs_l.append(obs)
            rew_l.append(r)
            term_l.append(term)
            trunc_l.append(trunc)
        return PoolStep(
            obs=self._stack(obs_l),
            reward=np.asarray(rew_l, np.float32),
            terminated=np.asarray(term_l, bool),
            truncated=np.asarray(trunc_l, bool),
            final_obs=self._stack(final_l),
        )

    def close(self) -> None:
        for env in self.envs:
            close = getattr(env, "close", None)
            if close:
                close()
