"""Per-environment presets: value support, reward scaling, horizons.

Parity: the reference's ``configure_env_params`` hook (``main.py:84-99``,
mostly commented out — only Pendulum's v_min=-300/v_max=0 survives,
``main.py:86-88``) generalized into typed presets for the five
``BASELINE.json`` benchmark configs (BASELINE.md).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EnvPreset:
    env_id: str
    v_min: float
    v_max: float
    n_atoms: int = 51
    reward_scale: float = 1.0  # rewards are multiplied by this before replay
    max_steps: int = 1000
    n_step: int = 3
    pixels: bool = False
    goal_conditioned: bool = False


PRESETS: dict[str, EnvPreset] = {
    # DELIBERATE DIVERGENCE from the reference: main.py:86-88 sets
    # v_min=-300/v_max=0 and no reward scaling; this preset ships
    # v_min=-100 with rewards scaled x0.1 — a tighter support over the
    # scaled returns that resolves the distribution better (atoms 2 apart
    # instead of 6) and solves Pendulum faster in our runs. The reference's
    # exact values are one flag away: --strict_reference 1 (or --v_min
    # -300 --reward_scale 1).
    "Pendulum-v1": EnvPreset(
        "Pendulum-v1", v_min=-100.0, v_max=0.0, reward_scale=0.1, max_steps=200
    ),
    # BASELINE.md configs 2-5
    # support reaches below zero: a random/early HalfCheetah policy earns
    # negative discounted returns (~-100), which a [0, vmax] support would
    # clip into the bottom atom and flatten early TD signal
    "HalfCheetah-v4": EnvPreset("HalfCheetah-v4", v_min=-100.0, v_max=1000.0),
    "Humanoid-v4": EnvPreset("Humanoid-v4", v_min=0.0, v_max=800.0),
    "cheetah-run-pixels": EnvPreset(
        "cheetah-run-pixels", v_min=0.0, v_max=1000.0, pixels=True
    ),
    # dm_control state-based tasks. Suite rewards are in [0, 1] per PHYSICS
    # step and the adapter sums them over action_repeat=4, so the per-tick
    # reward reaches 4 and the discounted return 4/(1-0.99) = 400.
    "dmc:cheetah-run": EnvPreset("dmc:cheetah-run", v_min=0.0, v_max=400.0,
                                 max_steps=250),
    "dmc:walker-walk": EnvPreset("dmc:walker-walk", v_min=0.0, v_max=400.0,
                                 max_steps=250),
    "dmc:cartpole-swingup": EnvPreset(
        "dmc:cartpole-swingup", v_min=0.0, v_max=400.0, max_steps=250
    ),
    "AdroitHandDoor-v1": EnvPreset(
        "AdroitHandDoor-v1", v_min=-100.0, v_max=300.0, goal_conditioned=False
    ),
    # goal-conditioned sparse-reward family for the HER path. Which version
    # suffix is registered depends on the installed gymnasium-robotics
    # (v2 on <=1.2, v4 on >=1.4 — the one on this image); both presets are
    # kept so either id resolves.
    "FetchReach-v2": EnvPreset(
        "FetchReach-v2", v_min=-50.0, v_max=0.0, max_steps=50, n_step=1,
        goal_conditioned=True,
    ),
    "FetchReach-v4": EnvPreset(
        "FetchReach-v4", v_min=-50.0, v_max=0.0, max_steps=50, n_step=1,
        goal_conditioned=True,
    ),
    "FetchPush-v4": EnvPreset(
        "FetchPush-v4", v_min=-50.0, v_max=0.0, max_steps=50, n_step=1,
        goal_conditioned=True,
    ),
}


# The reference's own per-env hook values (main.py:84-99; only Pendulum is
# live there). Selected by --strict_reference for parity experiments.
PRESETS_STRICT: dict[str, EnvPreset] = {
    "Pendulum-v1": EnvPreset(
        "Pendulum-v1", v_min=-300.0, v_max=0.0, reward_scale=1.0,
        max_steps=200,
    ),
}


def has_preset(env_id: str, strict: bool = False) -> bool:
    """True when a CURATED preset exists for ``env_id`` (the permissive
    fallback of :func:`get_preset` does not count — its field defaults are
    placeholders, not per-env tuning)."""
    return env_id in PRESETS or (strict and env_id in PRESETS_STRICT)


def get_preset(env_id: str, strict: bool = False) -> EnvPreset:
    """Preset lookup with a permissive default (wide symmetric support).
    ``strict=True`` prefers the reference's own values where they exist."""
    if strict and env_id in PRESETS_STRICT:
        return PRESETS_STRICT[env_id]
    if env_id in PRESETS:
        return PRESETS[env_id]
    return EnvPreset(env_id, v_min=-500.0, v_max=500.0)
