"""Per-environment presets: value support, reward scaling, horizons.

Parity: the reference's ``configure_env_params`` hook (``main.py:84-99``,
mostly commented out — only Pendulum's v_min=-300/v_max=0 survives,
``main.py:86-88``) generalized into typed presets for the five
``BASELINE.json`` benchmark configs (BASELINE.md).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class EnvPreset:
    env_id: str
    v_min: float
    v_max: float
    n_atoms: int = 51
    reward_scale: float = 1.0  # rewards are multiplied by this before replay
    max_steps: int = 1000
    n_step: int = 3
    pixels: bool = False
    goal_conditioned: bool = False


PRESETS: dict[str, EnvPreset] = {
    # reference preset (main.py:86-88)
    "Pendulum-v1": EnvPreset(
        "Pendulum-v1", v_min=-100.0, v_max=0.0, reward_scale=0.1, max_steps=200
    ),
    # BASELINE.md configs 2-5
    "HalfCheetah-v4": EnvPreset("HalfCheetah-v4", v_min=0.0, v_max=1000.0),
    "Humanoid-v4": EnvPreset("Humanoid-v4", v_min=0.0, v_max=800.0),
    "cheetah-run-pixels": EnvPreset(
        "cheetah-run-pixels", v_min=0.0, v_max=1000.0, pixels=True
    ),
    "AdroitHandDoor-v1": EnvPreset(
        "AdroitHandDoor-v1", v_min=-100.0, v_max=300.0, goal_conditioned=False
    ),
    # goal-conditioned sparse-reward family for the HER path
    "FetchReach-v2": EnvPreset(
        "FetchReach-v2", v_min=-50.0, v_max=0.0, max_steps=50, n_step=1,
        goal_conditioned=True,
    ),
}


def get_preset(env_id: str) -> EnvPreset:
    """Preset lookup with a permissive default (wide symmetric support)."""
    if env_id in PRESETS:
        return PRESETS[env_id]
    return EnvPreset(env_id, v_min=-500.0, v_max=500.0)
