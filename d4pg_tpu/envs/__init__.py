"""Environment layer: gymnasium adapters, goal handling, HER, vector pools.

Parity targets: ``NormalizeAction`` (``normalize_env.py:3-14``), the
goal-conditioned dict-obs handling + HER relabeling hardwired into the
reference's collection loop (``main.py:137-185``), and per-env value-support
presets (``main.py:84-99``). All acting-side machinery is vectorized: the
reference steps one env with batch-1 inference per step (SURVEY.md S3);
here a pool of E envs steps in lockstep against one batched jit'd policy
call.
"""

from d4pg_tpu.envs.wrappers import (
    GoalObs,
    flatten_goal_obs,
    rescale_action,
    RescaleActionWrapper,
)
from d4pg_tpu.envs.her import her_relabel
from d4pg_tpu.envs.vector import EnvPool
from d4pg_tpu.envs.presets import EnvPreset, PRESETS, get_preset
from d4pg_tpu.envs.fake import FakeGoalEnv, PixelPointEnv, PointMassEnv, SlowEnv

__all__ = [
    "GoalObs",
    "flatten_goal_obs",
    "rescale_action",
    "RescaleActionWrapper",
    "her_relabel",
    "EnvPool",
    "EnvPreset",
    "PRESETS",
    "get_preset",
    "FakeGoalEnv",
    "PixelPointEnv",
    "PointMassEnv",
    "SlowEnv",
]
