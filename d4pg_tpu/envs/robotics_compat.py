"""MuJoCo-3 compatibility for gymnasium-robotics Adroit / Shadow-Hand XMLs.

BASELINE.md config #5 (Adroit/Shadow-Hand manipulation) ships MJCF files
written for MuJoCo 2.x: they carry an ``<option apirate="...">`` attribute
that the MuJoCo 3 schema rejects, so every ``gym.make`` of an Adroit/Hand
env dies in XML parsing on this image. The attribute only ever controlled
the remote-render API rate — it has no physics effect — so stripping it is
semantics-preserving.

:func:`install` hooks ``mujoco.MjModel.from_xml_path`` (the single loading
funnel used by both gymnasium's ``MujocoEnv`` and gymnasium-robotics'
``MujocoRobotEnv``): when a model file contains ``apirate``, the loader is
redirected to a shadow copy of its directory in which every ``.xml`` has
the attribute stripped and every other entry (mesh/texture dirs) is
symlinked back to the original package assets. Clean files load through
the original code path untouched.
"""

from __future__ import annotations

import hashlib
import os
import re
import stat
import tempfile

_APIRATE = re.compile(rb'\s+apirate="[^"]*"')
_shadow_dirs: dict[str, str] = {}
_dir_needs_patch: dict[str, bool] = {}
_installed = False


def _needs_patch(src_dir: str) -> bool:
    """True if any XML in ``src_dir`` carries apirate — the attribute can
    live in an ``<include>``d sibling (adroit_assets.xml) rather than the
    model file itself, so the whole directory is the unit of patching."""
    cached = _dir_needs_patch.get(src_dir)
    if cached is not None:
        return cached
    found = False
    try:
        for name in os.listdir(src_dir):
            if name.endswith(".xml"):
                with open(os.path.join(src_dir, name), "rb") as f:
                    if b"apirate" in f.read():
                        found = True
                        break
    except OSError:
        found = False
    _dir_needs_patch[src_dir] = found
    return found


def _assets_root(src_dir: str) -> str:
    """Topmost ``assets`` ancestor of ``src_dir`` (the MJCF files reference
    meshes through ``../``-relative paths that stay inside the package's
    assets tree, so that tree is the unit of mirroring); ``src_dir`` itself
    when no such ancestor exists."""
    cur = src_dir
    root = src_dir
    while True:
        parent = os.path.dirname(cur)
        if parent == cur:
            break
        if os.path.basename(cur) == "assets":
            root = cur
        cur = parent
    return root


def _tree_fingerprint(root: str) -> bytes:
    """Content fingerprint of the assets tree: path + (size, mtime) of every
    XML under ``root``. Folding this into the shadow-dir tag means an
    in-place package upgrade (same install path, new MJCF) gets a FRESH
    mirror instead of being served stale patched XML — the mirror trusts
    existing entries, so the tag must change whenever the sources do."""
    parts = [root.encode()]
    for cur, _dirs, files in sorted(os.walk(root)):
        for name in sorted(files):
            if not name.endswith(".xml"):
                continue
            path = os.path.join(cur, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            parts.append(
                f"{os.path.relpath(path, root)}:{st.st_size}:{st.st_mtime_ns}".encode()
            )
    return b"\0".join(parts)


def _prune_stale_mirrors(root_tag: str, keep: str,
                         min_age_s: float = 3600.0) -> None:
    """Remove this uid's mirrors of the SAME assets tree whose content tag
    is superseded — each source change (package upgrade) mints a new tag,
    and nothing else ever deletes the orphaned tree of patched XMLs +
    symlinks. Mirrors of other trees (different ``root_tag``) may be in
    concurrent use by sibling processes and are never touched.

    In-use guard (ADVICE r3): a long-lived sibling process started BEFORE
    an in-place package upgrade still holds the old-tag path in its
    module-level ``_shadow_dirs`` cache and re-reads MJCF from it at every
    env construction; deleting it under that process breaks those
    constructions. Every process therefore holds a SHARED flock on its
    mirror's ``.inuse`` file for its lifetime (:func:`_hold_mirror_lock`);
    the pruner only removes a mirror whose lock it can take exclusively —
    crashed holders release the lock automatically. The mtime age gate
    stays as a backstop for mirrors created by versions that predate the
    lock file."""
    import fcntl
    import glob
    import shutil
    import time

    pattern = os.path.join(
        tempfile.gettempdir(),
        f"d4pg-tpu-mjcf-compat-{os.getuid()}-{root_tag}-*",
    )
    now = time.time()
    for path in glob.glob(pattern):
        if path == keep:
            continue
        try:
            st = os.lstat(path)
            if st.st_uid != os.getuid():
                continue
            # mtime of the mirror root moves on directory mutation only;
            # young mirror == a sibling may still be mid-creation of it
            if now - st.st_mtime < min_age_s:
                continue
            lock_path = os.path.join(path, _INUSE_NAME)
            fd = None
            try:
                fd = os.open(lock_path, os.O_RDONLY)
            except OSError:
                pass  # no lock file (pre-lock-version mirror): age decides
            if fd is not None:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    continue  # a live sibling holds it: in use, skip
                finally:
                    os.close(fd)
            shutil.rmtree(path, ignore_errors=True)
        except OSError:
            pass


_INUSE_NAME = ".inuse"
# fds of held mirror locks, keyed by mirror root; intentionally kept open
# for process lifetime so the pruner in sibling processes sees the mirror
# as in use (released by the kernel on exit/crash)
_mirror_lock_fds: dict = {}


def _hold_mirror_lock(shadow_root: str) -> None:
    """Take (and keep) a shared flock on the mirror's ``.inuse`` file so
    concurrent pruners never delete a mirror this process may still read
    MJCF from."""
    if shadow_root in _mirror_lock_fds:
        return
    import fcntl

    lock_path = os.path.join(shadow_root, _INUSE_NAME)
    try:
        fd = os.open(lock_path, os.O_CREAT | os.O_RDONLY, 0o600)
        fcntl.flock(fd, fcntl.LOCK_SH)
        _mirror_lock_fds[shadow_root] = fd
    except OSError:
        pass  # lock is best-effort; the age gate still applies


def _shadow_dir(src_dir: str) -> str:
    """Patched mirror of ``src_dir``: the whole assets tree is mirrored once
    (XMLs copied with apirate stripped, meshes/textures symlinked), and the
    corresponding shadow path for ``src_dir`` is returned. Idempotent, so a
    partial mirror left by a crashed process just gets finished."""
    cached = _shadow_dirs.get(src_dir)
    if cached is not None:
        return cached
    root = _assets_root(src_dir)
    # two-part tag: <root-path-hash>-<content-hash>. The content part makes
    # an in-place package upgrade mint a fresh mirror (existing entries are
    # trusted, so the tag must change whenever the sources do); the root
    # part scopes stale-mirror pruning to THIS assets tree, so concurrent
    # mirrors of other packages' trees are never touched.
    root_tag = hashlib.sha256(root.encode()).hexdigest()[:12]
    content_tag = hashlib.sha256(_tree_fingerprint(root)).hexdigest()[:12]
    tag = f"{root_tag}-{content_tag}"
    # Per-uid, mode-0700, ownership-verified: the path is predictable, so
    # on a multi-user host another user could otherwise pre-create it and
    # have MuJoCo load attacker-controlled MJCF (existing entries are
    # trusted and skipped below). Sharing WITHIN a uid is intentional —
    # --actor_procs workers reuse one mirror.
    shadow_root = os.path.join(
        tempfile.gettempdir(), f"d4pg-tpu-mjcf-compat-{os.getuid()}-{tag}"
    )
    _prune_stale_mirrors(root_tag, keep=shadow_root)
    os.makedirs(shadow_root, mode=0o700, exist_ok=True)
    st = os.lstat(shadow_root)  # lstat: a planted symlink must not pass by
    # pointing at a directory the victim owns
    if st.st_uid != os.getuid() or not stat.S_ISDIR(st.st_mode):
        # someone else owns (or symlinked) the predictable path: fall back
        # to a private unshared mirror rather than trusting its contents
        shadow_root = tempfile.mkdtemp(prefix="d4pg-tpu-mjcf-compat-")
    else:
        # mark the shared mirror in use for this process's lifetime so
        # sibling pruners (a later package upgrade mints a new tag) leave
        # it alone while we may still re-read its MJCF
        _hold_mirror_lock(shadow_root)
    for cur, dirs, files in os.walk(root):
        dst_cur = os.path.join(shadow_root, os.path.relpath(cur, root))
        os.makedirs(dst_cur, exist_ok=True)
        for name in files:
            src_path = os.path.join(cur, name)
            dst_path = os.path.join(dst_cur, name)
            if os.path.lexists(dst_path):
                # another process (--actor_procs spawns several, all
                # mirroring the same shared /tmp tree at startup) already
                # materialized this entry; package assets are immutable,
                # so an existing file is always complete and current
                continue
            if name.endswith(".xml"):
                with open(src_path, "rb") as f:
                    data = _APIRATE.sub(b"", f.read())
                # write-then-rename so concurrent readers never observe a
                # truncated XML
                tmp_path = f"{dst_path}.{os.getpid()}.tmp"
                with open(tmp_path, "wb") as f:
                    f.write(data)
                os.replace(tmp_path, dst_path)
            else:
                try:
                    os.symlink(src_path, dst_path)
                except FileExistsError:
                    pass  # lost the race to a concurrent mirror — fine
    dst = os.path.normpath(
        os.path.join(shadow_root, os.path.relpath(src_dir, root))
    )
    _shadow_dirs[src_dir] = dst
    return dst


def install() -> None:
    """Idempotently hook ``MjModel.from_xml_path`` with the apirate shim."""
    global _installed
    if _installed:
        return
    import mujoco

    orig = mujoco.MjModel.from_xml_path

    def from_xml_path(xml_path, *args, **kwargs):
        src_dir = os.path.dirname(os.path.abspath(xml_path))
        if _needs_patch(src_dir):
            xml_path = os.path.join(
                _shadow_dir(src_dir), os.path.basename(xml_path)
            )
        return orig(xml_path, *args, **kwargs)

    mujoco.MjModel.from_xml_path = staticmethod(from_xml_path)
    _installed = True
