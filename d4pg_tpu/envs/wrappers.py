"""Action rescaling and goal-observation flattening.

Parity: ``NormalizeAction`` (``normalize_env.py:3-14``) — the affine map
between the policy's tanh range (-1, 1) and the env's ``[low, high]`` action
box — and the dict-obs concatenation the reference hardwires into its
collection loop (``state['observation']`` + ``state['desired_goal']``,
``main.py:144``), here as an explicit, reusable adapter.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


def rescale_action(action: np.ndarray, low: np.ndarray, high: np.ndarray) -> np.ndarray:
    """tanh range (-1, 1) -> [low, high] (``normalize_env.py:5-8``)."""
    return low + (action + 1.0) * 0.5 * (high - low)


def inverse_rescale_action(
    action: np.ndarray, low: np.ndarray, high: np.ndarray
) -> np.ndarray:
    """[low, high] -> (-1, 1) (``normalize_env.py:10-14``)."""
    return 2.0 * (action - low) / (high - low) - 1.0


class RescaleActionWrapper:
    """gymnasium wrapper form of ``rescale_action`` for single envs."""

    def __init__(self, env):
        self.env = env
        self.low = np.asarray(env.action_space.low, np.float32)
        self.high = np.asarray(env.action_space.high, np.float32)

    def reset(self, **kw):
        return self.env.reset(**kw)

    def step(self, action):
        return self.env.step(rescale_action(np.asarray(action), self.low, self.high))

    def __getattr__(self, name):
        return getattr(self.env, name)


class GoalObs(NamedTuple):
    """Structured goal-conditioned observation (gymnasium GoalEnv dict)."""

    observation: np.ndarray
    achieved_goal: np.ndarray
    desired_goal: np.ndarray


def flatten_goal_obs(obs) -> np.ndarray:
    """Concatenate observation and desired goal into the policy input
    (``main.py:144``). Accepts a GoalObs, a gymnasium dict, or a plain
    array (returned unchanged)."""
    if isinstance(obs, GoalObs):
        return np.concatenate([obs.observation, obs.desired_goal], axis=-1)
    if isinstance(obs, dict):
        return np.concatenate([obs["observation"], obs["desired_goal"]], axis=-1)
    return np.asarray(obs)


class FrameStack:
    """Stack the last ``k`` pixel observations along the channel axis.

    Pixel control from a SINGLE frame is a POMDP — velocities are
    invisible, so tasks like cartpole-swingup (which way is the pole
    moving?) are structurally unlearnable. Stacking k frames restores the
    Markov property the state-vector path gets for free; every published
    pixel-control baseline (DQN's 4-stack; DrQ/D4PG-pixels' 3-stack) does
    this. The reference has no pixel path at all (``models.py:15`` is
    state-only), so this wrapper has no reference analogue.

    [H, W, C] -> [H, W, C*k], newest frame LAST (channels-concatenated);
    ``reset`` fills the buffer with k copies of the first frame. uint8
    in, uint8 out — the replay ring stores stacked rows directly.
    """

    def __init__(self, env, k: int):
        from collections import deque

        if k < 1:
            raise ValueError(f"frame_stack must be >= 1, got {k}")
        self.env = env
        self._k = int(k)
        self._frames: "deque" = deque(maxlen=self._k)
        space = env.observation_space
        if len(space.shape) != 3:
            raise ValueError(
                f"FrameStack wraps pixel [H, W, C] observations, got "
                f"shape {space.shape}")
        h, w, c = space.shape
        import gymnasium.spaces

        # duck-typed spaces (the fake test envs) may lack .dtype; the
        # bound arrays always carry one (possibly wider than the actual
        # frames — dims/dtype downstream come from a real reset obs in
        # train.infer_dims, not from this advertisement). tile, not
        # repeat: the data layout is whole frames concatenated
        # [c0,c1,c2, c0,c1,c2, ...], so per-channel bounds must tile in
        # the same order.
        dtype = getattr(space, "dtype", None) or space.low.dtype
        self.observation_space = gymnasium.spaces.Box(
            low=np.tile(np.asarray(space.low), (1, 1, self._k)),
            high=np.tile(np.asarray(space.high), (1, 1, self._k)),
            shape=(h, w, c * self._k),
            dtype=dtype,
        )
        self.action_space = env.action_space

    def _stacked(self):
        return np.concatenate(list(self._frames), axis=-1)

    def reset(self, **kw):
        obs, info = self.env.reset(**kw)
        for _ in range(self._k):
            self._frames.append(obs)
        return self._stacked(), info

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        self._frames.append(obs)
        return self._stacked(), reward, terminated, truncated, info

    def close(self):
        if hasattr(self.env, "close"):
            self.env.close()
