"""Action rescaling and goal-observation flattening.

Parity: ``NormalizeAction`` (``normalize_env.py:3-14``) — the affine map
between the policy's tanh range (-1, 1) and the env's ``[low, high]`` action
box — and the dict-obs concatenation the reference hardwires into its
collection loop (``state['observation']`` + ``state['desired_goal']``,
``main.py:144``), here as an explicit, reusable adapter.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


def rescale_action(action: np.ndarray, low: np.ndarray, high: np.ndarray) -> np.ndarray:
    """tanh range (-1, 1) -> [low, high] (``normalize_env.py:5-8``)."""
    return low + (action + 1.0) * 0.5 * (high - low)


def inverse_rescale_action(
    action: np.ndarray, low: np.ndarray, high: np.ndarray
) -> np.ndarray:
    """[low, high] -> (-1, 1) (``normalize_env.py:10-14``)."""
    return 2.0 * (action - low) / (high - low) - 1.0


class RescaleActionWrapper:
    """gymnasium wrapper form of ``rescale_action`` for single envs."""

    def __init__(self, env):
        self.env = env
        self.low = np.asarray(env.action_space.low, np.float32)
        self.high = np.asarray(env.action_space.high, np.float32)

    def reset(self, **kw):
        return self.env.reset(**kw)

    def step(self, action):
        return self.env.step(rescale_action(np.asarray(action), self.low, self.high))

    def __getattr__(self, name):
        return getattr(self.env, name)


class GoalObs(NamedTuple):
    """Structured goal-conditioned observation (gymnasium GoalEnv dict)."""

    observation: np.ndarray
    achieved_goal: np.ndarray
    desired_goal: np.ndarray


def flatten_goal_obs(obs) -> np.ndarray:
    """Concatenate observation and desired goal into the policy input
    (``main.py:144``). Accepts a GoalObs, a gymnasium dict, or a plain
    array (returned unchanged)."""
    if isinstance(obs, GoalObs):
        return np.concatenate([obs.observation, obs.desired_goal], axis=-1)
    if isinstance(obs, dict):
        return np.concatenate([obs["observation"], obs["desired_goal"]], axis=-1)
    return np.asarray(obs)
