"""Fake environments for tests and benchmarks — no MuJoCo required.

SURVEY.md §4: "a fake-env fixture so distributed tests need no MuJoCo".
Two families:

  - ``PointMassEnv``: dense-reward 2-D point mass with gym-style Box spaces;
    a stand-in for the dense continuous-control configs.
  - ``FakeGoalEnv``: goal-conditioned sparse-reward (-1/0) point mass with
    dict observations and ``compute_reward``, the shape the reference's HER
    loop assumes (``main.py:144-184``); a stand-in for Fetch/Adroit.
"""

from __future__ import annotations

import numpy as np


class _Box:
    def __init__(self, low, high, shape):
        self.low = np.full(shape, low, np.float32)
        self.high = np.full(shape, high, np.float32)
        self.shape = shape


class SlowEnv:
    """Wrap an env with a fixed wall-clock cost per ``step()``.

    Emulates a physics-bound env (MuJoCo steps cost ~1-40 ms of host CPU)
    without needing MuJoCo: the sleep holds the actor's *rate* at the
    wrapped cost while leaving its CPU demand near zero, so N throttled
    actor processes on one machine measure the TRANSPORT/INGEST plane's
    scaling (analysis/actor_scaling.py), not host-core contention — the
    regime the reference's N-worker fan-out (``main.py:399-405``) actually
    runs in, where workers are env-bound and the shared plane is the
    question."""

    def __init__(self, env, step_seconds: float):
        self._env = env
        self._step_seconds = step_seconds
        self.action_space = env.action_space
        self.observation_space = env.observation_space

    def reset(self, seed=None, **kw):
        return self._env.reset(seed=seed, **kw)

    def step(self, action):
        import time

        time.sleep(self._step_seconds)
        return self._env.step(action)

    def close(self):
        self._env.close()

    def __getattr__(self, name):
        return getattr(self._env, name)


class PointMassEnv:
    """2-D point mass: action = acceleration, reward = -|pos| - 0.01|a|^2."""

    def __init__(self, horizon: int = 100, seed: int = 0):
        self.horizon = horizon
        self.action_space = _Box(-1.0, 1.0, (2,))
        self.observation_space = _Box(-np.inf, np.inf, (4,))
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._pos = np.zeros(2, np.float32)
        self._vel = np.zeros(2, np.float32)

    def reset(self, seed=None, **kw):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(-1, 1, 2).astype(np.float32)
        self._vel = np.zeros(2, np.float32)
        self._t = 0
        return self._obs(), {}

    def _obs(self):
        return np.concatenate([self._pos, self._vel]).astype(np.float32)

    def step(self, action):
        action = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        self._vel = 0.9 * self._vel + 0.1 * action
        self._pos = self._pos + self._vel
        self._t += 1
        reward = float(-np.linalg.norm(self._pos) - 0.01 * np.sum(action**2))
        truncated = self._t >= self.horizon
        return self._obs(), reward, False, truncated, {}

    def close(self):
        pass


class PixelPointEnv:
    """Pixel-observation point mass: the agent is a bright blob on an
    [H, W, 3] uint8 frame; action = velocity; reward = -|pos - center|.
    Stand-in for the DM-Control-from-pixels config (BASELINE.md #4) so the
    conv-encoder path tests without dm_control/MuJoCo."""

    def __init__(self, size: int = 16, horizon: int = 50, seed: int = 0):
        self.size = int(size)
        self.horizon = horizon
        self.action_space = _Box(-1.0, 1.0, (2,))
        self.observation_space = _Box(0, 255, (self.size, self.size, 3))
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._pos = np.zeros(2, np.float32)  # in [0, 1]^2

    def _obs(self):
        frame = np.zeros((self.size, self.size, 3), np.uint8)
        i = int(np.clip(self._pos[0] * (self.size - 1), 0, self.size - 1))
        j = int(np.clip(self._pos[1] * (self.size - 1), 0, self.size - 1))
        frame[i, j] = 255
        return frame

    def reset(self, seed=None, **kw):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(0, 1, 2).astype(np.float32)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        action = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        self._pos = np.clip(self._pos + 0.1 * action, 0.0, 1.0)
        self._t += 1
        reward = float(-np.linalg.norm(self._pos - 0.5))
        truncated = self._t >= self.horizon
        return self._obs(), reward, False, truncated, {}

    def close(self):
        pass


class FakeGoalEnv:
    """Goal-conditioned point reach with sparse -1/0 reward and dict obs."""

    def __init__(self, horizon: int = 50, tol: float = 0.15, seed: int = 0):
        self.horizon = horizon
        self.tol = tol
        self.action_space = _Box(-1.0, 1.0, (2,))
        self.observation_space = _Box(-np.inf, np.inf, (2,))
        self.goal_dim = 2
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._pos = np.zeros(2, np.float32)
        self._goal = np.zeros(2, np.float32)

    def compute_reward(self, achieved_goal, desired_goal, info=None):
        """Sparse -1/0 (``env.compute_reward`` contract, ``main.py:177``).
        Vectorized over leading dims."""
        d = np.linalg.norm(
            np.asarray(achieved_goal) - np.asarray(desired_goal), axis=-1
        )
        return -(d > self.tol).astype(np.float32)

    def _obs(self):
        return {
            "observation": self._pos.copy(),
            "achieved_goal": self._pos.copy(),
            "desired_goal": self._goal.copy(),
        }

    def reset(self, seed=None, **kw):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = self._rng.uniform(-1, 1, 2).astype(np.float32)
        self._goal = self._rng.uniform(-1, 1, 2).astype(np.float32)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        action = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        self._pos = self._pos + 0.2 * action
        self._t += 1
        reward = float(self.compute_reward(self._pos, self._goal))
        success = reward == 0.0
        truncated = self._t >= self.horizon
        return self._obs(), reward, bool(success), truncated, {"is_success": success}

    def close(self):
        pass
