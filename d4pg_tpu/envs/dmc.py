"""DM-Control suite adapter: pixel observations through the gymnasium API.

BASELINE.md config #4 is "DM-Control cheetah-run from pixels (conv
encoder)". The reference has no dm_control path at all (it is gym-only,
``main.py:68``); this adapter exposes any ``dm_control.suite`` task as the
same five-tuple gymnasium-style env the rest of the framework consumes
(``EnvPool``, ``train.make_env_fn``), with:

  - pixel observations rendered on the physics camera as [H, W, 3] uint8
    (the shape ``train.infer_dims`` routes to the conv-encoder path), or
    flattened state observations when ``pixels=False``;
  - an action-repeat knob (standard for pixel control: the policy acts
    every ``action_repeat`` physics control steps and rewards are summed),
    keeping the effective episode length TPU-friendly;
  - dm_control's time-limit end reported as gymnasium ``truncated`` (the
    suite tasks never terminate early, so ``terminated`` is always False
    and bootstrapping through the horizon is correct).

Rendering needs an offscreen GL backend; EGL is the one present on this
image, so it is defaulted here before MuJoCo loads (set ``MUJOCO_GL``
yourself to override).
"""

from __future__ import annotations

import os

import numpy as np


def _box(low, high, shape, dtype=np.float32):
    from gymnasium.spaces import Box  # real gymnasium space: wrappers may
    # read .dtype / .contains, which a hand-rolled shim would lack

    return Box(
        low=np.broadcast_to(np.asarray(low, dtype), shape),
        high=np.broadcast_to(np.asarray(high, dtype), shape),
        dtype=dtype,
    )


class DMControlEnv:
    """One ``dm_control.suite`` task behind the gymnasium five-tuple API."""

    def __init__(
        self,
        domain: str,
        task: str,
        pixels: bool = True,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        action_repeat: int = 4,
        seed: int = 0,
    ):
        os.environ.setdefault("MUJOCO_GL", "egl")
        from dm_control import suite  # lazy: only dmc envs pay the import

        self._suite = suite
        self._domain, self._task = domain, task
        self._pixels = pixels
        self._height, self._width, self._camera = height, width, camera_id
        self._repeat = max(1, int(action_repeat))
        self._env = suite.load(domain, task, task_kwargs={"random": seed})

        spec = self._env.action_spec()
        self.action_space = _box(spec.minimum, spec.maximum, spec.shape)
        if pixels:
            self.observation_space = _box(
                0, 255, (height, width, 3), dtype=np.uint8
            )
        else:
            dim = sum(
                int(np.prod(v.shape)) if v.shape else 1
                for v in self._env.observation_spec().values()
            )
            self.observation_space = _box(-np.inf, np.inf, (dim,))

    def _obs(self, timestep):
        if self._pixels:
            return self._env.physics.render(
                height=self._height, width=self._width, camera_id=self._camera
            )
        parts = [
            np.atleast_1d(np.asarray(v, np.float32)).ravel()
            for v in timestep.observation.values()
        ]
        return np.concatenate(parts).astype(np.float32)

    def reset(self, seed=None, **kw):
        if seed is not None:
            # Re-seed IN PLACE: rebuilding via suite.load would leak the
            # previous native physics (and EGL context on the pixel path)
            # and recompile the MJCF — per seeded reset, i.e. per eval
            # trial. dm_control tasks draw all episode randomness from
            # task.random (dm_control.rl.control.Environment hands it to
            # initialize_episode), so swapping the RandomState is the whole
            # seeding story. The attribute is private, so verify it exists
            # before assigning — a dm_control rename must fail loudly (a
            # silent setattr would de-seed every eval trial), falling back
            # to a full rebuild through the public constructor.
            if hasattr(self._env.task, "_random"):
                self._env.task._random = np.random.RandomState(seed)
            else:  # dm_control renamed the field: rebuild via the public API
                self._env.close()
                self._env = self._suite.load(
                    self._domain, self._task, task_kwargs={"random": seed}
                )
        ts = self._env.reset()
        return self._obs(ts), {}

    def step(self, action):
        action = np.clip(
            np.asarray(action, np.float32),
            self.action_space.low,
            self.action_space.high,
        )
        reward, ts = 0.0, None
        for _ in range(self._repeat):
            ts = self._env.step(action)
            reward += float(ts.reward or 0.0)
            if ts.last():
                break
        # suite tasks end only by time limit -> truncation, never termination
        return self._obs(ts), reward, False, bool(ts.last()), {}

    def close(self):
        self._env.close()


def parse_dmc_id(env_id: str):
    """``'dmc:cheetah-run'`` / ``'dmc:cheetah-run-pixels'`` /
    ``'cheetah-run-pixels'`` -> (domain, task, pixels) or None if the id is
    not a dm_control spec."""
    name = env_id[4:] if env_id.startswith("dmc:") else env_id
    pixels = name.endswith("-pixels")
    if pixels:
        name = name[: -len("-pixels")]
    elif not env_id.startswith("dmc:"):
        return None
    if "-" not in name:
        return None
    domain, task = name.split("-", 1)
    return domain, task, pixels
