"""Hindsight Experience Replay: future-strategy relabeling.

Parity: the reference's HER block (``main.py:154-184``): with probability
``her_ratio`` per transition, substitute a goal achieved at a *future*
timestep of the same episode for the desired goal, recompute the reward with
the env's ``compute_reward``, and store the relabeled transition alongside
the original.

The reference has a bug here: the relabeled transition stores the Python
loop variable ``action`` left over from the rollout (the episode's LAST
action) instead of the transition's own ``episode_buffer[t][1]``
(``main.py:184``). SURVEY.md §7 capability 7 mandates the fix — this
implementation indexes every field by ``t``.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from d4pg_tpu.replay.uniform import TransitionBatch


def her_relabel(
    observation: np.ndarray,  # [T, obs_dim]   raw (goal-free) observations
    achieved_goal: np.ndarray,  # [T+1, goal_dim] achieved goals incl. final
    action: np.ndarray,  # [T, act_dim]
    next_observation: np.ndarray,  # [T, obs_dim]
    compute_reward: Callable[..., np.ndarray],  # (ag, dg, info) GoalEnv API
    rng: np.random.Generator,
    her_ratio: float = 0.8,
    gamma: float = 0.99,
) -> TransitionBatch:
    """Relabel an episode with future achieved goals.

    For each selected t, draw k uniform in [t+1, T] (the reference draws
    ``randint(t, T)+1`` i.e. future inclusive of the next step,
    ``main.py:171-173``) and use ``achieved_goal[k]`` as the substitute
    desired goal. Rewards are recomputed via ``compute_reward(achieved_goal
    [t+1], new_goal)`` and transitions are terminal when the relabeled
    reward indicates success (reward == 0 under the standard sparse
    -1/0 convention, matching ``done = info['is_success']``,
    ``main.py:148``).

    Returns a TransitionBatch of ONLY the relabeled transitions, with policy
    inputs already goal-concatenated ([obs, goal]) and ``discount`` folded
    as gamma * (1 - done) (1-step; n-step folding happens upstream for the
    originals, HER transitions are 1-step like the reference's).
    """
    T = action.shape[0]
    sel = np.nonzero(rng.random(T) < her_ratio)[0]
    if sel.size == 0:
        obs_dim = observation.shape[-1] + achieved_goal.shape[-1]
        z = np.zeros((0,), np.float32)
        return TransitionBatch(
            obs=np.zeros((0, obs_dim), np.float32),
            action=np.zeros((0, action.shape[-1]), np.float32),
            reward=z,
            next_obs=np.zeros((0, obs_dim), np.float32),
            done=z,
            discount=z,
        )
    # future index k in [t+1, T] per selected t (vectorized)
    k = rng.integers(sel + 1, T + 1)  # inclusive upper: achieved_goal has T+1 rows
    new_goal = achieved_goal[k]  # [S, goal_dim]
    # gymnasium-robotics GoalEnv signature: compute_reward(ag, dg, info)
    reward = np.asarray(
        compute_reward(achieved_goal[sel + 1], new_goal, None), np.float32
    ).reshape(-1)
    done = (reward == 0.0).astype(np.float32)  # sparse -1/0 success convention
    return TransitionBatch(
        obs=np.concatenate([observation[sel], new_goal], axis=-1).astype(np.float32),
        action=action[sel].astype(np.float32),  # the t-indexed action (bug fix)
        reward=reward,
        next_obs=np.concatenate([next_observation[sel], new_goal], axis=-1).astype(
            np.float32
        ),
        done=done,
        discount=(gamma * (1.0 - done)).astype(np.float32),
    )
