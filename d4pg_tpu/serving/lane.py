"""VectorActorLane: the env-stepping half of acting.

One lane owns an ``EnvPool`` (E envs stepping in lockstep), an n-step
folder, and a transition sink (``ReplayService`` or the
``RemoteReplayClient`` adapter over a ``CoalescingSender``); the policy
queries go through an injected :class:`PolicyClient` — in-process
(:class:`~d4pg_tpu.serving.client.LocalPolicyClient`, the legacy shape)
or the serving wire
(:class:`~d4pg_tpu.serving.client.RemotePolicyClient`, SEED-style).

This loop IS the pre-serving ``ActorWorker.run``, moved: the tick
order (poll gate → normalize → act → step → fold → send → noise reset →
epsilon decay), the reset-once ``_obs`` persistence across ``run``
calls, and the dropped-batch accounting are unchanged, and
``distributed.actor.ActorWorker`` now delegates here — so the parity
oracle (1-env lane + local client ≡ legacy actor, seed for seed) is
structural, not aspirational.
"""

from __future__ import annotations

import threading

from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.envs.vector import EnvPool
from d4pg_tpu.replay.nstep import NStepFolder
from d4pg_tpu.serving.client import ActorConfig, LocalPolicyClient


class VectorActorLane:
    """Batched acting loop over a vectorized EnvPool with n-step folding.

    ``run`` is resumable: the pool is reset once, and both the episode
    state and the n-step window persist across calls — a cycle boundary
    in the training loop must NOT restart episodes or drop pending
    window entries (stale entries stitched across a reset would corrupt
    transitions).
    """

    def __init__(
        self,
        lane_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        pool: EnvPool,
        service,
        weights=None,
        seed: int = 0,
        obs_dtype=None,
        obs_norm=None,
        policy=None,
        stop: threading.Event | None = None,
    ):
        self.lane_id = lane_id
        self.config = config
        self.cfg = actor_cfg
        self.pool = pool
        self.service = service
        self.policy = policy if policy is not None else LocalPolicyClient(
            config, actor_cfg, weights, seed=seed, obs_norm=obs_norm)
        self._folder = NStepFolder(
            actor_cfg.n_step, actor_cfg.gamma, pool.num_envs,
            config.obs_spec, config.act_dim, obs_dtype=obs_dtype,
        )
        self._obs = None
        self._stop = stop if stop is not None else threading.Event()
        self.env_steps = 0
        # Degradation accounting: ``service.add`` returning False (ingest
        # backpressure past its timeout) or a drop_on_timeout transport
        # shedding a frame means replay rows were LOST — benign for
        # ingest, but it must be a counted, surfaced event (the fleet
        # plane's no-silent-loss rule), never a crash or a silent pass.
        self.dropped_batches = 0

    def run(self, max_steps: int) -> int:
        """Collect ``max_steps`` pool ticks (E transitions per tick)."""
        if self._obs is None:
            self._obs = self.pool.reset()
            self._folder.reset()
        obs = self._obs
        policy = self.policy
        policy.pull()
        for tick in range(max_steps):
            if self._stop.is_set():
                break
            if tick % self.cfg.weight_poll_every == 0:
                policy.pull()
            if policy.obs_norm is not None:
                actions = policy.actions(policy.obs_norm.normalize(obs))
            else:
                actions = policy.actions(obs)
            out = self.pool.step(actions)
            folded = self._folder.step(
                obs, actions, out.reward * self.cfg.reward_scale,
                out.final_obs, out.terminated, out.truncated,
            )
            if not self.service.add(folded, actor_id=self.lane_id):
                self.dropped_batches += 1
            done_any = out.terminated | out.truncated
            policy.reset_noise(done_any)
            for _ in range(int(done_any.sum())):
                policy.decay_epsilon()
            obs = out.obs
            self.env_steps += self.pool.num_envs
        self._obs = obs
        return self.env_steps

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        self.policy.close()
        self.pool.close()
