"""Serving-plane wire protocol: CRC-framed action request/response.

The fifth wire plane (after ingest 0xD4F6/0xD4F8, weights 0xD4F7/0xD4FC,
updates 0xD4AB, and the generation greeting 0xD4FA), in the same family:
a fixed ``!II`` (magic, body_len) outer frame — the transport module's
framing convention — followed by a fixed inner header and a CRC32 over
the float payload. The CRC is the torn-response defense: a response cut
mid-``sendall`` by a server kill must be a COUNTED rejection at the
client, never a silently-wrong action batch.

    request  0xD4E2: !BIHHI  flags, req_id, n_rows, obs_dim, crc32
             [16-byte trace ext ``!Qd`` (trace id, birth ts) if flags&1]
             payload: float32 obs rows [n_rows, obs_dim]
    response 0xD4E3: !BIIIHHI status, req_id, generation, version,
                              n_rows, act_dim, crc32
             payload: float32 action rows [n_rows, act_dim] (OK only)

Status codes: OK (actions attached), NO_PARAMS (server adopted nothing
yet — the client falls back to its warmup policy), BAD_REQUEST (the
server could not trust the request frame; req_id echoed from the
header so the caller can fail that one request instead of the
connection). The response carries the serving (generation, version)
pair so a lane can observe exactly which fenced snapshot acted for it.
"""

from __future__ import annotations

import zlib

import numpy as np

# Frame shapes come from the declared wire registry (serve-request /
# serve-response rows); see core/wire.py and
# ``python -m d4pg_tpu.lint --wire``. MAX_BODY is the serving plane's
# tighter cap (requests/responses are tiny next to the transport
# plane's 64 MiB bound; it catches a desynced stream before it
# allocates gigabytes).
from d4pg_tpu.core.wire import (
    FRAME_HEADER as HEADER,
    MAGIC_SERVE_REQUEST as MAGIC_REQUEST,
    MAGIC_SERVE_RESPONSE as MAGIC_RESPONSE,
    MAX_BODY,
    SERVE_REQ_HEADER as REQ_HEADER,
    SERVE_RSP_HEADER as RSP_HEADER,
    SERVE_TRACE_EXT as TRACE_EXT,
    SFLAG_TRACE as FLAG_TRACE,
)


class ProtocolError(RuntimeError):
    """Malformed serving frame (bad magic, truncation, size mismatch).

    Deliberately NOT the transport module's ProtocolError: importing
    ``distributed.transport`` here would close an import cycle through
    ``distributed/__init__`` -> ``actor`` -> ``serving.client``. Callers
    that speak both planes catch both types explicitly."""


STATUS_OK = 0
STATUS_NO_PARAMS = 1
STATUS_BAD_REQUEST = 2
# SLO admission control (d4pg_tpu/elastic): the server's per-class
# admission budget rejected this request — a load verdict, not an
# error. Clients degrade down their ladder (cached params, then
# warmup) exactly as for no-params; the status is separate so both
# sides can attribute the rejection. Payload-free like the other
# non-OK statuses: no frame-shape or flag-bit change.
STATUS_OVERLOAD = 3


class TornFrameError(ProtocolError):
    """CRC mismatch: the payload bytes do not match the header's CRC.

    Deterministic wire corruption (torn write across a server kill, or
    injected chaos) — the caller counts and REJECTS the frame; retrying
    the same bytes can never succeed."""


def encode_request(req_id: int, obs: np.ndarray,
                   trace: tuple[int, float] | None = None) -> bytes:
    """One action request frame for a [n_rows, obs_dim] float32 batch."""
    obs = np.ascontiguousarray(obs, dtype=np.float32)
    if obs.ndim != 2:
        raise ValueError(f"obs must be [n_rows, obs_dim], got {obs.shape}")
    n_rows, obs_dim = obs.shape
    payload = obs.tobytes()
    flags = FLAG_TRACE if trace is not None else 0
    head = REQ_HEADER.pack(flags, req_id & 0xFFFFFFFF, n_rows, obs_dim,
                           zlib.crc32(payload))
    ext = TRACE_EXT.pack(trace[0], trace[1]) if trace is not None else b""
    body = head + ext + payload
    return HEADER.pack(MAGIC_REQUEST, len(body)) + body


def decode_request(body: bytes) -> dict:
    """Parse a request body; raises TornFrameError on CRC mismatch (the
    header fields are still returned inside the exception's ``.meta`` so
    the server can echo the req_id in a BAD_REQUEST response)."""
    if len(body) < REQ_HEADER.size:
        raise ProtocolError(f"request body too short ({len(body)} bytes)")
    flags, req_id, n_rows, obs_dim, crc = REQ_HEADER.unpack_from(body)
    off = REQ_HEADER.size
    trace = None
    if flags & FLAG_TRACE:
        if len(body) < off + TRACE_EXT.size:
            raise ProtocolError("request trace extension truncated")
        trace = TRACE_EXT.unpack_from(body, off)
        off += TRACE_EXT.size
    payload = body[off:]
    if len(payload) != 4 * n_rows * obs_dim:
        raise ProtocolError(
            f"request payload {len(payload)}B != {4 * n_rows * obs_dim}B "
            f"for [{n_rows}, {obs_dim}] f32")
    if zlib.crc32(payload) != crc:
        err = TornFrameError(f"request {req_id} failed CRC")
        err.meta = {"req_id": req_id}
        raise err
    obs = np.frombuffer(payload, np.float32).reshape(n_rows, obs_dim)
    return {"req_id": req_id, "obs": obs, "trace": trace}


def encode_response(req_id: int, status: int, generation: int, version: int,
                    actions: np.ndarray | None) -> bytes:
    """One response frame; ``actions`` is required iff status == OK."""
    if status == STATUS_OK:
        actions = np.ascontiguousarray(actions, dtype=np.float32)
        n_rows, act_dim = actions.shape
        payload = actions.tobytes()
    else:
        n_rows = act_dim = 0
        payload = b""
    head = RSP_HEADER.pack(status, req_id & 0xFFFFFFFF,
                           generation & 0xFFFFFFFF, version & 0xFFFFFFFF,
                           n_rows, act_dim, zlib.crc32(payload))
    body = head + payload
    return HEADER.pack(MAGIC_RESPONSE, len(body)) + body


def decode_response(body: bytes) -> dict:
    """Parse a response body; TornFrameError on CRC mismatch — the
    client counts it and treats the request as failed (degrading to its
    local fallback), never acts on the corrupt rows."""
    if len(body) < RSP_HEADER.size:
        raise ProtocolError(f"response body too short ({len(body)} bytes)")
    status, req_id, generation, version, n_rows, act_dim, crc = \
        RSP_HEADER.unpack_from(body)
    payload = body[RSP_HEADER.size:]
    if status == STATUS_OK and len(payload) != 4 * n_rows * act_dim:
        raise ProtocolError(
            f"response payload {len(payload)}B != {4 * n_rows * act_dim}B")
    if zlib.crc32(payload) != crc:
        raise TornFrameError(f"response {req_id} failed CRC")
    actions = (np.frombuffer(payload, np.float32).reshape(n_rows, act_dim)
               if status == STATUS_OK else None)
    return {"req_id": req_id, "status": status, "generation": generation,
            "version": version, "actions": actions}


def read_frame(sock, expect_magic: int, recv_exact) -> bytes | None:
    """Read one length-prefixed frame body off ``sock`` (None on clean
    EOF). ``recv_exact`` is injected so client and server share the
    transport module's socket-read discipline without importing its
    private helper here."""
    head = recv_exact(sock, HEADER.size)
    if head is None:
        return None
    magic, body_len = HEADER.unpack(head)
    if magic != expect_magic:
        raise ProtocolError(f"bad serving magic 0x{magic:X} "
                            f"(want 0x{expect_magic:X})")
    if body_len > MAX_BODY:
        raise ProtocolError(f"serving body {body_len}B exceeds {MAX_BODY}B")
    body = recv_exact(sock, body_len)
    if body is None:
        raise ProtocolError("peer closed mid-frame")
    return body
