"""Policy clients: the query half of acting, behind one interface.

``distributed/actor.py`` used to fuse two jobs: stepping envs and
querying the policy (weight pulls, exploration noise, epsilon decay,
device pinning). The serving plane needs the query half alone — a
vectorized lane asks *something* for actions, and that something is
either in-process inference against the ``WeightStore``
(:class:`LocalPolicyClient`, the legacy behavior, extracted verbatim so
the seeded action stream is bitwise-unchanged) or a wire round trip to
a :class:`~d4pg_tpu.serving.server.PolicyInferenceServer`
(:class:`RemotePolicyClient`, SEED-style: the server owns params and
batches inference; the client owns exploration noise and degradation).

Interface contract (duck-typed; both clients honor it):

    pull() -> bool            refresh params if a newer version exists
    actions(obs) -> [B, A]    noisy exploration actions; ``obs`` is
                              ALREADY normalized by the caller (the
                              legacy ``_explore_actions`` convention)
    greedy_actions(obs)       deterministic mu(s) for evaluation
    reset_noise(done_mask)    zero per-env noise state on episode end
    decay_epsilon()           episode-boundary epsilon schedule step
    close()                   release sockets (no-op locally)
    obs_norm                  read-only normalizer view (or None)
    epsilon / version         current exploration scale / param version

The remote client never stalls an env loop: a dead or slow server is a
COUNTED degradation (timeout -> reconnect -> local cached-params or
uniform-warmup fallback), mirroring the fleet plane's no-silent-loss
rule on the ingest side.
"""

from __future__ import annotations

import contextlib
import dataclasses
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.core.noise import ou
from d4pg_tpu.envs.normalizer import FrozenNormalizer, RunningMeanStd
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.learner.update import act, act_deterministic, act_ou
from d4pg_tpu.obs.trace import new_trace_id
from d4pg_tpu.serving import protocol

# NOTE: d4pg_tpu.distributed.transport is imported lazily inside the
# remote client's connection path — a module-level import would close
# the cycle distributed/__init__ -> actor -> serving.client.


@dataclasses.dataclass
class ActorConfig:
    """Acting-plane config (exploration + env-loop knobs). Lives here so
    both policy clients and the env-stepping lanes can import it without
    a cycle; ``distributed.actor`` re-exports it unchanged."""

    epsilon_0: float = 0.3  # the reference's live, never-decayed eps (C5)
    min_epsilon: float = 0.01
    epsilon_horizon: int = 5000  # episodes to decay over (random_process.py:13)
    n_step: int = 3
    gamma: float = 0.99
    reward_scale: float = 1.0
    weight_poll_every: int = 1  # pool ticks between version checks
    # Exploration process. The reference exposes --ou_theta/--ou_sigma/--ou_mu
    # but never wires OU in (SURVEY.md C6 — constructed nowhere live); here
    # noise='ou' actually runs the temporally-correlated process.
    noise: str = "gaussian"  # 'gaussian' | 'ou'
    # Probability of replacing the policy action with a uniform random one,
    # per env per tick (the HER recipe's epsilon-greedy component — sparse
    # goal tasks need undirected exploration that additive Gaussian noise
    # around a confident wrong policy cannot provide). 0 = reference
    # behavior (additive noise only, random_process.py:16-18).
    random_eps: float = 0.0
    ou_theta: float = 0.25
    ou_sigma: float = 0.05
    ou_mu: float = 0.0
    ou_dt: float = 0.01
    # Where actor inference runs. Acting is latency-bound batch-E inference
    # dispatched every pool tick; on a TPU host every tick would round-trip
    # PCIe (or a remote tunnel) for microseconds of MLP compute, serializing
    # the env loop on transfer latency and contending with the learner's
    # dispatch queue. 'cpu' (default) pins the policy forward to the host
    # CPU backend — the D4PG production shape: the accelerator belongs to
    # the learner, actors run on TPU-VM host cores. 'default' uses the
    # default backend (worth it only for big conv encoders + wide pools).
    device: str = "cpu"  # 'cpu' | 'default'

    def __post_init__(self):
        if self.noise not in ("gaussian", "ou"):
            raise ValueError(f"unknown noise process {self.noise!r}")
        if self.device not in ("cpu", "default"):
            raise ValueError(f"unknown actor device {self.device!r}")


def resolve_act_device(kind: str):
    """Pinned inference device for an acting/eval component: the host CPU
    backend for ``'cpu'`` (see ``ActorConfig.device``), None (follow the
    default backend) for ``'default'``. Shared by actors, the serving
    plane, and the Evaluator so the placement policy lives in one place."""
    if kind not in ("cpu", "default"):
        raise ValueError(f"unknown actor device {kind!r}")
    if kind != "cpu":
        return None
    # local_devices, not devices: under jax.distributed the global device
    # list starts with process 0's devices, so devices("cpu")[0] on any
    # other process is NON-addressable and acting there either errors or
    # produces arrays this process cannot read.
    return jax.local_devices(backend="cpu")[0]


def act_device_scope(device):
    """Thread-local default-device scope for a pinned device (no-op scope
    when following the default backend)."""
    if device is None:
        return contextlib.nullcontext()
    return jax.default_device(device)


def put_params_on(device, params):
    """Move published params onto the pinned device. Publishes may carry
    accelerator arrays (the fused learner publishes device params);
    committed arrays would drag the acting computation back onto the
    learner's chip."""
    if device is None:
        return params
    return jax.device_put(params, device)


class LocalPolicyClient:
    """In-process policy queries against a ``WeightStore``-shaped handle.

    This is the policy half of the pre-serving ``_BaseActor``, moved —
    not rewritten: the jax key split order, the ``seed + 17`` numpy rng,
    the OU lazy init, and the epsilon schedule are preserved exactly so
    a seeded action stream through this client is bitwise-identical to
    the legacy actor's (the serving parity oracle pins this).
    """

    def __init__(
        self,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        weights,
        seed: int = 0,
        obs_norm=None,
    ):
        self.config = config
        self.cfg = actor_cfg
        self.weights = weights
        # READ-ONLY normalizer view for the policy input (the networks are
        # trained on standardized rows — the ReplayService's drain thread
        # owns the statistics and normalizes at insert). In-process actors
        # share the service's RunningMeanStd; remote/spawned actors receive
        # a FrozenNormalizer refreshed from the weight channel (below).
        self.obs_norm = obs_norm
        self._act_device = resolve_act_device(actor_cfg.device)
        with self._device_scope():
            self._key = jax.random.key(seed)
        self._version = 0
        self._params = None
        self._epsilon = actor_cfg.epsilon_0
        self._explore_rng = np.random.default_rng(seed + 17)
        self._episodes = 0
        self._ou = None  # lazily-sized OU state when cfg.noise == 'ou'

    def _device_scope(self):
        """Context placing this client's jax dispatches on its pinned
        device (thread-local, so actor threads don't disturb the
        learner's default placement)."""
        return act_device_scope(self._act_device)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def version(self) -> int:
        return self._version

    @property
    def params(self):
        return self._params

    def pull(self) -> bool:
        """Refresh params if the store has a newer version."""
        got = self.weights.get_if_newer(self._version)
        if got is not None:
            self._version, params = got
            self._params = put_params_on(self._act_device, params)
            # Remote/spawned actors: the weight payload piggybacks the
            # learner's normalization statistics (WeightClient.norm_stats).
            # An in-process RunningMeanStd handle stays authoritative.
            ns = getattr(self.weights, "norm_stats", None)
            if ns is not None and not isinstance(self.obs_norm, RunningMeanStd):
                if self.obs_norm is None:
                    self.obs_norm = FrozenNormalizer(*ns)
                else:
                    self.obs_norm.set(*ns)
            return True
        return False

    def snapshot_pull(self) -> tuple[int, int]:
        """Adopt the store's CURRENT params regardless of version (the
        evaluator's pull: eval must describe the weights it actually ran,
        so the published step is returned with the version)."""
        version, params, published_step = self.weights.snapshot()
        if params is None:
            raise RuntimeError("no weights published yet")
        self._version = version
        self._params = put_params_on(self._act_device, params)
        return version, published_step

    def actions(self, obs: np.ndarray) -> np.ndarray:
        """Noisy policy actions for a [B, obs_dim] batch; uniform random
        before the first weight publish (warmup, ``main.py:200-207``)."""
        with self._device_scope():
            return self._actions_inner(obs)

    def _actions_inner(self, obs: np.ndarray) -> np.ndarray:
        self._key, ka = jax.random.split(self._key)
        if self._params is None:
            return np.asarray(
                jax.random.uniform(ka, (obs.shape[0], self.config.act_dim),
                                   minval=-1.0, maxval=1.0)
            )
        if self.cfg.noise == "ou":
            if self._ou is None or self._ou.x.shape[0] != obs.shape[0]:
                self._ou = ou.init(self.config.act_dim, (obs.shape[0],))
            actions, self._ou = act_ou(
                self.config, self._params, jnp.asarray(obs), self._ou, ka,
                epsilon=self._epsilon, theta=self.cfg.ou_theta,
                mu=self.cfg.ou_mu, sigma=self.cfg.ou_sigma, dt=self.cfg.ou_dt,
            )
            actions = np.asarray(actions)
        else:
            actions = np.asarray(
                act(self.config, self._params, jnp.asarray(obs), ka,
                    self._epsilon)
            )
        if self.cfg.random_eps > 0.0:
            rng = self._explore_rng
            mask = rng.random(actions.shape[0]) < self.cfg.random_eps
            if mask.any():
                actions = np.array(actions)  # jax->np output is read-only
                actions[mask] = rng.uniform(
                    -1.0, 1.0, (int(mask.sum()), actions.shape[1])
                ).astype(actions.dtype)
        return actions

    def greedy_actions(self, obs: np.ndarray) -> np.ndarray:
        """Deterministic mu(s) for a [B, obs_dim] batch (evaluation)."""
        if self._params is None:
            raise RuntimeError("no weights pulled yet")
        with self._device_scope():
            return np.asarray(
                act_deterministic(self.config, self._params,
                                  jnp.asarray(obs))
            )

    def reset_noise(self, done_mask: np.ndarray) -> None:
        """Zero the OU state of envs whose episode ended
        (``random_process.py:41-45`` resets x on episode reset)."""
        if self._ou is not None and done_mask.any():
            with self._device_scope():  # keep the OU state on the pinned device
                keep = jnp.asarray(~done_mask, jnp.float32)[:, None]
                self._ou = self._ou._replace(x=self._ou.x * keep)

    def decay_epsilon(self) -> None:
        """eps = min + (eps0-min) * exp(-5k/horizon) on episode end — the
        decay the reference defines but never runs (``random_process.py:
        19-21``, call commented at ``main.py:366``)."""
        self._episodes += 1
        c = self.cfg
        self._epsilon = c.min_epsilon + (c.epsilon_0 - c.min_epsilon) * float(
            np.exp(-5.0 * self._episodes / c.epsilon_horizon)
        )

    def close(self) -> None:
        pass


class RemotePolicyClient:
    """Policy queries over the serving wire protocol, with a declared
    degradation ladder instead of stalls:

        1. server OK            -> served mu, local gaussian noise
        2. timeout / torn / EOF -> drop the connection (responses are
           in-order per connection; a late reply for an abandoned
           request must never be matched to a newer one), count the
           event, and fall back to
        3. cached params        -> local ``act_deterministic`` against
           the last params pulled from an optional ``weights`` handle
        4. no params anywhere   -> uniform warmup actions

    Every rung is a counted event (``stats()``); the env loop never
    blocks past ``timeout`` per tick. Exploration noise stays CLIENT
    side (the server computes greedy mu only) so one shared server
    never correlates exploration across lanes.

    Thread contract: one lane, one client (the request counter, socket,
    and rng are intentionally unshared — matching one ``EnvPool`` per
    lane thread).
    """

    def __init__(
        self,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        host: str,
        port: int,
        *,
        secret: str | None = None,
        lane_id: int = 0,
        seed: int = 0,
        timeout: float = 0.5,
        connect_timeout: float = 1.0,
        reconnect_backoff: float = 0.05,
        weights=None,
        obs_norm=None,
        trace_sample: float = 0.0,
        record_ledger: bool = False,
    ):
        if actor_cfg.noise != "gaussian":
            # OU state lives per-client; the remote split keeps noise
            # client-side but only the uncorrelated process is wired.
            raise ValueError("RemotePolicyClient supports gaussian noise only")
        self.config = config
        self.cfg = actor_cfg
        self.host, self.port = host, int(port)
        self.secret = secret
        self.lane_id = int(lane_id)
        self.weights = weights
        self.obs_norm = obs_norm
        self.timeout = float(timeout)
        self.connect_timeout = float(connect_timeout)
        self.reconnect_backoff = float(reconnect_backoff)
        self._act_device = resolve_act_device(actor_cfg.device)
        self._epsilon = actor_cfg.epsilon_0
        self._episodes = 0
        self._explore_rng = np.random.default_rng(seed + 17)
        self._noise_rng = np.random.default_rng(seed + 29)
        self._req_counter = 0
        self._sock: socket.socket | None = None
        self._next_connect = 0.0
        self._version = 0
        self._generation = 0
        self._fallback_params = None
        self._fallback_version = 0
        self._trace_sample = float(trace_sample)
        self._trace_rng = np.random.default_rng((seed << 8) ^ 0xD4E2)
        # Optional acceptance ledger for the chaos oracle: the set of
        # req_ids whose responses this client ACTED on. Intersected with
        # the server's torn-injection ledger it proves torn responses
        # are rejected, not just counted.
        self.accepted_req_ids: set[int] | None = set() if record_ledger else None
        self.stats_lock = threading.Lock()
        self._stats = {
            "requests": 0, "served": 0, "timeouts": 0, "torn_rejected": 0,
            "wire_errors": 0, "no_params": 0, "overload_rejected": 0,
            "fallbacks": 0, "warmup_fallbacks": 0, "reconnects": 0,
        }

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def version(self) -> int:
        """Version of the last params that acted for this lane (server
        snapshot version, or the cached fallback's)."""
        return self._version

    @property
    def generation(self) -> int:
        return self._generation

    def _count(self, key: str, n: int = 1) -> None:
        with self.stats_lock:
            self._stats[key] += n

    def stats(self) -> dict:
        with self.stats_lock:
            return dict(self._stats)

    # -- connection ---------------------------------------------------------
    def _ensure_conn(self) -> socket.socket | None:
        from d4pg_tpu.distributed import transport

        if self._sock is not None:
            return self._sock
        now = time.monotonic()
        if now < self._next_connect:
            return None
        try:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.connect_timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            transport.client_handshake(s, self.secret)
            s.settimeout(self.timeout)
            self._sock = s
            self._count("reconnects")
            return s
        except (OSError, transport.ProtocolError):
            self._next_connect = now + self.reconnect_backoff
            return None

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- weight pulls (fallback cache) --------------------------------------
    def pull(self) -> bool:
        """Refresh the local FALLBACK params (and the frozen normalizer
        view) from the optional weights handle. The server feeds itself;
        this cache only backs the degradation ladder's rung 3."""
        if self.weights is None:
            return False
        got = self.weights.get_if_newer(self._fallback_version)
        if got is None:
            return False
        self._fallback_version, params = got
        self._fallback_params = put_params_on(self._act_device, params)
        ns = getattr(self.weights, "norm_stats", None)
        if ns is not None and not isinstance(self.obs_norm, RunningMeanStd):
            if self.obs_norm is None:
                self.obs_norm = FrozenNormalizer(*ns)
            else:
                self.obs_norm.set(*ns)
        return True

    # -- the request path ---------------------------------------------------
    def _request_mu(self, obs: np.ndarray) -> np.ndarray | None:
        """One round trip; None on any failure (all counted)."""
        from d4pg_tpu.distributed.transport import _recv_exact

        sock = self._ensure_conn()
        if sock is None:
            return None
        self._req_counter += 1
        req_id = ((self.lane_id & 0xFFF) << 20) | (self._req_counter & 0xFFFFF)
        trace = None
        if self._trace_sample > 0.0 and \
                self._trace_rng.random() < self._trace_sample:
            trace = (new_trace_id(self.lane_id), time.monotonic())
        self._count("requests")
        try:
            sock.sendall(protocol.encode_request(req_id, obs, trace=trace))
            body = protocol.read_frame(sock, protocol.MAGIC_RESPONSE,
                                       _recv_exact)
            if body is None:
                raise ConnectionError("server closed")
            rsp = protocol.decode_response(body)
        except protocol.TornFrameError:
            self._count("torn_rejected")
            self._drop_conn()
            return None
        except (TimeoutError, socket.timeout):
            self._count("timeouts")
            self._drop_conn()
            return None
        except (OSError, protocol.ProtocolError, ConnectionError):
            self._count("wire_errors")
            self._drop_conn()
            return None
        if rsp["req_id"] != req_id:
            # in-order protocol: a mismatch means this connection's
            # stream no longer lines up with our requests — poison
            self._count("wire_errors")
            self._drop_conn()
            return None
        if rsp["status"] != protocol.STATUS_OK:
            # overload = the server's admission budget said no (elastic
            # plane) — same degradation rung as no-params (fall back to
            # cached params, then warmup), separate counter so a load
            # verdict never masquerades as a freshness gap
            self._count("overload_rejected"
                        if rsp["status"] == protocol.STATUS_OVERLOAD
                        else "no_params")
            return None
        self._count("served")
        self._generation = rsp["generation"]
        self._version = rsp["version"]
        if self.accepted_req_ids is not None:
            self.accepted_req_ids.add(req_id)
        return rsp["actions"]

    def _fallback_mu(self, obs: np.ndarray) -> np.ndarray | None:
        if self._fallback_params is None:
            self.pull()
        if self._fallback_params is None:
            return None
        self._count("fallbacks")
        self._version = self._fallback_version
        with act_device_scope(self._act_device):
            return np.asarray(
                act_deterministic(self.config, self._fallback_params,
                                  jnp.asarray(obs))
            )

    def actions(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        mu = self._request_mu(obs)
        if mu is None:
            mu = self._fallback_mu(obs)
        if mu is None:
            # rung 4: uniform warmup — already maximal exploration, no
            # additive noise on top
            self._count("warmup_fallbacks")
            return self._noise_rng.uniform(
                -1.0, 1.0, (obs.shape[0], self.config.act_dim)
            ).astype(np.float32)
        noise = self._noise_rng.standard_normal(mu.shape).astype(np.float32)
        actions = np.clip(mu + self._epsilon * noise, -1.0, 1.0)
        if self.cfg.random_eps > 0.0:
            rng = self._explore_rng
            mask = rng.random(actions.shape[0]) < self.cfg.random_eps
            if mask.any():
                actions[mask] = rng.uniform(
                    -1.0, 1.0, (int(mask.sum()), actions.shape[1])
                ).astype(actions.dtype)
        return actions

    def greedy_actions(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        mu = self._request_mu(obs)
        if mu is None:
            mu = self._fallback_mu(obs)
        if mu is None:
            raise RuntimeError("no server response and no cached params")
        return mu

    def reset_noise(self, done_mask: np.ndarray) -> None:
        pass  # gaussian noise is memoryless

    def decay_epsilon(self) -> None:
        self._episodes += 1
        c = self.cfg
        self._epsilon = c.min_epsilon + (c.epsilon_0 - c.min_epsilon) * float(
            np.exp(-5.0 * self._episodes / c.epsilon_horizon)
        )

    def close(self) -> None:
        self._drop_conn()
