"""PolicyInferenceServer: continuous-batching action inference.

The serving half of ROADMAP direction #2 ("Accelerated Methods for Deep
RL", arXiv 1803.02811): instead of every actor paying its own batch-E
jit dispatch, many lanes send obs batches over the serving wire
(``serving.protocol``) and a single batcher thread coalesces whatever
arrived inside a bounded window into ONE device dispatch. Three rules
keep it production-shaped:

- **Bounded window, never a stall.** The batcher waits at most
  ``batch_window_s`` after the first pending request (or until
  ``max_batch_rows`` accumulate) — latency is capped by construction,
  and an idle server burns a condition wait, not a spin.
- **Padded power-of-two buckets.** The fused row batch is padded to the
  next power of two before dispatch, so a steady state serves from a
  handful of compiled shapes instead of recompiling per occupancy
  (``batch_occupancy`` tracks the honest fill ratio).
- **Fenced freshness.** A refresher thread adopts (generation, version)
  snapshots from the ``WeightStore`` monotonically — a regression
  without a generation bump is a COUNTED rejection (``fenced_rejected``)
  — and every response carries the pair that produced it. The freshness
  SLA is declared, not implied: ``staleness_s`` (now - published_ts of
  the adopted snapshot) is exported, and a batch served beyond
  ``sla_staleness_s`` increments ``sla_breaches``.

Obs rows arrive ALREADY normalized (the legacy ``_explore_actions``
convention — the normalizer view lives with the lane, refreshed off the
weight channel); the server computes greedy mu only, exploration noise
stays client-side so a shared server never correlates lanes.

Locking: all serving state (pending deque, adopted params, counters)
lives under the declared ``pserve``-tier condition ``_pserve_cond``
(below ``wserve``, above ``wstore`` — the refresher's store snapshot is
taken OUTSIDE the condition, so the only nesting is none at all).
Responses are written outside the condition; a connection has at most
one in-flight request (the client protocol is send→wait), so the single
batcher thread is the only response writer per socket.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque

import jax.numpy as jnp
import numpy as np

from d4pg_tpu.core.locking import TieredCondition
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.learner.update import act_deterministic
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import EVENT_ADMISSION_REJECT, record_event
from d4pg_tpu.obs.registry import REGISTRY, percentile_summary
from d4pg_tpu.obs.trace import RECORDER
from d4pg_tpu.distributed.transport import (
    ConnRegistry,
    _recv_exact,
    server_handshake,
)
from d4pg_tpu.serving import protocol
from d4pg_tpu.serving.client import act_device_scope, put_params_on, \
    resolve_act_device


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class ServingChaos:
    """Deterministic response corruption for the serving wire.

    Flips one payload byte AFTER the CRC is computed, at a seeded rate —
    the frame still parses structurally (framing intact, stream not
    desynced) but the CRC check must reject it. ``torn_req_ids`` is the
    injection ledger the chaos oracle intersects with the clients'
    acceptance ledgers: torn ∩ accepted must be empty."""

    def __init__(self, torn_response_rate: float = 0.0, seed: int = 0):
        self.torn_response_rate = float(torn_response_rate)
        self._rng = np.random.default_rng((seed << 4) ^ 0xD4E3)
        self._mu = threading.Lock()
        self.torn_req_ids: set[int] = set()
        self.torn_injected = 0

    def maybe_tear(self, req_id: int, frame: bytes) -> bytes:
        body_payload_off = protocol.HEADER.size + protocol.RSP_HEADER.size
        if (self.torn_response_rate <= 0.0
                or len(frame) <= body_payload_off
                or self._rng.random() >= self.torn_response_rate):
            return frame
        torn = bytearray(frame)
        idx = body_payload_off + int(
            self._rng.integers(0, len(frame) - body_payload_off))
        torn[idx] ^= 0xFF
        with self._mu:
            self.torn_req_ids.add(req_id)
            self.torn_injected += 1
        return bytes(torn)


class PolicyInferenceServer(ConnRegistry):
    """Continuous-batching greedy-action service over one port."""

    def __init__(
        self,
        config: D4PGConfig,
        weights,
        host: str = "127.0.0.1",
        port: int = 0,
        secret: str | None = None,
        batch_window_s: float = 0.002,
        max_batch_rows: int = 256,
        sla_staleness_s: float = 1.0,
        refresh_interval_s: float = 0.02,
        device: str = "cpu",
        chaos: ServingChaos | None = None,
        admission=None,
        admission_depth: int = 64,
        sla_latency_ms: float | None = None,
    ):
        super().__init__()
        self.config = config
        self._weights = weights
        self._secret = secret
        self.batch_window_s = float(batch_window_s)
        self.max_batch_rows = int(max_batch_rows)
        self.sla_staleness_s = float(sla_staleness_s)
        self.refresh_interval_s = float(refresh_interval_s)
        # SLO admission control (docs/architecture.md "Elastic traffic
        # plane"): with an ``elastic.AdmissionPolicy`` attached, each
        # request's lane id (the top 12 bits of req_id — identity the
        # client cannot forge upward, no wire change) classifies it,
        # and class c is admitted only while the pending queue stands
        # below its share of ``admission_depth``. Rejections answer
        # STATUS_OVERLOAD immediately and are attributed per class.
        # None (default) keeps the unbounded legacy queue bit-for-bit.
        self._admission = admission
        self.admission_depth = int(admission_depth)
        # Optional queueing-latency SLO: a served response whose
        # enqueue->write latency exceeds this counts a latency breach
        # (the staleness SLA above is freshness; this is promptness).
        self.sla_latency_ms = sla_latency_ms
        self.chaos = chaos
        self._obs_dim = int(config.obs_dim)
        self._act_device = resolve_act_device(device)
        # ---- serving state, all under the declared pserve tier ----
        self._pserve_cond = TieredCondition("pserve")
        self._pending: deque = deque()  # (conn, req dict, enqueue_ts)
        self._params = None
        self._generation = 0
        self._version = 0
        self._published_ts: float | None = None
        self._occupancy: deque = deque(maxlen=4096)
        self._latency_ms: deque = deque(maxlen=4096)
        self._batch_rows: deque = deque(maxlen=4096)
        self.stats = {
            "requests": 0, "responses_ok": 0, "batches": 0, "rows": 0,
            "padded_rows": 0, "no_params": 0, "bad_requests": 0,
            "write_errors": 0, "adoptions": 0, "fenced_rejected": 0,
            "sla_breaches": 0, "admission_rejects": 0,
            "latency_breaches": 0,
        }
        # per-class admission attribution (class name -> rejected
        # requests), written under the serving condition like stats
        self.admission_rejects_by_class: dict[str, int] = {}
        # ---- wiring ----
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen()
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._conn_threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._batch_thread = threading.Thread(target=self._batcher,
                                              daemon=True)
        self._refresh_thread = threading.Thread(target=self._refresher,
                                                daemon=True)
        REGISTRY.register_provider("serving", self.serving_stats)
        self._accept_thread.start()
        self._batch_thread.start()
        self._refresh_thread.start()

    # -- param freshness ----------------------------------------------------
    def _refresher(self) -> None:
        try:
            while not self._stop.is_set():
                self.refresh_once()
                self._stop.wait(self.refresh_interval_s)
        except Exception as e:
            contained_crash("serving.refresher", e)

    def refresh_once(self) -> bool:
        """One adoption attempt against the store's current snapshot.
        The store read and the device placement happen OUTSIDE the
        serving condition (no lock nesting at all); only the swap is
        under it."""
        snap = self._weights.snapshot_ex()
        if snap["params"] is None:
            return False
        gen, ver = int(snap["generation"]), int(snap["version"])
        with self._pserve_cond:
            newer = (gen > self._generation
                     or (gen == self._generation and ver > self._version))
            current = (gen, ver) == (self._generation, self._version)
            if not newer:
                if not current and self._params is not None:
                    # the fence: a (gen, version) behind what we already
                    # serve is a rewind without a generation bump —
                    # never adopted, always counted
                    self.stats["fenced_rejected"] += 1
                return False
        params = put_params_on(self._act_device, snap["params"])
        with self._pserve_cond:
            # re-check under the cond: another refresh_once may have
            # adopted something newer while we were placing arrays
            if (gen > self._generation
                    or (gen == self._generation and ver > self._version)):
                self._params = params
                self._generation, self._version = gen, ver
                self._published_ts = snap.get("published_ts") \
                    or time.monotonic()
                self.stats["adoptions"] += 1
                return True
        return False

    def staleness_s(self) -> float | None:
        """Age of the served snapshot against the SLA clock."""
        with self._pserve_cond:
            if self._published_ts is None:
                return None
            return time.monotonic() - self._published_ts

    # -- live capacity knobs (elastic actuators) ----------------------------
    def set_batch_limits(self, window_s: float | None = None,
                         max_rows: int | None = None) -> None:
        """Live-adjust the continuous-batching knobs. The batch loop
        reads both on every iteration under the serving condition, so a
        set takes effect at the next window — no restart, no drain. The
        autoscaler calls this with nothing held (top-level pserve
        acquisition: no new lock edges)."""
        with self._pserve_cond:
            if window_s is not None:
                self.batch_window_s = float(window_s)
            if max_rows is not None:
                self.max_batch_rows = max(1, int(max_rows))
            self._pserve_cond.notify()

    def set_admission_depth(self, depth: int) -> None:
        """Live-adjust the admission queue-depth bound the per-class
        budgets are computed against."""
        with self._pserve_cond:
            self.admission_depth = max(1, int(depth))

    # -- connections --------------------------------------------------------
    def _accept(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    self._server.settimeout(0.2)
                    conn, _ = self._server.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                self._register_conn(conn)
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
                t = threading.Thread(target=self._reader, args=(conn,),
                                     daemon=True)
                self._conn_threads.append(t)
                t.start()
        except Exception as e:
            contained_crash("serving.accept", e)

    def _reader(self, conn: socket.socket) -> None:
        """Per-connection request pump: decode, validate, enqueue."""
        try:
            self._read_conn(conn)
        except Exception as e:
            contained_crash("serving.reader", e)

    def _read_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if not server_handshake(conn, self._secret):
                return
            conn.settimeout(None)
            while not self._stop.is_set():
                body = protocol.read_frame(conn, protocol.MAGIC_REQUEST,
                                           _recv_exact)
                if body is None:
                    return
                try:
                    req = protocol.decode_request(body)
                except protocol.TornFrameError as e:
                    # corrupt payload with a readable header: fail the
                    # one request, keep the connection
                    self._respond_error(conn, e.meta["req_id"],
                                        protocol.STATUS_BAD_REQUEST)
                    continue
                if req["obs"].shape[1] != self._obs_dim:
                    self._respond_error(conn, req["req_id"],
                                        protocol.STATUS_BAD_REQUEST)
                    continue
                self._admit_request(conn, req)
        except (OSError, protocol.ProtocolError):
            return  # peer died or desynced; the lane reconnects
        finally:
            self._unregister_conn(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _admit_request(self, conn: socket.socket, req: dict) -> None:
        """Admit one decoded request into the pending queue, opening its
        trace span; custody of the span rides the queue entry from here
        (the batcher's response path commits or sheds it). With an
        admission policy attached, the request first passes its class's
        queue-depth budget — a rejection answers STATUS_OVERLOAD from
        this (reader) thread and the span terminal-sheds, so the
        zero-orphan invariant covers rejected work too."""
        now = time.monotonic()
        tid = None
        if req["trace"] is not None:
            tid, birth = req["trace"]
            RECORDER.begin(tid, birth)
            RECORDER.record_span(tid, "admission", now)
        try:
            rejected_cls = None
            with self._pserve_cond:
                self.stats["requests"] += 1
                if self._admission is not None:
                    cls = self._admission.classify_index(
                        (req["req_id"] >> 20) & 0xFFF)
                    budget = self._admission.depth_for(
                        cls, self.admission_depth)
                    if len(self._pending) >= budget:
                        name = self._admission.class_name(cls)
                        self.stats["admission_rejects"] += 1
                        self.admission_rejects_by_class[name] = \
                            self.admission_rejects_by_class.get(name, 0) + 1
                        rejected_cls = name
                if rejected_cls is None:
                    self._pending.append((conn, req, now))
                    self._pserve_cond.notify()
        except BaseException:
            # zero-orphan invariant: a failed enqueue sheds the span it
            # just opened before the raise escapes the frame
            if tid is not None:
                RECORDER.terminal_shed(tid)
            raise
        if rejected_cls is not None:
            # everything below runs OUTSIDE the serving condition: the
            # overload reply, the breadcrumb, and the span terminal
            record_event(EVENT_ADMISSION_REJECT, plane="serving",
                         cls=rejected_cls, req_id=req["req_id"])
            try:
                conn.sendall(protocol.encode_response(
                    req["req_id"], protocol.STATUS_OVERLOAD, 0, 0, None))
            except OSError:
                with self._pserve_cond:
                    self.stats["write_errors"] += 1
            if tid is not None:
                RECORDER.terminal_shed(tid)

    def _respond_error(self, conn: socket.socket, req_id: int,
                       status: int) -> None:
        with self._pserve_cond:
            self.stats["bad_requests"] += 1
        try:
            conn.sendall(protocol.encode_response(req_id, status, 0, 0, None))
        except OSError:
            with self._pserve_cond:
                self.stats["write_errors"] += 1

    # -- the batcher --------------------------------------------------------
    def _pop_batch_locked(self) -> list:  # jaxlint: guarded-by=_pserve_cond
        """FIFO-pop pending requests up to the row budget (at least one:
        a single oversized request serves alone at its own bucket)."""
        batch, rows = [], 0
        while self._pending:
            n = self._pending[0][1]["obs"].shape[0]
            if batch and rows + n > self.max_batch_rows:
                break
            batch.append(self._pending.popleft())
            rows += n
        return batch

    def _batcher(self) -> None:
        try:
            self._batch_loop()
        except Exception as e:
            contained_crash("serving.batcher", e)

    def _batch_loop(self) -> None:
        while True:
            with self._pserve_cond:
                while not self._pending and not self._stop.is_set():
                    self._pserve_cond.wait(0.1)
                if self._stop.is_set():
                    return
                # continuous-batching window: the FIRST pending request
                # opens it; later arrivals ride along until it closes or
                # the row budget fills
                deadline = time.monotonic() + self.batch_window_s
                while (sum(r[1]["obs"].shape[0] for r in self._pending)
                        < self.max_batch_rows):
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop.is_set():
                        break
                    self._pserve_cond.wait(remaining)
                batch = self._pop_batch_locked()
                params = self._params
                gen, ver = self._generation, self._version
                pub_ts = self._published_ts
            if batch:
                self._serve_batch(batch, params, gen, ver, pub_ts)

    def _serve_batch(self, batch: list, params, gen: int, ver: int,
                     pub_ts: float | None) -> None:
        """One fused dispatch for a popped batch; runs OUTSIDE the
        serving condition (compute and socket writes never hold it)."""
        rows = sum(req["obs"].shape[0] for _, req, _ in batch)
        if params is None:
            for conn, req, _ in batch:
                self._write_response(conn, req, protocol.encode_response(
                    req["req_id"], protocol.STATUS_NO_PARAMS, gen, ver, None))
            with self._pserve_cond:
                self.stats["batches"] += 1
                self.stats["no_params"] += len(batch)
            return
        fused = np.concatenate([req["obs"] for _, req, _ in batch], axis=0)
        bucket = max(_next_pow2(rows), 1)
        if bucket > rows:
            fused = np.concatenate(
                [fused, np.zeros((bucket - rows, self._obs_dim), np.float32)],
                axis=0)
        with act_device_scope(self._act_device):
            mu = np.asarray(
                act_deterministic(self.config, params, jnp.asarray(fused)))
        now = time.monotonic()
        ok = 0
        off = 0
        for conn, req, t_enq in batch:
            n = req["obs"].shape[0]
            frame = protocol.encode_response(
                req["req_id"], protocol.STATUS_OK, gen, ver, mu[off:off + n])
            off += n
            if self.chaos is not None:
                frame = self.chaos.maybe_tear(req["req_id"], frame)
            if self._write_response(conn, req, frame):
                ok += 1
            self._latency_ms.append(1e3 * (now - t_enq))
        breach = (pub_ts is not None
                  and (now - pub_ts) > self.sla_staleness_s)
        late = 0
        if self.sla_latency_ms is not None:
            late = sum(1 for _, _, t_enq in batch
                       if 1e3 * (now - t_enq) > self.sla_latency_ms)
        with self._pserve_cond:
            self.stats["batches"] += 1
            self.stats["rows"] += rows
            self.stats["padded_rows"] += bucket - rows
            self.stats["responses_ok"] += ok
            if breach:
                self.stats["sla_breaches"] += 1
            self.stats["latency_breaches"] += late
            self._occupancy.append(rows / bucket)
            self._batch_rows.append(rows)

    def _write_response(self, conn: socket.socket, req: dict,
                        frame: bytes) -> bool:
        try:
            conn.sendall(frame)
        except OSError:
            with self._pserve_cond:
                self.stats["write_errors"] += 1
            if req["trace"] is not None:
                RECORDER.terminal_shed(req["trace"][0])
            return False
        if req["trace"] is not None:
            RECORDER.record_span(req["trace"][0], "commit")
        return True

    # -- observability ------------------------------------------------------
    def serving_stats(self) -> dict:
        """The ``serving`` obs-registry provider: one consistent snapshot
        under the serving condition (the PR-4 rule: counters read under
        the lock that writes them)."""
        with self._pserve_cond:
            out = dict(self.stats)
            out["queue_depth"] = len(self._pending)
            out["admission_rejects_by_class"] = \
                dict(self.admission_rejects_by_class)
            out["admission_depth"] = self.admission_depth
            out["batch_window_s"] = self.batch_window_s
            out["max_batch_rows"] = self.max_batch_rows
            out["generation"] = self._generation
            out["version"] = self._version
            out["staleness_s"] = (
                None if self._published_ts is None
                else round(time.monotonic() - self._published_ts, 6))
            out["sla_staleness_s"] = self.sla_staleness_s
            out["batch_occupancy"] = percentile_summary(list(self._occupancy))
            out["batch_rows"] = percentile_summary(list(self._batch_rows))
            out["latency_ms"] = percentile_summary(list(self._latency_ms))
        if self.chaos is not None:
            out["torn_injected"] = self.chaos.torn_injected
        return out

    def close(self) -> None:
        self._stop.set()
        with self._pserve_cond:
            self._pserve_cond.notify_all()
        try:
            self._server.close()
        except OSError:
            pass
        self._shutdown_conns()
        self._batch_thread.join(timeout=5.0)
        self._refresh_thread.join(timeout=5.0)
        self._accept_thread.join(timeout=5.0)
        for t in self._conn_threads:
            t.join(timeout=2.0)
        # pending requests die with the server: traced ones get their
        # terminal so the zero-orphan invariant survives a kill
        with self._pserve_cond:
            leftovers = list(self._pending)
            self._pending.clear()
        for _, req, _ in leftovers:
            if req["trace"] is not None:
                RECORDER.terminal_shed(req["trace"][0])
        record_event("serving_server_closed", port=self.port,
                     requests=self.stats["requests"])
        REGISTRY.unregister_provider("serving", self.serving_stats)
