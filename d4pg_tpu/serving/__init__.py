"""Serving plane: vectorized actor lanes + continuous-batching policy
inference (ROADMAP direction #2; arXiv 1803.02811's batched-inference
shape, SEED-style split between env stepping and policy queries).

Modules:

- ``protocol`` — the CRC-framed request/response wire format
  (magics 0xD4E2/0xD4E3, the fifth dual-magic plane).
- ``client`` — the ``PolicyClient`` interface: ``LocalPolicyClient``
  (in-process inference, bitwise the legacy actor's policy half) and
  ``RemotePolicyClient`` (wire round trips with a counted degradation
  ladder). Also home of ``ActorConfig`` and the acting device helpers.
- ``server`` — ``PolicyInferenceServer``: bounded-window continuous
  batching into padded power-of-two buckets, fenced (generation,
  version) adoption under a declared freshness SLA, the ``serving``
  obs provider, and ``ServingChaos`` torn-response injection.
- ``lane`` — ``VectorActorLane``: the env-stepping half (EnvPool +
  n-step folding + transition sink) against any policy client.
"""

from d4pg_tpu.serving.client import (  # noqa: F401
    ActorConfig,
    LocalPolicyClient,
    RemotePolicyClient,
)
from d4pg_tpu.serving.lane import VectorActorLane  # noqa: F401
from d4pg_tpu.serving.server import (  # noqa: F401
    PolicyInferenceServer,
    ServingChaos,
)
