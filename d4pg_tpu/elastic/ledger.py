"""Scaling-decision ledger: every observation -> decision -> actuation
tuple the autoscaler takes, recorded in order.

The autoscaler changes live capacity knobs on a running fleet — the
one category of mutation that is invisible in a post-hoc artifact
unless it is journaled. The ledger is that journal, with two jobs:

- **audit**: each record carries the signals the decision saw, the
  decisions taken, the targets after, and which actuators actually
  fired (plus any actuator errors, degrade-and-count style);
- **replayability**: the decision core (``autoscaler.ControlPolicy``)
  is a pure function of (config, signal stream, control state), so
  re-running it over the recorded signals MUST reproduce the recorded
  decision stream bit for bit. ``digest()`` canonicalizes exactly the
  replay-covered fields — wall-clock timestamps ride the records for
  humans but stay OUT of the digest, which is what lets two runs of
  the same seed pin stream equality with one string compare.

Locking: one plain terminal ``_mu`` (the obs-plane discipline — no
path holding it acquires anything else), so the ledger adds zero lock
edges no matter which thread appends.
"""

from __future__ import annotations

import hashlib
import json
import threading


def canonical_record(rec: dict) -> dict:
    """The replay-covered projection of a record: tick, sensed signals,
    decisions, post-decision targets. Deterministic across runs of the
    same seed; excludes wall time and actuation outcomes (an actuator
    error is an environment fact, not a decision fact)."""
    return {
        "tick": rec["tick"],
        "signals": dict(sorted(rec["signals"].items())),
        "decisions": dict(sorted(rec["decisions"].items())),
        "targets": dict(sorted(rec["targets"].items())),
    }


class ScalingLedger:
    """Append-only, bounded decision journal (oldest dropped past
    ``capacity`` with the drop counted — a week-long run must not grow
    an unbounded list; the digest covers what is retained plus the
    count of what is not)."""

    def __init__(self, capacity: int = 8192):
        self._mu = threading.Lock()
        self._records: list[dict] = []
        self._dropped = 0
        self._capacity = max(1, int(capacity))

    def append(self, rec: dict) -> None:
        with self._mu:
            self._records.append(rec)
            if len(self._records) > self._capacity:
                self._records.pop(0)
                self._dropped += 1

    def records(self) -> list[dict]:
        with self._mu:
            return list(self._records)

    def __len__(self) -> int:
        with self._mu:
            return len(self._records)

    @property
    def dropped(self) -> int:
        with self._mu:
            return self._dropped

    def digest(self) -> str:
        """sha256 over the canonical (replay-covered) stream — the
        decision-stream-equality oracle compares two of these."""
        with self._mu:
            recs = list(self._records)
            dropped = self._dropped
        doc = {"dropped": dropped,
               "records": [canonical_record(r) for r in recs]}
        blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    def to_jsonable(self, tail: int | None = None) -> dict:
        """Artifact form: digest + (optionally tail-truncated) records."""
        with self._mu:
            recs = list(self._records)
            dropped = self._dropped
        if tail is not None:
            recs = recs[-tail:]
        return {"digest": self.digest(), "dropped": dropped,
                "n_records": len(self), "records": recs}
