"""Obs-driven autoscaler: sense -> decide -> actuate, ledgered.

The loop closes the gap ROADMAP direction 3 named: every capacity knob
in the fleet was static at startup while all the SENSING (obs-registry
snapshot providers: queue depths, shed counters, latency histograms)
and all the safe ACTUATION primitives (live serving batch limits,
dealer pacing, ingest depth, generation-fenced replica respawn)
already existed. The autoscaler polls the providers, runs a pure
hysteresis controller, and applies bounded actuations — journaling
every observation -> decision -> actuation tuple in a
``ScalingLedger``.

Structure (and the properties each piece buys):

- ``ControlPolicy`` — the decision core. PURE: next decisions are a
  function of (config, sensed signals, control state) only — no
  clocks, no randomness, no I/O — which is what makes the ledger
  replayable: ``replay_decisions`` re-runs the policy over a ledger's
  recorded signals and must reproduce the decision stream bit for bit.
- ``Autoscaler`` — the thread. One tick = sense (invoke the registry
  export with NOTHING held), decide (pure), actuate (each setter
  takes its owner's locks at top level), journal. Its own state sits
  under ``_elastic_cond`` — tier 60, ABOVE every data-plane tier, so
  even an accidental hold-across-actuation is declared descent — but
  the loop's contract is stronger: no lock is held across sense,
  decide, or actuate, so the whole feature adds ZERO lock edges.
- hysteresis + bounded actuation: scale-up and scale-down use separate
  thresholds, each knob moves at most one step per decision, and a
  per-knob cooldown separates consecutive moves — the classic
  anti-flap trio, all config, all replay-covered.

Crash containment (failgraph family 16): the thread's top frame routes
any escape through ``obs.containment.contained_crash`` — a dead
autoscaler degrades the fleet to static knobs and counts itself; it
never takes the process down.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from d4pg_tpu.core.locking import TieredCondition
from d4pg_tpu.elastic.ledger import ScalingLedger, canonical_record
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import (
    EVENT_SCALE_DOWN, EVENT_SCALE_UP, record_event,
)
from d4pg_tpu.obs.registry import REGISTRY

# The knob vocabulary. Every knob the controller may move appears here;
# actuator dicts are validated against it so a typo'd wiring fails at
# construction, not silently at the first scale event.
KNOBS = ("serving_rows", "serving_window_s", "dealer_deals",
         "ingest_capacity", "replicas")


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Controller constants. Frozen: (config, signals) -> decisions is
    the replay contract, so the config is part of the stream identity."""

    interval_s: float = 0.25
    # -- serving batcher ----------------------------------------------------
    serving_rows_init: int = 32
    serving_rows_min: int = 16
    serving_rows_max: int = 512
    # two-point window schedule: hot traffic wants the batcher to close
    # windows fast (rows budget dominates), calm traffic wants wider
    # windows for occupancy
    serving_window_hot_s: float = 0.0005
    serving_window_cold_s: float = 0.004
    queue_high: int = 8          # pending requests: scale-up threshold
    queue_low: int = 2           # scale-down threshold (hysteresis gap)
    latency_high_ms: float = 50.0
    latency_low_ms: float = 10.0
    # -- ingest plane -------------------------------------------------------
    ingest_capacity_init: int = 64
    ingest_capacity_min: int = 32
    ingest_capacity_max: int = 512
    ingest_high: float = 0.5     # max shard depth / capacity
    ingest_low: float = 0.1
    # -- dealer pacing ------------------------------------------------------
    dealer_deals_init: int = 1
    dealer_deals_min: int = 1
    dealer_deals_max: int = 4
    # -- learner replicas ---------------------------------------------------
    replicas_init: int = 1
    replicas_min: int = 1
    replicas_max: int = 1
    # -- anti-flap ----------------------------------------------------------
    cooldown_ticks: int = 4


# Initial control state: current target per knob, last-move tick per
# knob, previous cumulative counters for delta signals.
def initial_state(cfg: AutoscalerConfig) -> dict:
    return {
        "targets": {
            "serving_rows": int(cfg.serving_rows_init),
            "serving_window_s": float(cfg.serving_window_cold_s),
            "dealer_deals": int(cfg.dealer_deals_init),
            "ingest_capacity": int(cfg.ingest_capacity_init),
            "replicas": int(cfg.replicas_init),
        },
        "last_move": {k: -10**9 for k in KNOBS},
        "prev_sheds": 0.0,
        "tick": 0,
    }


def extract_signals(snapshot: dict) -> dict:
    """Project a registry export (or any dict shaped like one) onto the
    controller's signal vector. Total: a missing provider or a
    provider_error section reads as a calm plane (zeros), never a
    crash — a dead component must degrade the controller to
    do-nothing, not kill its thread."""

    def _num(v, default=0.0):
        try:
            return float(v) if v is not None else float(default)
        except (TypeError, ValueError):
            return float(default)

    serving = snapshot.get("serving") or {}
    ingest = snapshot.get("ingest") or {}
    if not isinstance(serving, dict) or "provider_error" in serving:
        serving = {}
    if not isinstance(ingest, dict) or "provider_error" in ingest:
        ingest = {}
    lat = serving.get("latency_ms") or {}
    p95 = lat.get("p95") if isinstance(lat, dict) else None
    per_shard = ingest.get("per_shard") or []
    depth_frac = 0.0
    for sh in per_shard:
        cap = _num(sh.get("capacity"), 0.0)
        if cap > 0:
            depth_frac = max(depth_frac,
                             _num(sh.get("queue_depth")) / cap)
    return {
        "serving_queue": _num(serving.get("queue_depth")),
        "serving_p95_ms": _num(p95),
        "ingest_depth_frac": depth_frac,
        "ingest_sheds": (_num(ingest.get("sheds"))
                         + _num(ingest.get("admit_fails"))),
    }


class ControlPolicy:
    """The pure hysteresis controller. ``decide`` never mutates its
    inputs and touches no ambient state — the replay oracle depends on
    exactly this."""

    def __init__(self, cfg: AutoscalerConfig):
        self.cfg = cfg

    def initial_state(self) -> dict:
        return initial_state(self.cfg)

    def decide(self, signals: dict, state: dict) -> tuple[dict, dict]:
        """One control step: (signals, state) -> (decisions, state').
        ``decisions`` holds ONLY the knobs that move this tick, mapped
        to their new targets."""
        cfg = self.cfg
        tick = state["tick"]
        targets = dict(state["targets"])
        last = dict(state["last_move"])
        shed_delta = signals["ingest_sheds"] - state["prev_sheds"]

        hot_serving = (signals["serving_queue"] > cfg.queue_high
                       or signals["serving_p95_ms"] > cfg.latency_high_ms)
        cold_serving = (signals["serving_queue"] < cfg.queue_low
                        and signals["serving_p95_ms"] < cfg.latency_low_ms)
        hot_ingest = (signals["ingest_depth_frac"] > cfg.ingest_high
                      or shed_delta > 0)
        cold_ingest = (signals["ingest_depth_frac"] < cfg.ingest_low
                       and shed_delta == 0)

        def ready(knob: str) -> bool:
            return tick - last[knob] >= cfg.cooldown_ticks

        decisions: dict = {}

        def move(knob: str, value) -> None:
            if value != targets[knob]:
                decisions[knob] = value
                targets[knob] = value
                last[knob] = tick

        # serving batcher: one doubling/halving per move, window snaps
        # between its two set points alongside the row budget
        if hot_serving and ready("serving_rows"):
            move("serving_rows",
                 min(cfg.serving_rows_max, targets["serving_rows"] * 2))
            move("serving_window_s", cfg.serving_window_hot_s)
        elif cold_serving and ready("serving_rows"):
            move("serving_rows",
                 max(cfg.serving_rows_min, targets["serving_rows"] // 2))
            move("serving_window_s", cfg.serving_window_cold_s)

        # ingest depth: absorb a transient crowd by deepening the shard
        # deques (bounded), give the memory back when calm
        if hot_ingest and ready("ingest_capacity"):
            move("ingest_capacity",
                 min(cfg.ingest_capacity_max,
                     targets["ingest_capacity"] * 2))
        elif cold_ingest and ready("ingest_capacity"):
            move("ingest_capacity",
                 max(cfg.ingest_capacity_min,
                     targets["ingest_capacity"] // 2))

        # dealer pacing: a backlogged ingest plane needs the commit
        # thread's buffer-lock windows for DRAINING, not dealing — pace
        # the dealer down under pressure, back up when calm
        if hot_ingest and ready("dealer_deals"):
            move("dealer_deals",
                 max(cfg.dealer_deals_min, targets["dealer_deals"] // 2))
        elif cold_ingest and ready("dealer_deals"):
            move("dealer_deals",
                 min(cfg.dealer_deals_max, targets["dealer_deals"] * 2))

        # learner replicas: scale the training side with sustained load
        # (either plane hot), one replica per move through the
        # respawn + generation-fencing path
        if (hot_serving or hot_ingest) and ready("replicas"):
            move("replicas", min(cfg.replicas_max, targets["replicas"] + 1))
        elif cold_serving and cold_ingest and ready("replicas"):
            move("replicas", max(cfg.replicas_min, targets["replicas"] - 1))

        new_state = {
            "targets": targets,
            "last_move": last,
            "prev_sheds": signals["ingest_sheds"],
            "tick": tick + 1,
        }
        return decisions, new_state


def replay_decisions(cfg: AutoscalerConfig, records: list[dict]) -> list[dict]:
    """Re-run the pure controller over a ledger's recorded signal
    stream; returns the reproduced decision stream (one dict per
    record, same order)."""
    policy = ControlPolicy(cfg)
    state = policy.initial_state()
    out = []
    for rec in records:
        decisions, state = policy.decide(rec["signals"], state)
        out.append(decisions)
    return out


def replay_matches(cfg: AutoscalerConfig, ledger: ScalingLedger) -> bool:
    """The decision-stream replay oracle: True iff re-running the
    controller over the recorded signals reproduces every recorded
    decision (and the canonical digest therefore pins across runs of
    the same seed)."""
    records = ledger.records()
    replayed = replay_decisions(cfg, records)
    return all(
        canonical_record(rec)["decisions"]
        == dict(sorted(dec.items()))
        for rec, dec in zip(records, replayed)
    ) and len(replayed) == len(records)


class Autoscaler:
    """The control-loop thread. ``actuators`` maps knob names (see
    ``KNOBS``) to setter callables; absent knobs are decided and
    journaled but not actuated (the fleet may wire any subset).
    ``sensor`` defaults to the process registry's ``export`` — pass a
    callable for isolated tests."""

    def __init__(
        self,
        cfg: AutoscalerConfig | None = None,
        actuators: dict | None = None,
        sensor=None,
        ledger: ScalingLedger | None = None,
        register_provider: bool = True,
    ):
        self.cfg = cfg or AutoscalerConfig()
        self.actuators = dict(actuators or {})
        unknown = set(self.actuators) - set(KNOBS)
        if unknown:
            raise ValueError(f"unknown autoscaler knobs: {sorted(unknown)}")
        self._sensor = sensor if sensor is not None else REGISTRY.export
        self.ledger = ledger if ledger is not None else ScalingLedger()
        self._policy = ControlPolicy(self.cfg)
        # controller state + counters, all under the elastic condition
        self._elastic_cond = TieredCondition("elastic")
        self._state = self._policy.initial_state()
        self.stats = {
            "ticks": 0, "decisions": 0, "actuations": 0,
            "actuator_errors": 0, "sense_errors": 0,
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._registered = bool(register_provider)
        if self._registered:
            REGISTRY.register_provider("elastic", self.autoscaler_stats)

    # -- one control step ---------------------------------------------------
    def tick_once(self) -> dict:
        """Sense -> decide -> actuate -> journal, holding NO lock across
        any of the three phases (the zero-new-lock-edges contract).
        Returns the appended ledger record."""
        t_wall = time.monotonic()
        try:
            snapshot = self._sensor()
        except Exception:
            # a crashed sensor is a calm-plane read, counted
            snapshot = {}
            with self._elastic_cond:
                self.stats["sense_errors"] += 1
        signals = extract_signals(snapshot)
        with self._elastic_cond:
            state = self._state
        decisions, new_state = self._policy.decide(signals, state)
        actuated, errors = [], []
        for knob, value in decisions.items():
            fn = self.actuators.get(knob)
            if fn is None:
                continue
            try:
                fn(value)
                actuated.append(knob)
            except Exception as e:  # degrade-and-count, never wedge
                errors.append(f"{knob}: {type(e).__name__}: {e}")
        for knob, value in decisions.items():
            old = state["targets"][knob]
            record_event(EVENT_SCALE_UP if value > old else EVENT_SCALE_DOWN,
                         knob=knob, frm=old, to=value,
                         tick=state["tick"],
                         actuated=knob in actuated)
        rec = {
            "tick": state["tick"],
            "t_wall": round(t_wall, 6),
            "signals": signals,
            "decisions": decisions,
            "targets": dict(new_state["targets"]),
            "actuated": actuated,
            "errors": errors,
        }
        self.ledger.append(rec)
        with self._elastic_cond:
            self._state = new_state
            self.stats["ticks"] += 1
            self.stats["decisions"] += len(decisions)
            self.stats["actuations"] += len(actuated)
            self.stats["actuator_errors"] += len(errors)
        return rec

    # -- the thread ---------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="elastic-autoscaler")
            self._thread.start()
        return self

    def _run(self) -> None:
        try:
            self._loop()
        except Exception as e:  # noqa: BLE001 — top frame of the loop
            contained_crash("elastic.autoscaler", e)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.tick_once()
            self._stop.wait(self.cfg.interval_s)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._registered:
            REGISTRY.unregister_provider("elastic", self.autoscaler_stats)
            self._registered = False

    # -- observability ------------------------------------------------------
    def targets(self) -> dict:
        with self._elastic_cond:
            return dict(self._state["targets"])

    def autoscaler_stats(self) -> dict:
        """The ``elastic`` obs-registry provider: counters + live
        targets, one consistent snapshot under the elastic condition."""
        with self._elastic_cond:
            out = dict(self.stats)
            out["targets"] = dict(self._state["targets"])
            out["tick"] = self._state["tick"]
        out["ledger_digest"] = self.ledger.digest()
        out["ledger_records"] = len(self.ledger)
        return out
