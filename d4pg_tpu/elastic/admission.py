"""Priority classes + the per-class admission policy.

The fleet plane's original overload behavior was FLAT: a shard at its
shed watermark evicts the oldest queued batch regardless of whose it
is, and the serving queue is unbounded. Under a flash crowd that means
latency-critical work is exactly as likely to be shed as bulk backfill.
This module makes admission class-aware:

- every producer identity (actor id on the ingest plane, lane id on
  the serving plane) maps to a PRIORITY CLASS — class 0 is the most
  protected. Classification is derived from identity server-side, so a
  client cannot self-promote by asserting a priority byte on the wire
  (and no wire format changes at all);
- under pressure the LOWEST-priority work is shed first (oldest within
  the class), and an incoming low-class item is itself the victim when
  everything queued outranks it;
- every shed/reject is ATTRIBUTED to its class in the owning
  component's ledger (``sheds_by_class`` in ``ingest_stats()``,
  ``admission_rejects_by_class`` in ``serving_stats()``), so an SLO
  report can show who paid for the overload.

The policy object is frozen and stateless — safe to share across every
shard condition and the serving condition without adding a single lock
edge.
"""

from __future__ import annotations

import dataclasses
import re
import zlib

_TRAILING_INT = re.compile(r"(\d+)\s*$")


@dataclasses.dataclass(frozen=True)
class AdmissionPolicy:
    """Class table + per-class queue budgets.

    ``classes`` are ordered most-protected first. ``depth_fracs`` give
    each class's share of a queue-depth bound: class c is admitted only
    while the queue stands below ``frac[c] * bound`` — so when the
    queue passes the bulk budget, bulk work bounces while protected
    work still lands, which is precisely a strict-priority admission
    curve without any queue reordering."""

    classes: tuple[str, ...] = ("rt", "bulk")
    depth_fracs: tuple[float, ...] = (1.0, 0.5)

    def __post_init__(self):
        if len(self.classes) != len(self.depth_fracs) or not self.classes:
            raise ValueError("classes and depth_fracs must align, non-empty")
        if any(not (0.0 < f <= 1.0) for f in self.depth_fracs):
            raise ValueError("depth_fracs must be in (0, 1]")

    @property
    def n_classes(self) -> int:
        return len(self.classes)

    def classify_index(self, index: int) -> int:
        """Lane/actor INDEX -> class, interleaved (index % n_classes) so
        every class is populated at any fleet size."""
        return int(index) % self.n_classes

    def classify_actor(self, actor_id: str) -> int:
        """Actor-id string -> class: a trailing integer (the fleet's
        ``actor-<i>`` / ``proc-<i>`` convention) classifies by index;
        anything else falls back to a crc32 of the id (NOT ``hash()``,
        which is salted per process and would reclassify actors across
        restarts)."""
        m = _TRAILING_INT.search(actor_id)
        if m is not None:
            return self.classify_index(int(m.group(1)))
        return zlib.crc32(actor_id.encode()) % self.n_classes

    def class_name(self, cls: int) -> str:
        return self.classes[min(max(cls, 0), self.n_classes - 1)]

    def depth_for(self, cls: int, depth_bound: int) -> int:
        """Queue-depth budget for ``cls`` under ``depth_bound``."""
        frac = self.depth_fracs[min(max(cls, 0), self.n_classes - 1)]
        return max(1, int(frac * depth_bound))

    def shed_victim(self, queued_classes: list[int],
                    incoming_cls: int) -> int | None:
        """Pick the shed victim among ``queued_classes`` (queue order,
        oldest first) and the incoming item. Returns the QUEUE INDEX of
        the victim, or None when the incoming item itself is the
        lowest-priority work (caller rejects it instead of evicting
        better-class work — no priority inversion)."""
        if not queued_classes:
            return None
        worst = max(queued_classes)
        if incoming_cls > worst:
            return None
        # oldest item of the worst class present
        return queued_classes.index(worst)
