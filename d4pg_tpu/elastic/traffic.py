"""Seeded offered-load model for the fleet plane.

The harness's flat ``rows_per_sec`` measures the planes at a KNOWN
constant demand; production traffic is nothing like that — it breathes
on a diurnal cycle, spikes in flash crowds, and spreads across actors
on a heavy tail (a few hot lanes carry most of the load). This module
is that load, as a pure function: ``rate(actor, t)`` is fully
determined by ``TrafficConfig`` (seed included), so two models built
from the same config emit bit-for-bit identical traces — the same
replayability contract as the chaos scripts (``fleet/chaos.py``), and
the property the A/B drill leans on to hold OFFERED load equal across
arms while the autoscaler varies everything else.

Determinism discipline (the chaos-script rules):

- every stochastic component draws from its OWN ``SeedSequence``
  branch (disjoint ``spawn_key`` tags), so adding one component never
  shifts another's stream;
- the flash-crowd event stream draws a FIXED number of variates per
  event (gap, duration, amplitude), keeping event k's draws at stream
  offset 3k regardless of parameters;
- the whole schedule is materialized eagerly in ``__init__`` up to
  ``horizon_s`` — after construction the model is IMMUTABLE, so lanes
  on different threads read it lock-free (no lock edges, nothing for
  the lockgraph to even see).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from d4pg_tpu.obs.draw_ledger import LEDGER

# SeedSequence spawn-key tags (disjoint from the chaos planes' 0x5E11 /
# 0xD4B0 / 0xD4E4 / 0xD4E5 tags): diurnal phase, flash-crowd event
# stream, per-actor Pareto weights.
_TAG_DIURNAL = 0xE7A0
_TAG_FLASH = 0xE7A1
_TAG_PARETO = 0xE7A2

# Fixed draw count per flash event (gap, duration, amplitude) — the
# stream-offset stability rule.
_DRAWS_PER_FLASH = 3


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """Everything the offered-load surface depends on. Frozen: the
    config IS the trace identity (hash it, log it, replay it)."""

    seed: int = 0
    n_actors: int = 4
    # fleet-mean per-lane rate at multiplier 1.0 (rows/s); the actual
    # lane rate is base * pareto_weight[actor] * diurnal(t) * flash(t)
    base_rows_per_sec: float = 256.0
    # diurnal component: 1 + amp * sin(2*pi*(t/period + phase)), phase
    # seeded per-run. amp=0 disables. Period is model seconds — scaled
    # way down from 86400 so a bench run crosses full cycles.
    diurnal_amp: float = 0.3
    diurnal_period_s: float = 60.0
    # flash crowds: either a SCRIPTED schedule of (start_s, duration_s,
    # amplitude) triples (the A/B drill pins its crowd this way), or —
    # when None — a seeded renewal process: exponential gaps at
    # ``flash_rate_per_s``, uniform durations/amplitudes in the given
    # ranges, materialized out to ``horizon_s``.
    flash_schedule: tuple[tuple[float, float, float], ...] | None = None
    flash_rate_per_s: float = 0.02
    flash_duration_s: tuple[float, float] = (2.0, 6.0)
    flash_amp: tuple[float, float] = (4.0, 10.0)
    # per-actor heavy tail: Pareto(alpha) weights normalized to mean
    # 1.0 across the fleet (so fleet offered load stays
    # n_actors * base regardless of the tail draw). alpha <= 2 has
    # infinite variance — 1.5 is the classic "few hot lanes" shape.
    pareto_alpha: float = 1.5
    # floor under the composed rate so a deep diurnal trough can never
    # stall a lane entirely (a zero rate would divide the tick period).
    min_rows_per_sec: float = 1.0
    # schedule horizon: flash events are materialized to here; past it
    # the flash multiplier is 1.0 (queries stay valid, just calm).
    horizon_s: float = 3600.0


class TrafficModel:
    """Immutable seeded offered-load surface; see module docstring."""

    def __init__(self, cfg: TrafficConfig):
        self.cfg = cfg
        # diurnal phase: one uniform draw on its own branch (all three
        # construction streams are ledger-wrapped: their draw counts are
        # config-deterministic, so the A/B drivers can pin the
        # schedule.* digest across arms as the equal-load oracle)
        d_rng = LEDGER.wrap("schedule.traffic.diurnal", np.random.default_rng(
            np.random.SeedSequence(cfg.seed, spawn_key=(_TAG_DIURNAL, 0))))
        self._diurnal_phase = float(d_rng.random())
        # per-actor Pareto weights, one branch per actor (adding lanes
        # extends the weight vector without disturbing existing lanes'
        # draws), normalized to mean 1.0
        raw = np.empty(max(1, cfg.n_actors), np.float64)
        for i in range(raw.shape[0]):
            rng = LEDGER.wrap(
                "schedule.traffic.pareto", np.random.default_rng(
                    np.random.SeedSequence(cfg.seed, spawn_key=(_TAG_PARETO, i))))
            u = rng.random()
            raw[i] = (1.0 - u) ** (-1.0 / cfg.pareto_alpha)
        self._weights = raw / raw.mean()
        # flash-crowd schedule: scripted verbatim, or the seeded renewal
        # stream at fixed draws per event
        if cfg.flash_schedule is not None:
            self._flash = [(float(s), float(d), float(a))
                           for s, d, a in cfg.flash_schedule]
        else:
            f_rng = LEDGER.wrap(
                "schedule.traffic.flash", np.random.default_rng(
                    np.random.SeedSequence(cfg.seed, spawn_key=(_TAG_FLASH, 0))))
            events = []
            t = 0.0
            rate = max(1e-9, cfg.flash_rate_per_s)
            while True:
                gap = f_rng.exponential(1.0 / rate)
                dur = f_rng.uniform(*cfg.flash_duration_s)
                amp = f_rng.uniform(*cfg.flash_amp)
                t += gap
                if t >= cfg.horizon_s:
                    break
                events.append((t, dur, amp))
            self._flash = events

    # -- components ---------------------------------------------------------
    def pareto_weight(self, actor: int) -> float:
        return float(self._weights[actor % self._weights.shape[0]])

    def diurnal(self, t: float) -> float:
        c = self.cfg
        if c.diurnal_amp == 0.0:
            return 1.0
        m = 1.0 + c.diurnal_amp * math.sin(
            2.0 * math.pi * (t / c.diurnal_period_s + self._diurnal_phase))
        return max(0.0, m)

    def flash(self, t: float) -> float:
        """Multiplier from flash crowds active at ``t`` (overlapping
        crowds take the max, not the product — two simultaneous events
        are one bigger crowd, not a multiplicative explosion)."""
        m = 1.0
        for start, dur, amp in self._flash:
            if start <= t < start + dur:
                m = max(m, amp)
        return m

    def flash_events(self) -> list[tuple[float, float, float]]:
        return list(self._flash)

    # -- the surface --------------------------------------------------------
    def rate(self, actor: int, t: float) -> float:
        """Offered load for ``actor`` at model time ``t`` (rows/s)."""
        c = self.cfg
        r = (c.base_rows_per_sec * self.pareto_weight(actor)
             * self.diurnal(t) * self.flash(t))
        return max(c.min_rows_per_sec, r)

    def rate_fn(self, actor: int):
        """Per-lane closure for ``ThrottledSender(rate_fn=...)``: the
        lane advances its own model clock tick by tick, so the offered
        schedule is a pure recurrence — independent of wall-clock
        jitter and therefore identical across runs."""
        return lambda t: self.rate(actor, t)

    def trace(self, actor: int, horizon_s: float, dt: float) -> np.ndarray:
        """The offered-load curve sampled on a fixed grid — the
        determinism oracle's artifact (two models, same config, equal
        arrays bit for bit) and the bench block's offered curve."""
        ts = np.arange(0.0, horizon_s, dt, dtype=np.float64)
        return np.array([self.rate(actor, float(t)) for t in ts],
                        np.float64)

    def fleet_trace(self, horizon_s: float, dt: float) -> np.ndarray:
        """Summed offered load across every lane on the same grid."""
        total = np.zeros(int(math.ceil(horizon_s / dt)), np.float64)
        for a in range(self.cfg.n_actors):
            total += self.trace(a, horizon_s, dt)[: total.shape[0]]
        return total
