"""Elastic traffic plane: seeded load model, obs-driven autoscaler,
SLO admission control (docs/architecture.md "Elastic traffic plane").

Three cooperating pieces close the loop the fleet plane left open:

- ``traffic``   — a seeded offered-load model (diurnal curve, flash
                  crowds, per-actor heavy-tailed Pareto rates); every
                  trace is bit-for-bit replayable from its seed, the
                  same contract as the PR-3 chaos scripts.
- ``admission`` — priority classes over actor/lane identity plus the
                  per-class shed/budget policy ``ReplayService`` and
                  ``PolicyInferenceServer`` enforce at admission.
- ``autoscaler``/``ledger`` — the control loop (sense obs-registry
                  providers, decide with hysteresis, actuate live
                  knobs) and the deterministic decision ledger that
                  makes every run's decision stream auditable and
                  replayable.
"""

from d4pg_tpu.elastic.admission import AdmissionPolicy
from d4pg_tpu.elastic.autoscaler import (
    Autoscaler, AutoscalerConfig, ControlPolicy, extract_signals,
)
from d4pg_tpu.elastic.ledger import ScalingLedger
from d4pg_tpu.elastic.traffic import TrafficConfig, TrafficModel

__all__ = [
    "AdmissionPolicy",
    "Autoscaler",
    "AutoscalerConfig",
    "ControlPolicy",
    "ScalingLedger",
    "TrafficConfig",
    "TrafficModel",
    "extract_signals",
]
