"""Target-network update rules as pytree transforms.

Parity: the reference's per-parameter soft update
``theta' <- (1 - tau) * theta' + tau * theta`` (``ddpg.py:110-116``) and hard
update / state_dict copy (``ddpg.py:92-94``). Here these are pure pytree maps
that live *inside* the jit'd learner step — no parameter iteration on the
host, no data movement.
"""

from __future__ import annotations

from typing import TypeVar

import jax
import jax.numpy as jnp

T = TypeVar("T")


def soft_update(target: T, online: T, tau: float) -> T:
    """Polyak-averaged target update over arbitrary parameter pytrees."""
    return jax.tree_util.tree_map(
        lambda t, o: (1.0 - tau) * t + tau * o, target, online
    )


def hard_update(target: T, online: T) -> T:
    """Copy online params into the target pytree (``ddpg.py:92-94``).

    Real copies, not identity aliases: aliased target/online buffers break
    buffer donation in the jit'd update.
    """
    del target
    return jax.tree_util.tree_map(jnp.copy, online)


def tie_encoder(actor_params, critic_params):
    """Replace the actor's ``encoder`` subtree with the critic's
    (``--share_encoder``, SAC-AE/DrQ: the conv encoder is trained by the
    critic loss alone). One definition for every tie site — init, the
    per-step online tie, and the target tie — so the param-tree layout
    assumption lives in exactly one place.

    The tied subtree is COPIED, not aliased: an aliased buffer appears in
    both donated param trees of the jit'd update, and XLA rejects donating
    the same buffer twice (``--share_encoder`` with ``make_multi_update``
    crashed on exactly this). ``jnp.copy`` is identity for autodiff and
    costs ~µs per step against the conv forward/backward it rides with.
    Collections other than 'params' (e.g. a future encoder's batch_stats)
    are preserved from the actor tree untouched."""
    return {**actor_params,
            "params": {**actor_params["params"],
                       "encoder": jax.tree_util.tree_map(
                           jnp.copy, critic_params["params"]["encoder"])}}
