"""Exploration noise as pure PRNG-key-threaded functions with explicit state.

Parity: the reference's ``GaussianNoise`` and ``OrnsteinUhlenbeckProcess``
(``random_process.py:4-21`` and ``:23-45``): epsilon-scaled noise with an
exponential decay schedule ``eps = min_eps + (1 - min_eps) * exp(-decay * k)``
advanced on episode reset, where ``decay = 5 / horizon``.

TPU-first differences:
  - stateless sampling from ``jax.random`` keys instead of the global numpy
    RNG, so per-actor streams are decorrelated by key-splitting and runs are
    reproducible;
  - state (epsilon counter, OU mean-reverting x) is an explicit pytree that
    can be vmapped over a batch of environments and carried through
    ``lax.scan`` rollouts.

Reference quirks deliberately NOT reproduced (documented divergence):
  - ``GaussianNoise.reset`` never increments its counter
    (``random_process.py:19-21``), so a reset would *raise* epsilon from the
    initial 0.3 to 1.0; and the live loop never calls ``reset()`` anyway
    (call commented at ``main.py:366``), freezing epsilon at 0.3. Here the
    decay schedule actually runs, starting from the same eps_0 = 0.3.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array


def _epsilon(k: Array, min_epsilon: float, decay_rate: float) -> Array:
    return min_epsilon + (1.0 - min_epsilon) * jnp.exp(-decay_rate * k)


class GaussianNoiseState(NamedTuple):
    """State of epsilon-decayed Gaussian action noise."""

    epsilon: Array  # scalar (or [num_envs]) current scale
    resets: Array  # int32 reset counter driving the decay


class gaussian:
    """eps * N(mu, sigma^2) per action dim (``random_process.py:4-21``)."""

    @staticmethod
    def init(
        horizon: int = 5000, epsilon_0: float = 0.3, batch_shape: tuple = ()
    ) -> GaussianNoiseState:
        del horizon  # decay rate is recomputed in reset(); kept for symmetry
        return GaussianNoiseState(
            epsilon=jnp.full(batch_shape, epsilon_0, dtype=jnp.float32),
            resets=jnp.zeros(batch_shape, dtype=jnp.int32),
        )

    @staticmethod
    def sample(
        state: GaussianNoiseState,
        key: Array,
        shape: tuple,
        mu: float = 0.0,
        sigma: float = 1.0,
    ) -> Array:
        eps = jnp.reshape(state.epsilon, state.epsilon.shape + (1,) * (len(shape) - state.epsilon.ndim))
        return eps * (mu + sigma * jax.random.normal(key, shape))

    @staticmethod
    def reset(
        state: GaussianNoiseState,
        horizon: int = 5000,
        min_epsilon: float = 0.01,
    ) -> GaussianNoiseState:
        """Advance the decay schedule by one episode."""
        k = state.resets + 1
        return GaussianNoiseState(
            epsilon=_epsilon(k.astype(jnp.float32), min_epsilon, 5.0 / horizon),
            resets=k,
        )


class OUNoiseState(NamedTuple):
    """State of an Ornstein-Uhlenbeck process with epsilon decay."""

    x: Array  # [..., act_dim] mean-reverting state
    epsilon: Array  # scalar (or [...]) scale
    resets: Array  # int32 reset counter


class ou:
    """Temporally correlated OU noise (``random_process.py:23-45``):
    ``x += theta * (mu - x) * dt + sigma * sqrt(dt) * N(0, I)``, scaled by a
    decaying epsilon."""

    @staticmethod
    def init(act_dim: int, batch_shape: tuple = (), epsilon_0: float = 1.0) -> OUNoiseState:
        return OUNoiseState(
            x=jnp.zeros(batch_shape + (act_dim,), dtype=jnp.float32),
            epsilon=jnp.full(batch_shape, epsilon_0, dtype=jnp.float32),
            resets=jnp.zeros(batch_shape, dtype=jnp.int32),
        )

    @staticmethod
    def sample(
        state: OUNoiseState,
        key: Array,
        theta: float = 0.25,
        mu: float = 0.0,
        sigma: float = 0.05,
        dt: float = 0.01,
    ) -> tuple[OUNoiseState, Array]:
        x = state.x + theta * (mu - state.x) * dt + sigma * jnp.sqrt(
            jnp.asarray(dt)
        ) * jax.random.normal(key, state.x.shape)
        eps = state.epsilon[..., None]
        return state._replace(x=x), eps * x

    @staticmethod
    def reset(
        state: OUNoiseState, horizon: int = 5000, min_epsilon: float = 0.01
    ) -> OUNoiseState:
        """Zero the process and advance the epsilon decay
        (``random_process.py:41-45``)."""
        k = state.resets + 1
        return OUNoiseState(
            x=jnp.zeros_like(state.x),
            epsilon=_epsilon(k.astype(jnp.float32), min_epsilon, 5.0 / horizon),
            resets=k,
        )
