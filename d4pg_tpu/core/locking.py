"""Tiered locks: the runtime half of the concurrency correctness plane.

PR 4 made the replay receiver a real concurrent system (K shard workers,
per-shard conditions, per-ring leaf locks, one merge-commit thread) and
the review round immediately found a merge wedge — the class of defect
that only shows up under interleavings no unit test schedules. The
defense is a SINGLE declared lock hierarchy that both the static
lock-graph pass (``d4pg_tpu/lint/lockgraph.py``) and the runtime objects
enforce, so a refactor that inverts an acquisition order is caught by
the linter at review time or by an assertion in the fleet chaos smoke —
never by a wedged ingest plane in production.

``HIERARCHY`` maps tier names to integer tiers, OUTERMOST FIRST. The
rule is **monotone tier descent per thread**: a thread may only acquire
a lock whose tier is STRICTLY below every tier it already holds.
Sequential (non-nested) acquisition is always legal; equal-tier nesting
is a violation (two sibling shard conditions held at once is the classic
hidden deadlock between shard workers). The tier order encodes the
documented discipline of the sharded receiver (docs/architecture.md
"Sharded receiver"):

- ``service``/``buffer`` above everything: the commit thread and the
  learner take them at top level and may reach leaf locks below
  (``stage_block`` under the buffer lock refills from the ring locks).
- ``commit`` above ``shard``/``ring``: commit-cond work under a shard
  or ring leaf lock is exactly the PR-4 merge-wedge shape — a shard
  worker that waits on the merge inbox while holding its own condition
  deadlocks against the commit thread's ``notify``. Descent makes that
  acquisition raise.
- ``shard``/``ring`` are LEAF tiers: nothing in the table sits below
  them, so holding one admits no further tiered acquisition but
  ``ring`` under ``shard`` (a worker staging into its private ring).

In debug mode (``enable_debug``) every acquisition checks descent and
counts contention — acquisitions, contended acquisitions (the lock was
held when we arrived), cumulative wait time, max hold time — keyed by
tier name so the fleet artifact can attribute time to lock waits
(``bench.py --fleet`` → ``locks`` block). Production mode delegates
straight to ``threading`` with no bookkeeping.
"""

from __future__ import annotations

import threading
import time

from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import REGISTRY as _obs_registry

# The declared hierarchy — the single source of truth shared with the
# static pass and the architecture doc. Outermost (largest tier) first.
HIERARCHY: dict[str, int] = {
    # Elastic control plane: the autoscaler's own state (targets, tick
    # counter, stop handshake) lives under one condition ABOVE every
    # data-plane tier. The loop's contract is sense/decide/actuate with
    # NOTHING held — providers and actuator setters take their owners'
    # locks at top level — but the tier placement makes even an
    # accidental hold-across-actuation legal descent rather than a
    # silent inversion, so the sentinels report it instead of wedging.
    "elastic": 60,  # Autoscaler._elastic_cond (targets + tick + stop)
    "service": 50,  # ReplayService._lock (heartbeats, pending, env_steps)
    "buffer": 40,   # ReplayService._buffer_lock (all replay-state access)
    # Multi-learner plane (replica -> aggregator -> store): a replica may
    # hold its control lock while submitting to the aggregator
    # (replica -> agg descends), and the aggregator publishes merged
    # params into the WeightStore while holding its own condition
    # (agg -> wstore descends). A replica must NEVER hold its lock
    # across replay sampling — buffer(40) sits ABOVE replica(36), so the
    # sentinels catch that inversion at the first acquisition.
    "replica": 36,  # LearnerReplica._replica_lock (epoch, counters, flags)
    "agg": 34,      # Aggregator._agg_cond (merge state + sync barrier)
    "commit": 30,   # ReplayService._commit_cond (ordered-merge state)
    # Weight-distribution plane (learner -> actors; disjoint from the
    # ingest tiers above, so its band sits between commit and the leaf
    # tiers): a relay's swap state may publish into its local store
    # (wrelay -> wstore), and a server's frame cache refreshes from the
    # store under the cache lock (wserve -> wstore) — both descend.
    "wrelay": 28,   # WeightRelay._relay_lock (generation swap + counters)
    "wserve": 26,   # WeightServer._frame_lock (version window + frame memo)
    # Serving plane: the inference server's pending queue + adopted
    # params live under one condition. Between wserve and wstore: a
    # refresher that ever snapshots the WeightStore while holding it
    # (pserve -> wstore) descends, and nothing below the weight band
    # may climb into it.
    "pserve": 25,   # PolicyInferenceServer._pserve_cond (pending + params)
    "wstore": 24,   # WeightStore._store_lock (published params + version)
    "shard": 20,    # _IngestShard.cond (admission deque + counters)
    # Sample-on-ingest plane (replay/sampler.py): the dealer's shard-slice
    # PER trees, write-back queues and counters live under ONE sampler
    # lock. Between shard and ring: the commit thread reaches it while
    # holding the buffer lock (insert-priorities + draw + gather in the
    # commit's existing buffer-lock window — buffer -> sampler descends),
    # a shard worker draining its write-back queues takes it at top level,
    # and the dealer pushes dealt blocks into the per-replica rings AFTER
    # releasing it (sampler -> ring would descend, but the publish happens
    # lock-free of the sampler tier anyway). Replica write-back enqueue is
    # sampler-only — the "zero buffer-lock acquisitions on the replica
    # sample path" invariant of ISSUE 12.
    "sampler": 15,  # SampleDealer._sampler_lock (slice trees + queues)
    "ring": 10,     # MultiRingStaging._ring_locks[i] (staging ring slices)
}

_MAX_VIOLATION_RECORDS = 64


class LockHierarchyError(RuntimeError):
    """A thread acquired a tiered lock out of declared order."""


class _TLS(threading.local):
    def __init__(self):
        self.held: list[tuple[int, str]] = []


_tls = _TLS()

_debug = False
_raise_on_violation = True
_registry_lock = threading.Lock()
_instances: list["TieredLock | TieredCondition"] = []
_violations: list[str] = []
_violation_count = 0


def enable_debug(raise_on_violation: bool = True) -> None:
    """Turn on descent assertions + contention counting. ``raise_on_
    violation=False`` records violations instead of raising — the fleet
    harness runs in record mode (a raise inside a worker thread would
    kill the ingest plane mid-measurement and read as a deadlock) and
    asserts the count is zero afterwards."""
    global _debug, _raise_on_violation
    _raise_on_violation = raise_on_violation
    _debug = True


def disable_debug() -> None:
    global _debug
    _debug = False


def debug_enabled() -> bool:
    return _debug


def reset_stats() -> None:
    global _violations, _violation_count
    with _registry_lock:
        _violations = []
        _violation_count = 0
        for inst in _instances:
            inst._reset_stats()


def hierarchy_violations() -> list[str]:
    with _registry_lock:
        return list(_violations)


def violation_count() -> int:
    with _registry_lock:
        return _violation_count


def lock_stats() -> dict[str, dict]:
    """Contention counters aggregated by tier name (all shard conditions
    fold into one ``shard`` row, etc.). ``wait_ns`` is time spent
    blocked on contended acquisitions; ``cond_waits`` counts
    ``Condition.wait`` calls (intentional waiting, kept separate from
    contention)."""
    agg: dict[str, dict] = {}
    with _registry_lock:
        instances = list(_instances)
    for inst in instances:
        row = agg.setdefault(inst.tier_name, {
            "tier": inst.tier, "acquisitions": 0, "contended": 0,
            "wait_ns": 0, "max_hold_ns": 0, "cond_waits": 0,
        })
        row["acquisitions"] += inst._acquisitions
        row["contended"] += inst._contended
        row["wait_ns"] += inst._wait_ns
        row["max_hold_ns"] = max(row["max_hold_ns"], inst._max_hold_ns)
        row["cond_waits"] += getattr(inst, "_cond_waits", 0)
    return agg


def held_tiers() -> list[tuple[int, str]]:
    """The current thread's held (tier, name) stack — for tests."""
    return list(_tls.held)


def _record_violation(msg: str) -> None:
    global _violation_count
    with _registry_lock:
        _violation_count += 1
        if len(_violations) < _MAX_VIOLATION_RECORDS:
            _violations.append(msg)
    # Flight-recorder event (obs/flight), recorded OUTSIDE the registry
    # lock: a hierarchy violation is exactly the event whose surrounding
    # context the postmortem ring exists to preserve — the fleet harness
    # dumps the ring whenever this count is nonzero at run end.
    record_event("lock_violation", msg=msg)
    if _raise_on_violation:
        raise LockHierarchyError(msg)


class _TieredBase:
    """Shared bookkeeping: descent check + contention counters. The
    counters are only mutated by the acquiring/holding thread (pre-hold
    wait folds in right after the acquire lands), so they need no extra
    synchronization; cross-instance aggregation happens at snapshot
    time in ``lock_stats``."""

    def __init__(self, tier_name: str, tier: int | None = None):
        if tier is None:
            if tier_name not in HIERARCHY:
                raise ValueError(
                    f"unknown lock tier {tier_name!r}; declare it in "
                    f"core.locking.HIERARCHY or pass tier= explicitly")
            tier = HIERARCHY[tier_name]
        self.tier_name = tier_name
        self.tier = int(tier)
        self._reset_stats()
        with _registry_lock:
            _instances.append(self)

    def _reset_stats(self) -> None:
        self._acquisitions = 0
        self._contended = 0
        self._wait_ns = 0
        self._max_hold_ns = 0
        self._held_since = 0

    def _check_and_push(self) -> None:
        held = _tls.held
        if held:
            floor = min(t for t, _ in held)
            if self.tier >= floor:
                chain = " -> ".join(n for _, n in held)
                _record_violation(
                    f"hierarchy violation: acquiring '{self.tier_name}' "
                    f"(tier {self.tier}) while holding [{chain}] (floor "
                    f"tier {floor}); declared order is monotone descent "
                    f"({', '.join(f'{k}={v}' for k, v in HIERARCHY.items())})")
        held.append((self.tier, self.tier_name))

    def _pop(self) -> bool:
        # Unconditional on release (debug on or off): a debug-mode flip
        # between a thread's acquire and its release must never strand a
        # phantom entry on the thread-local stack (daemon service threads
        # outlive the harness bracket that armed the sentinels).
        held = _tls.held
        for i in range(len(held) - 1, -1, -1):
            if held[i] == (self.tier, self.tier_name):
                del held[i]
                return True
        return False

    def _on_acquired(self, waited_ns: int, contended: bool) -> None:
        self._acquisitions += 1
        if contended:
            self._contended += 1
            self._wait_ns += waited_ns
        self._held_since = time.perf_counter_ns()

    def _on_release(self) -> None:
        if self._held_since:
            hold = time.perf_counter_ns() - self._held_since
            if hold > self._max_hold_ns:
                self._max_hold_ns = hold
            self._held_since = 0


class TieredLock(_TieredBase):
    """``threading.Lock`` carrying a tier from the declared hierarchy."""

    def __init__(self, tier_name: str, tier: int | None = None):
        super().__init__(tier_name, tier)
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _debug:
            return self._inner.acquire(blocking, timeout)
        self._check_and_push()
        t0 = time.perf_counter_ns()
        got = self._inner.acquire(False)
        contended = not got
        if not got and blocking:
            got = self._inner.acquire(True, timeout)
        if got:
            self._on_acquired(time.perf_counter_ns() - t0, contended)
        else:
            self._pop()
        return got

    def release(self) -> None:
        if _debug:
            self._on_release()
        self._pop()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TieredLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TieredCondition(_TieredBase):
    """``threading.Condition`` carrying a tier. ``wait`` releases the
    underlying lock, so the held-stack entry and the hold-time segment
    are closed across the wait and reopened on wake (the re-acquisition
    after a wake is not re-checked: descent was asserted when the
    condition was first entered, and the thread's other held locks
    cannot have changed while it was blocked in ``wait``)."""

    def __init__(self, tier_name: str, tier: int | None = None):
        super().__init__(tier_name, tier)
        self._inner = threading.Condition()
        self._cond_waits = 0

    def _reset_stats(self) -> None:
        super()._reset_stats()
        self._cond_waits = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _debug:
            return self._inner.acquire(blocking, timeout)
        self._check_and_push()
        t0 = time.perf_counter_ns()
        got = self._inner.acquire(False)
        contended = not got
        if not got and blocking:
            got = self._inner.acquire(True, timeout)
        if got:
            self._on_acquired(time.perf_counter_ns() - t0, contended)
        else:
            self._pop()
        return got

    def release(self) -> None:
        if _debug:
            self._on_release()
        self._pop()
        self._inner.release()

    def __enter__(self) -> "TieredCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None) -> bool:
        if _debug:
            self._cond_waits += 1
            self._on_release()
        popped = self._pop()
        try:
            return self._inner.wait(timeout)
        finally:
            if popped:  # re-open exactly the entry the wait released
                _tls.held.append((self.tier, self.tier_name))
            if _debug:
                self._held_since = time.perf_counter_ns()

    def wait_for(self, predicate, timeout: float | None = None):
        # mirror threading.Condition.wait_for in terms of our wait()
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def _locks_snapshot() -> dict:
    """The unified-registry view of the lock plane: per-tier contention
    counters + the hierarchy-violation tally. Same consistency contract
    as the bespoke accessors it wraps (counters are owner-thread-mutated
    and aggregated at snapshot time; see ``lock_stats``)."""
    return {
        "debug": _debug,
        "hierarchy_violations": violation_count(),
        "per_lock": lock_stats(),
    }


# module-level function: strong registration is fine (the lock plane
# lives for the process, like the registry itself)
_obs_registry.register_provider("locks", _locks_snapshot)
