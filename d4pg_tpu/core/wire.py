"""Declared wire-protocol registry: the single table of every frame magic.

Five hand-rolled wire planes cross process boundaries (ingest
0xD4F6/0xD4F8, weights 0xD4F7/0xD4FC, updates 0xD4AB, serving
0xD4E2/0xD4E3), plus the 0xD4FA generation greeting and the D4RS
snapshot sidecar. Their correctness depends on framing being exactly
symmetric between encoder and decoder — same magic, same header
``struct`` format, same flag-byte bit meanings, same CRC discipline.
This module is the ONE place those facts are declared; the plane
modules (transport, weight_server, weight_plane, update_plane,
serving.protocol, io.checkpoint) import from here instead of
re-declaring privately.

Enforcement is threefold, the same house pattern as the lock tiers
(core.locking.HIERARCHY / lint.lockgraph):

  1. this declared table — what the protocol IS;
  2. a stdlib-only static mirror in ``d4pg_tpu.lint.wiregraph`` that
     independently *discovers* the protocol surface from the AST
     (pack/unpack sites, magic literals, flag constants) and lints it
     against the declaration (families ``wire-magic-registry``,
     ``codec-asymmetry``, ``unchecked-frame``, ``flag-bit-collision``);
  3. a tier-1 equality pin (tests/test_lint_clean.py) that the mirror,
     the discovered surface, and this table agree exactly.

Minting a new magic or flag bit therefore means adding it HERE first —
an undeclared 0xD4xx packed into a frame fails the lint gate.

Stdlib-only (``struct`` + ``dataclasses``): importable from anywhere,
including non-accelerator tooling.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

# --------------------------------------------------------------------------
# Magics. One u32 (or 4-byte prefix) per frame family; all socket magics
# live in the 0xD4xx page. Seed-derivation uses of 0xD4xx literals
# (SeedSequence spawn keys, default_rng XOR salts) are NOT wire magics
# and are exempted by the lint pass.
# --------------------------------------------------------------------------

MAGIC_INGEST_V1 = 0xD4F6  # transition frames, npz payload
MAGIC_INGEST_V2 = 0xD4F8  # transition frames, raw column payload
MAGIC_GEN_GREETING = 0xD4FA  # server->client generation greeting (u16 on wire)
MAGIC_WEIGHTS_V1 = 0xD4F7  # legacy full-snapshot weight pull
MAGIC_WEIGHTS_V2 = 0xD4FC  # versioned delta/full weight plane
MAGIC_UPDATE = 0xD4AB  # learner update submission + ack
MAGIC_SERVE_REQUEST = 0xD4E2  # policy inference request
MAGIC_SERVE_RESPONSE = 0xD4E3  # policy inference response
SIDECAR_MAGIC = b"D4RS"  # replay snapshot sidecar file prefix (not socket-facing)

# --------------------------------------------------------------------------
# Header / extension structs. Each format string is written ONCE, here,
# as the Struct constructor literal; the registry table below references
# the compiled ``.format`` so declaration and compilation cannot drift.
# --------------------------------------------------------------------------

FRAME_HEADER = struct.Struct("!II")  # [magic][payload len] outer framing
GEN_GREETING = struct.Struct("!HI")  # [u16 magic][u32 generation]

# ingest v2 raw-payload header walk, in fixed order:
#   [pre][actor id bytes][trace ext?][generation ext?][field table]
RAW_PRE = struct.Struct("!BB")  # [flag byte][actor-id length]
RAW_TRACE = struct.Struct("!Qd")  # trace ext: [trace id][t_enqueue]
RAW_GEN = struct.Struct("!I")  # generation ext: [generation]
RAW_NFIELDS = struct.Struct("!B")  # field-table prefix: [field count]
RAW_FIELD_PRE = struct.Struct("!BB")  # per field: [dtype-str len][ndim]

WEIGHTS_V1_REQ = struct.Struct("!Iq")  # [magic][have_version]
WEIGHTS_V1_RESP = struct.Struct("!II")  # [magic][payload len]
WEIGHTS_V2_REQ = struct.Struct("!IqIBB")  # [magic][have_ver][have_gen][codec][flags]
WEIGHTS_V2_RESP = struct.Struct("!IBII")  # [magic][kind][crc32][payload len]

# [magic][replica][epoch][generation][version][base_version][clock]
# [weight][flags][crc32][payload len]
UPDATE_HEADER = struct.Struct("!IIIIqqqdBII")
# [magic][status][version][lag][weight][clipped]
UPDATE_ACK = struct.Struct("!IBqqdB")

SERVE_REQ_HEADER = struct.Struct("!BIHHI")  # [flags][req_id][n_rows][obs_dim][crc32]
SERVE_RSP_HEADER = struct.Struct("!BIIIHHI")  # [status][req_id][gen][ver][rows][dim][crc]
SERVE_TRACE_EXT = struct.Struct("!Qd")  # [trace id][t_submit]

SIDECAR_HEAD = struct.Struct("!4sBI")  # [b"D4RS"][version][crc32]
SIDECAR_VERSION = 1

# --------------------------------------------------------------------------
# Flag-byte bit allocations, per plane. A plane's flag byte is a single
# namespace: two extensions claiming the same bit is a wire break
# (lint family ``flag-bit-collision``). Bits not declared here are
# unallocated — packing them fails ``wire-magic-registry``.
# --------------------------------------------------------------------------

F_COUNT = 0x01  # ingest bit0: payload carries a transition count
F_TRACE = 0x02  # ingest bit1: RAW_TRACE extension present
F_GEN = 0x04  # ingest bit2: RAW_GEN extension present
WFLAG_DELTA = 0x01  # weights bit0: client can apply a delta frame
SFLAG_TRACE = 0x01  # serving bit0: SERVE_TRACE_EXT present

# --------------------------------------------------------------------------
# Payload caps (shared admission bound per plane).
# --------------------------------------------------------------------------

MAX_PAYLOAD = 64 << 20  # ingest / weights / updates frames
MAX_BODY = 8 << 20  # serving request/response bodies


@dataclass(frozen=True)
class FrameSpec:
    """One frame family: a magic, its owning plane, and its codec facts.

    ``crc`` is the CRC discipline: ``"none"`` or ``"crc32-payload"``
    (a u32 crc32 of the payload travels in the header and MUST be
    checked before the payload is parsed). ``flags`` are the
    ``(bit, meaning)`` allocations of this frame's flag byte;
    ``extensions`` are the ``(name, format)`` sub-structs that follow
    the header, in wire order where the order is fixed.
    """

    name: str
    plane: str  # ingest | weights | updates | serving | recovery
    magic: object  # int for socket frames, bytes for the file sidecar
    header: str  # struct format of the magic-bearing header
    crc: str = "none"
    flags: tuple = ()
    extensions: tuple = ()

    @property
    def header_size(self) -> int:
        return struct.calcsize(self.header)


REGISTRY: dict[str, FrameSpec] = {
    spec.name: spec
    for spec in (
        FrameSpec("ingest-v1", "ingest", MAGIC_INGEST_V1, FRAME_HEADER.format),
        FrameSpec(
            "ingest-v2",
            "ingest",
            MAGIC_INGEST_V2,
            FRAME_HEADER.format,
            flags=((F_COUNT, "count"), (F_TRACE, "trace"), (F_GEN, "generation")),
            extensions=(
                ("pre", RAW_PRE.format),
                ("trace", RAW_TRACE.format),
                ("generation", RAW_GEN.format),
                ("nfields", RAW_NFIELDS.format),
                ("field-pre", RAW_FIELD_PRE.format),
            ),
        ),
        FrameSpec("gen-greeting", "ingest", MAGIC_GEN_GREETING, GEN_GREETING.format),
        FrameSpec("weights-v1-req", "weights", MAGIC_WEIGHTS_V1, WEIGHTS_V1_REQ.format),
        FrameSpec("weights-v1-resp", "weights", MAGIC_WEIGHTS_V1, WEIGHTS_V1_RESP.format),
        FrameSpec(
            "weights-v2-req",
            "weights",
            MAGIC_WEIGHTS_V2,
            WEIGHTS_V2_REQ.format,
            flags=((WFLAG_DELTA, "delta"),),
        ),
        FrameSpec(
            "weights-v2-resp",
            "weights",
            MAGIC_WEIGHTS_V2,
            WEIGHTS_V2_RESP.format,
            crc="crc32-payload",
        ),
        FrameSpec(
            "update-req", "updates", MAGIC_UPDATE, UPDATE_HEADER.format,
            crc="crc32-payload",
        ),
        FrameSpec("update-ack", "updates", MAGIC_UPDATE, UPDATE_ACK.format),
        FrameSpec(
            "serve-request",
            "serving",
            MAGIC_SERVE_REQUEST,
            FRAME_HEADER.format,
            crc="crc32-payload",
            flags=((SFLAG_TRACE, "trace"),),
            extensions=(
                ("req-header", SERVE_REQ_HEADER.format),
                ("trace", SERVE_TRACE_EXT.format),
            ),
        ),
        FrameSpec(
            "serve-response",
            "serving",
            MAGIC_SERVE_RESPONSE,
            FRAME_HEADER.format,
            crc="crc32-payload",
            extensions=(("rsp-header", SERVE_RSP_HEADER.format),),
        ),
        FrameSpec(
            "sidecar", "recovery", SIDECAR_MAGIC, SIDECAR_HEAD.format,
            crc="crc32-payload",
        ),
    )
}


def _magic_planes() -> dict:
    """Magic -> owning plane; a magic shared by req/resp specs must agree."""
    planes: dict = {}
    for spec in REGISTRY.values():
        prev = planes.setdefault(spec.magic, spec.plane)
        if prev != spec.plane:
            raise AssertionError(
                f"magic {spec.magic!r} claimed by planes {prev} and {spec.plane}"
            )
    return planes


MAGIC_PLANES = _magic_planes()


def _plane_flag_bits() -> dict:
    """Plane -> {bit: meaning}; a bit claimed twice with different
    meanings is a declaration-time collision."""
    bits: dict = {}
    for spec in REGISTRY.values():
        table = bits.setdefault(spec.plane, {})
        for bit, meaning in spec.flags:
            prev = table.setdefault(bit, meaning)
            if prev != meaning:
                raise AssertionError(
                    f"plane {spec.plane} flag bit {bit:#04x} claimed as "
                    f"both {prev!r} and {meaning!r}"
                )
    return bits


PLANE_FLAG_BITS = _plane_flag_bits()


def ingest_v2_layout(flags: int, aid_len: int) -> dict:
    """Declared byte offsets of an ingest-v2 payload carrying ``flags``.

    The v2 raw header is [RAW_PRE][actor id][trace?][generation?][field
    table] in that fixed order. The zero-decode admission readers
    (``transport.raw_frame_meta*``) and the full decoder both walk the
    header through THESE offsets, so admission can never drift from the
    codec. Absent extensions report offset -1; ``"fields"`` is where
    the field table starts.
    """
    off = RAW_PRE.size + aid_len
    layout = {"aid": RAW_PRE.size, "trace": -1, "generation": -1}
    if flags & F_TRACE:
        layout["trace"] = off
        off += RAW_TRACE.size
    if flags & F_GEN:
        layout["generation"] = off
        off += RAW_GEN.size
    layout["fields"] = off
    return layout
