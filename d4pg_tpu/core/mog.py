"""Mixture-of-Gaussian distributional critic math.

The reference declared a ``mixture_of_gaussian`` critic family but left every
branch an empty TODO (``models.py:63-65, 85-87``; ``ddpg.py:48-50,
224-226``). This module implements it properly:

  - the Bellman-backed target of a MoG is again a MoG with
    ``mu' = r + gamma^n * (1 - d) * mu`` and ``std' = gamma^n * std`` (for
    terminals the target collapses toward a point mass at r; a std floor
    keeps the log-density finite),
  - the critic loss is the cross-entropy H(target, pred) estimated with a
    fixed number of reparameterized samples from the (stop-gradient) target
    mixture — fully jittable, PRNG-key-threaded,
  - expected Q is the closed-form mixture mean, used for the policy loss.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import Array

from d4pg_tpu.models.critic import MoGParams

# Plain Python float: a module-level jnp call would initialize the default
# backend at import time, before callers can select a platform.
_LOG2PI = math.log(2.0 * math.pi)


def mog_log_prob(params: MoGParams, x: Array) -> Array:
    """log p(x) under the mixture. x: [..., S] -> [..., S]."""
    mu = params.means[..., None, :]  # [..., 1, K]
    std = params.stds[..., None, :]
    lw = params.log_weights[..., None, :]
    z = (x[..., :, None] - mu) / std
    comp = -0.5 * (z * z + _LOG2PI) - jnp.log(std)
    return jax.nn.logsumexp(lw + comp, axis=-1)


def mog_mean(params: MoGParams) -> Array:
    """Closed-form E[Z] = sum_k w_k mu_k."""
    return jnp.sum(jnp.exp(params.log_weights) * params.means, axis=-1)


def mog_target(
    params: MoGParams, rewards: Array, discounts: Array, min_std: float = 1e-2
) -> MoGParams:
    """Bellman-map the target critic's mixture: affine shift/scale of each
    component (discounts = gamma^n * (1 - done))."""
    return MoGParams(
        log_weights=params.log_weights,
        means=rewards[..., None] + discounts[..., None] * params.means,
        stds=jnp.maximum(discounts[..., None] * params.stds, min_std),
    )


def mog_td_loss(
    pred: MoGParams,
    target: MoGParams,
    key: Array,
    n_samples: int = 32,
    weights: Array | None = None,
) -> tuple[Array, Array]:
    """Sampled cross-entropy -E_{z~target}[log p_pred(z)].

    Returns (scalar loss, per-sample td_error) like
    ``losses.categorical_td_loss``; td_error is the per-transition CE
    estimate (the PER priority signal for the MoG family).
    """
    target = jax.tree_util.tree_map(jax.lax.stop_gradient, target)
    batch_shape = target.means.shape[:-1]
    k = target.means.shape[-1]
    key_c, key_z = jax.random.split(key)
    comp = jax.random.categorical(
        key_c, target.log_weights[..., None, :], axis=-1,
        shape=batch_shape + (n_samples,),
    )  # [..., S]
    mu = jnp.take_along_axis(target.means, comp, axis=-1)
    std = jnp.take_along_axis(target.stds, comp, axis=-1)
    z = mu + std * jax.random.normal(key_z, mu.shape)
    td = -jnp.mean(mog_log_prob(pred, z), axis=-1)  # [...]
    loss = jnp.mean(td if weights is None else weights * td)
    return loss, td
