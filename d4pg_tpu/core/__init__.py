"""Pure-functional core: distributional ops, losses, target updates, noise.

Everything here is shape-polymorphic, jit-able, and PRNG-key-threaded. No
mutable state, no host round-trips — this layer is what compiles onto the TPU.
"""

from d4pg_tpu.core.distribution import (
    CategoricalSupport,
    categorical_projection,
    projection_weights,
)
from d4pg_tpu.core.losses import (
    categorical_td_loss,
    expected_q,
    policy_loss,
)
from d4pg_tpu.core.noise import GaussianNoiseState, OUNoiseState, gaussian, ou
from d4pg_tpu.core.updates import hard_update, soft_update

__all__ = [
    "CategoricalSupport",
    "categorical_projection",
    "projection_weights",
    "categorical_td_loss",
    "expected_q",
    "policy_loss",
    "GaussianNoiseState",
    "OUNoiseState",
    "gaussian",
    "ou",
    "hard_update",
    "soft_update",
]
