"""Categorical value-distribution support and Bellman projection.

Capability parity with the reference's categorical machinery
(``ddpg.py:42-47`` support construction; ``ddpg.py:122-140`` vectorized
projection; ``ddpg.py:142-185`` the live per-atom-loop projection), designed
TPU-first: the projection is expressed as a dense interpolation-weight matmul
so XLA maps it onto the MXU instead of the reference's host-side
``np.add.at`` scatter / boolean-mask writes, which do not translate to
compiled TPU code.

Semantics implemented (the spec both reference impls define):
  Tz_i = clip(r + gamma^n * (1 - done) * z_i, v_min, v_max)
  b_i  = (Tz_i - v_min) / delta
  p_i's mass is linearly split between floor(b_i) and ceil(b_i).
Terminal transitions collapse the target onto a delta distribution at
clip(r): with discount 0 every Tz_i equals clip(r), and since p sums to 1
the projected distribution is exactly the reference's terminal overwrite
(``ddpg.py:165-181``). Unlike the live reference impl (which uses plain
``gamma`` even for n-step transitions, ``ddpg.py:155``), the n-step discount
gamma^n is applied as the reference's *intended* vectorized impl does
(``ddpg.py:129``, ``n_step_gamma`` from ``ddpg.py:24``).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import Array


@dataclasses.dataclass(frozen=True)
class CategoricalSupport:
    """Fixed categorical support over returns: n_atoms bins on [v_min, v_max].

    Mirrors the reference's support construction (``ddpg.py:42-47``):
    ``delta = (v_max - v_min) / (n_atoms - 1)`` and
    ``atoms[i] = v_min + i * delta`` (bin *centers* including both endpoints).
    """

    v_min: float
    v_max: float
    n_atoms: int

    @property
    def delta(self) -> float:
        return (self.v_max - self.v_min) / float(self.n_atoms - 1)

    @property
    def atoms(self) -> Array:
        return jnp.linspace(self.v_min, self.v_max, self.n_atoms)

    def replace(self, **kw) -> "CategoricalSupport":
        return dataclasses.replace(self, **kw)


def projection_weights(support: CategoricalSupport, target_atoms: Array) -> Array:
    """Interpolation-weight tensor W with W[..., i, j] = mass fraction of
    target atom i that lands on support bin j.

    ``target_atoms`` has shape [..., n_atoms] (already Bellman-mapped and
    clipped). Returns [..., n_atoms, n_atoms]. Rows sum to 1.

    The linear-interpolation split onto floor/ceil bins is exactly
    ``clip(1 - |b_i - j|, 0, 1)``: for fractional b it puts (u - b) on l and
    (b - l) on u; for integral b it puts 1 on that bin — the same mass
    placement as the reference's eq/ne-mask branches (``ddpg.py:160-164``).
    Expressing it this way turns the scatter-add into a dense matmul the MXU
    executes directly.
    """
    b = (target_atoms - support.v_min) / support.delta  # [..., A]
    j = jnp.arange(support.n_atoms, dtype=b.dtype)
    return jnp.clip(1.0 - jnp.abs(b[..., :, None] - j), 0.0, 1.0)


def categorical_projection(
    support: CategoricalSupport,
    target_probs: Array,
    rewards: Array,
    discounts: Array,
) -> Array:
    """Project the Bellman-backed target distribution onto the fixed support.

    Args:
      support: the categorical support.
      target_probs: [..., n_atoms] probabilities of Z(s', pi(s')) from the
        target critic.
      rewards: [...] (n-step folded) rewards.
      discounts: [...] per-sample effective discount, i.e.
        ``gamma**n * (1 - done)``. Terminal transitions pass 0 here, which
        reproduces the reference's terminal-overwrite branch exactly.

    Returns:
      [..., n_atoms] projected probabilities (rows sum to 1).
    """
    tz = rewards[..., None] + discounts[..., None] * support.atoms
    tz = jnp.clip(tz, support.v_min, support.v_max)
    w = projection_weights(support, tz)  # [..., A, A]
    # [..., 1, A] @ [..., A, A] -> [..., A]; contraction over source atoms.
    return jnp.einsum("...i,...ij->...j", target_probs, w)
