"""D4PG losses, expressed as pure functions over distributions.

Parity targets in the reference:
  - distributional critic loss: cross-entropy between the projected target
    distribution and the predicted distribution,
    ``-(proj * log(q + 1e-10)).sum(-1).mean()`` (``ddpg.py:217``);
  - PER priority signal (``ddpg.py:220-222``);
  - policy loss: ``-(Z(s, pi(s)) @ bin_centers).mean()`` — the negative
    expected Q through the support bin centers (``ddpg.py:236-238``).

Deviations (deliberate, documented):
  - Importance-sampling weights are *applied* to the critic loss here. The
    reference computes IS weights in its PER sampler
    (``prioritized_replay_memory.py:303-311``) but never multiplies them into
    the loss — we implement the PER algorithm as specified (Schaul et al.),
    with ``weights=None`` recovering the reference's unweighted behavior.
  - ``td_error`` offers the standard per-sample cross-entropy in addition to
    the reference's ``-(proj * q).sum(-1)`` signal (which is not a KL/CE and
    can be negative before the abs); both are available, cross-entropy is the
    default priority signal.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from d4pg_tpu.core.distribution import CategoricalSupport

_LOG_EPS = 1e-10  # matches the reference's log(q + 1e-010), ddpg.py:217


def cross_entropy_per_sample(proj: Array, pred_probs: Array) -> Array:
    """Per-sample CE between projected target and predicted distribution.

    proj, pred_probs: [..., n_atoms] -> [...].
    """
    return -jnp.sum(proj * jnp.log(pred_probs + _LOG_EPS), axis=-1)


def weighted_mean(td: Array, weights: Array | None = None) -> Array:
    """THE loss reduction: mean of per-sample errors, PER IS-weighted when
    ``weights`` is given. One definition shared by every critic-loss path
    (einsum, fused Pallas, MoG) so the weighting convention cannot
    diverge between them."""
    return jnp.mean(td if weights is None else weights * td)


def categorical_td_loss(
    proj: Array,
    pred_probs: Array,
    weights: Array | None = None,
) -> tuple[Array, Array]:
    """Distributional critic loss and per-sample TD error.

    Returns ``(scalar_loss, td_error)`` where ``td_error`` ([...]) is the
    per-sample cross-entropy — the PER priority signal. ``weights`` are PER
    importance-sampling weights ([...]) applied to the mean; ``None`` means
    uniform (reference behavior).
    """
    td = cross_entropy_per_sample(proj, pred_probs)
    return weighted_mean(td, weights), td


def reference_td_error(proj: Array, pred_probs: Array) -> Array:
    """The reference's exact priority signal, ``-(proj * q).sum(-1)``
    (``ddpg.py:220-222``). Provided for strict parity experiments."""
    return -jnp.sum(proj * pred_probs, axis=-1)


def expected_q(support: CategoricalSupport, probs: Array) -> Array:
    """E[Z] via the support bin centers: [..., n_atoms] -> [...]."""
    return jnp.sum(probs * support.atoms, axis=-1)


def policy_loss(support: CategoricalSupport, critic_probs: Array) -> Array:
    """Deterministic policy-gradient loss: negative mean expected Q of
    Z(s, pi(s)) (``ddpg.py:236-238``)."""
    return -jnp.mean(expected_q(support, critic_probs))
