"""d4pg_tpu — a TPU-native distributed distributional DDPG (D4PG) framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``ajgupta93/d4pg-pytorch`` (reference mounted at /root/reference):

- categorical (C51-style) distributional critic with configurable value support,
  plus a real mixture-of-Gaussian critic head (a stub in the reference,
  ``models.py:63-65``),
- categorical Bellman projection as an MXU-friendly one-hot interpolation
  matmul (replacing host-side numpy loops, reference ``ddpg.py:142-185``),
- uniform and prioritized replay (vectorized segment trees + optional C++
  native sampler), n-step returns, HER,
- Gaussian / Ornstein-Uhlenbeck exploration with PRNG-key discipline,
- a single jit'd learner update (losses, grads, Adam, soft target update in
  one XLA computation), data-parallel over a ``jax.sharding.Mesh`` via
  ``shard_map`` + ``psum`` over ICI,
- actor/evaluator/replay services for distributed actor fan-out,
- typed config, TensorBoard metrics, Orbax checkpoint/resume, plotting CLI.

See SURVEY.md for the reference analysis this build follows.
"""

__version__ = "0.1.0"
