"""Startup micro-autotuner for the categorical-projection implementation.

BENCH_r05 measured the K-scan update rate per ``--projection`` variant at
the Humanoid bench shape as einsum 12.6k > pallas 10.3k > pallas_ce 8.7k
steps/s — i.e. the best variant is an empirical fact of the (batch,
atoms, chip) triple, not something a default can know. ``--projection
auto`` (the config default) times the candidates ON THE ACTUAL SHAPES at
startup and picks the winner; an explicit ``--projection einsum|pallas|
pallas_ce`` remains the escape hatch and is honored verbatim.

What gets timed: the critic-loss core each variant actually changes —
``value_and_grad`` of the projected-Bellman cross-entropy at [B, A]
(projection forward for einsum/pallas, the fused forward+custom-VJP for
pallas_ce) — under jit, warmed up, best-of-``repeats`` wall time. The
surrounding network passes are identical across variants and would only
dilute the signal.

Static policy short-circuits (no timing, reason recorded):

  - non-TPU backends: CPU runs Pallas in interpret mode (measures the
    emulator, not a kernel) and other backends have no Pallas lowering —
    einsum is the only real candidate either way;
  - mesh/multi-host learners: the Pallas kernels have no GSPMD
    partitioning rule (``parallel/data_parallel.check_mesh_compatible``
    rejects them), so einsum is the only legal candidate.

Results are cached per (batch, support, backend) so repeated learner
builds in one process autotune once; the selection is logged once with
its timings so run logs name the variant actually compiled in.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

CANDIDATES = ("einsum", "pallas", "pallas_ce")


@dataclasses.dataclass(frozen=True)
class AutotuneResult:
    selected: str
    reason: str
    timings_ms: dict | None = None  # per-candidate best step time (None =
    #                                 static policy, nothing was timed)

    def as_json(self) -> dict:
        return {"selected": self.selected, "reason": self.reason,
                "timings_ms": self.timings_ms}


_CACHE: dict[tuple, AutotuneResult] = {}
_LOGGED: set[tuple] = set()

# Unified arbitration ledger: every select_* surface records its latest
# decision here, and bench.py persists the WHOLE ledger as ONE
# schema-versioned ``autotune`` block (autotune_block) instead of each
# surface ad-hoc logging its own key. Keyed by surface name
# ('projection', 'sampler', ...); latest selection wins.
AUTOTUNE_SCHEMA = 1
_SURFACES: dict[str, AutotuneResult] = {}


def _record(surface: str, result: AutotuneResult) -> AutotuneResult:
    _SURFACES[surface] = result
    return result


def autotune_block() -> dict:
    """The bench artifact's ``autotune`` block: chosen arm + timings for
    every arbitration surface that ran this process, one schema under
    one key (the satellite-2 contract; tests/test_devsample.py pins the
    shape)."""
    return {
        "metric": "autotune",
        "schema": AUTOTUNE_SCHEMA,
        "surfaces": {name: r.as_json() for name, r in _SURFACES.items()},
    }


def _loss_fn(variant: str, support, interpret: bool):
    import jax

    from d4pg_tpu.core.distribution import categorical_projection
    from d4pg_tpu.core.losses import categorical_td_loss, weighted_mean

    if variant == "pallas_ce":
        from d4pg_tpu.ops.projection_ce import projection_ce_pallas

        def loss(pred, tp, r, d):
            td = projection_ce_pallas(support, tp, r, d, pred, interpret)
            return weighted_mean(td, None)

        return loss

    if variant == "pallas":
        from d4pg_tpu.ops.projection import projection_pallas

        def project(tp, r, d):
            return projection_pallas(support, tp, r, d, interpret)
    else:
        def project(tp, r, d):
            return categorical_projection(support, tp, r, d)

    def loss(pred, tp, r, d):
        proj = jax.lax.stop_gradient(project(tp, r, d))
        return categorical_td_loss(proj, pred)[0]

    return loss


def _time_variant(variant: str, support, batch_size: int,
                  repeats: int, iters: int) -> float:
    """Best-of-``repeats`` wall time (ms) of one jitted grad step of the
    variant's loss core at [batch_size, n_atoms]."""
    import jax
    import jax.numpy as jnp

    interpret = jax.default_backend() == "cpu"
    rng = np.random.default_rng(0)
    a = support.n_atoms
    tp = rng.random((batch_size, a)).astype(np.float32)
    tp /= tp.sum(-1, keepdims=True)
    pred = jnp.asarray(tp)
    tp = jnp.asarray(tp)
    r = jnp.asarray(rng.standard_normal(batch_size).astype(np.float32))
    d = jnp.full((batch_size,), 0.99, jnp.float32)

    step = jax.jit(jax.value_and_grad(_loss_fn(variant, support, interpret)))
    v, g = step(pred, tp, r, d)  # warmup/compile
    jax.block_until_ready(g)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            v, g = step(pred, tp, r, d)
        jax.block_until_ready(g)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best * 1e3


def autotune_projection(batch_size: int, v_min: float, v_max: float,
                        n_atoms: int, repeats: int = 3,
                        iters: int = 20) -> AutotuneResult:
    """Time every candidate at the given shapes on the live backend and
    return the winner. TPU-only by policy (see module docstring) — the
    caller gates; this function times whatever backend is active."""
    from d4pg_tpu.core.distribution import CategoricalSupport

    support = CategoricalSupport(float(v_min), float(v_max), int(n_atoms))
    timings = {}
    for variant in CANDIDATES:
        try:
            timings[variant] = round(
                _time_variant(variant, support, batch_size, repeats, iters),
                4)
        except Exception as e:  # a kernel that fails to lower loses, not
            timings[variant] = None  # the whole startup
            timings[f"{variant}_error"] = f"{type(e).__name__}: {e}"
    timed = {k: v for k, v in timings.items() if isinstance(v, float)}
    if not timed:
        return AutotuneResult("einsum", "all candidates failed to time",
                              timings)
    best = min(timed, key=timed.get)
    return AutotuneResult(best, "measured fastest grad step at shape "
                          f"[{batch_size}, {n_atoms}]", timings)


def select_projection(flag: str, *, batch_size: int, v_min: float,
                      v_max: float, n_atoms: int,
                      mesh: bool = False) -> AutotuneResult:
    """Resolve a ``--projection`` flag to a concrete implementation.

    Explicit flags pass through untouched (the escape hatch); ``'auto'``
    applies the static policy, then measures when measuring is
    meaningful. Logs the selection (once per distinct choice) so every
    run names the variant it trains with."""
    if flag != "auto":
        return _record("projection",
                       AutotuneResult(flag, "explicit --projection override"))
    import jax

    backend = jax.default_backend()
    key = ("sel", batch_size, float(v_min), float(v_max), int(n_atoms),
           bool(mesh), backend)
    if key not in _CACHE:
        if mesh:
            result = AutotuneResult(
                "einsum", "mesh learner: Pallas kernels have no GSPMD "
                "partitioning rule (einsum is the only legal candidate)")
        elif backend != "tpu":
            result = AutotuneResult(
                "einsum", f"{backend} backend: Pallas would run in "
                "interpret/fallback mode — nothing real to time")
        else:
            result = autotune_projection(batch_size, v_min, v_max, n_atoms)
        _CACHE[key] = result
    result = _CACHE[key]
    log_key = (key, result.selected)
    if log_key not in _LOGGED:
        _LOGGED.add(log_key)
        timed = (f" timings_ms={result.timings_ms}"
                 if result.timings_ms else "")
        print(f"[autotune] projection='{result.selected}' "
              f"({result.reason}){timed}", flush=True)
    return _record("projection", result)


SAMPLER_ARMS = ("scan", "pallas", "host")


def autotune_sampler(capacity: int, k: int, batch_size: int,
                     repeats: int = 3, iters: int = 20) -> AutotuneResult:
    """Time the two DEVICE descent arms on the live backend at the real
    (capacity, K, B) shape — a synthetic tree with random positive
    priorities, [K*B] stratified queries — and return the faster. The
    'host' arm is never timed here: it is the PR-12 fallback the caller
    constructs when the device plane is unavailable, not a device
    candidate (the three-arm wall-clock A/B lives in bench.py's sampler
    block, where all three run the full wire-to-grad path)."""
    import jax
    import jax.numpy as jnp

    from d4pg_tpu.replay import device_per as dper
    from d4pg_tpu.ops.sampler_descent import descend_pallas, pallas_fits

    interpret = jax.default_backend() == "cpu"
    rng = np.random.default_rng(0)
    trees = dper.init(capacity)
    n = trees.capacity
    trees = dper.set_leaves_jitted(
        trees, jnp.arange(n),
        jnp.asarray(rng.random(n).astype(np.float32) + 1e-3))
    q = k * batch_size
    mass = jnp.asarray(
        (rng.random(q) * float(trees.sum_tree[1])).astype(np.float32))
    descend_scan = jax.jit(dper.descend)

    def _time(fn) -> float:
        out = fn()  # warmup/compile
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        return best * 1e3

    timings: dict = {"scan": round(_time(
        lambda: descend_scan(trees.sum_tree, mass)), 4)}
    if pallas_fits(n):
        try:
            timings["pallas"] = round(_time(
                lambda: descend_pallas(trees.sum_tree, mass, interpret)), 4)
        except Exception as e:  # a kernel that fails to lower loses
            timings["pallas"] = None
            timings["pallas_error"] = f"{type(e).__name__}: {e}"
    else:
        timings["pallas"] = None
        timings["pallas_error"] = (f"tree of {n} slots exceeds the VMEM "
                                   "residency budget")
    timed = {a: v for a, v in timings.items() if isinstance(v, float)}
    best = min(timed, key=timed.get)
    return AutotuneResult(best, "measured fastest descent at "
                          f"[{q}] queries over {n} slots", timings)


def select_sampler(flag: str, *, capacity: int, k: int,
                   batch_size: int) -> AutotuneResult:
    """Resolve a ``--sampler`` flag to a concrete sample-path arm —
    the third arbitration surface (after projection and projection_ce).

    Arms: ``'scan'`` (jnp gather descent on device), ``'pallas'``
    (VMEM-resident descent kernel, ``ops/sampler_descent``) and
    ``'host'`` (the PR-12 ``SampleDealer``, the fallback). Explicit
    flags pass through; ``'auto'`` applies the static policy — non-TPU
    backends fall back to 'host' (the fleet three-arm A/B shows the
    device arm's per-deal XLA dispatch saturating the CPU commit
    thread: deal→grad ~5× the host dealer's, wire→grad p95 pure
    queueing after that — and interpret-mode Pallas would measure the
    emulator, not the kernel), trees past the VMEM budget get 'scan' —
    and otherwise measures scan vs pallas. On TPU 'host' is never
    auto-selected: there the descent fuses into the commit dispatch the
    tree already lives behind, and the host arm would re-introduce the
    sampled-row H2D the device plane exists to delete."""
    if flag != "auto":
        if flag not in SAMPLER_ARMS:
            raise ValueError(f"unknown --sampler arm {flag!r} "
                             f"(want one of {('auto',) + SAMPLER_ARMS})")
        return _record("sampler",
                       AutotuneResult(flag, "explicit --sampler override"))
    import jax

    from d4pg_tpu.ops.sampler_descent import pallas_fits
    from d4pg_tpu.replay.segment_tree import next_pow2

    backend = jax.default_backend()
    key = ("sampler", int(capacity), int(k), int(batch_size), backend)
    if key not in _CACHE:
        if backend != "tpu":
            result = AutotuneResult(
                "host", f"{backend} backend: per-deal XLA dispatch "
                "saturates the commit thread off-accelerator (three-arm "
                "fleet A/B) — the PR-12 host dealer is the honest arm "
                "here; force --sampler scan/pallas to override")
        elif not pallas_fits(next_pow2(capacity)):
            result = AutotuneResult(
                "scan", f"tree of {next_pow2(capacity)} slots exceeds the "
                "Pallas kernel's VMEM residency budget")
        else:
            result = autotune_sampler(capacity, k, batch_size)
        _CACHE[key] = result
    result = _CACHE[key]
    log_key = (key, result.selected)
    if log_key not in _LOGGED:
        _LOGGED.add(log_key)
        timed = (f" timings_ms={result.timings_ms}"
                 if result.timings_ms else "")
        print(f"[autotune] sampler='{result.selected}' "
              f"({result.reason}){timed}", flush=True)
    return _record("sampler", result)
