"""Image augmentation for pixel-observation learners: DrQ random shift.

The single highest-leverage ingredient in published pixel continuous
control at small data budgets (DrQ / RAD): pad the frame by ``pad``
pixels with edge replication, then take a per-sample random crop back to
the original size. Regularizes the conv encoder against the tiny-replay
overfitting that keeps greedy returns at the random-policy level (the
exact failure measured in ``docs/evidence/dmc-pixels/``).

Applied INSIDE the jit'd update (``learner/update.py``) on the sampled
batch — uint8 rows stay uint8 through the shift, so the replay ring and
the H2D path are untouched; both the critic and actor losses see the
same augmented view (the one-sample DrQ variant, M=K=1). The reference
has no pixel path at all (``models.py:15`` is state-only).

Pure ``lax`` ops (pad + per-sample dynamic_slice under ``vmap``), so the
augmentation shards over the batch axis under GSPMD like every other
per-sample op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def random_shift(key: Array, imgs: Array, pad: int = 4) -> Array:
    """Per-sample random shift of a [B, H, W, C] image batch.

    Each sample is edge-padded by ``pad`` on both spatial axes and
    re-cropped to [H, W] at an offset drawn uniformly from
    ``[0, 2*pad]^2`` — i.e. a shift of up to ``pad`` pixels in any
    direction, with edge-replicated fill. dtype-preserving (uint8 in,
    uint8 out).

    Offsets derive from per-sample ``fold_in(key, i)`` keys over a
    global iota rather than one batch-shaped ``randint(key, (B, 2))``:
    a single batch-shaped draw is NOT sharding-layout-invariant under
    GSPMD with the default (non-partitionable) threefry — each data
    shard would generate different bits than the global computation,
    so the {data, model}-mesh update would train on different crops
    than the single-device one (caught by the real-shape equivalence
    gate in tests/test_mesh_pixels.py). The fold_in form is elementwise
    in the batch axis, so partitioning preserves values exactly."""
    if imgs.ndim != 4:
        raise ValueError(f"random_shift expects [B, H, W, C], got "
                         f"{imgs.shape}")
    if pad < 1:
        return imgs
    b, h, w, c = imgs.shape
    padded = jnp.pad(imgs, ((0, 0), (pad, pad), (pad, pad), (0, 0)),
                     mode="edge")
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(jnp.arange(b))

    def crop(img, k):
        off = jax.random.randint(k, (2,), 0, 2 * pad + 1)
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    return jax.vmap(crop)(padded, keys)
