"""Hand-written TPU kernels (Pallas).

The compute path is XLA-compiled JAX; these kernels cover the few ops where
explicit fusion/layout control beats the compiler. Each kernel has a
reference JAX formulation it is tested against, and callers can select the
implementation (``method='einsum' | 'pallas'``) — or leave the config
default ``'auto'``, which runs the startup micro-autotuner
(``ops/autotune.py``) to time the variants on the actual shapes and pick
the winner.
"""

from d4pg_tpu.ops.autotune import (
    AutotuneResult,
    autotune_projection,
    select_projection,
)
from d4pg_tpu.ops.projection import projection_pallas

__all__ = ["AutotuneResult", "autotune_projection", "projection_pallas",
           "select_projection"]
