"""Hand-written TPU kernels (Pallas).

The compute path is XLA-compiled JAX; these kernels cover the few ops where
explicit fusion/layout control beats the compiler. Each kernel has a
reference JAX formulation it is tested against, and callers can select the
implementation (``method='einsum' | 'pallas'``).
"""

from d4pg_tpu.ops.projection import projection_pallas

__all__ = ["projection_pallas"]
