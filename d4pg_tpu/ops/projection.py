"""Pallas TPU kernel for the categorical Bellman projection.

Fuses the whole projection — Bellman map, clip, interpolation-weight
construction, and the contraction over source atoms — into one VMEM-resident
kernel, so the [B, A, A] weight tensor never exists outside on-chip memory.
The reference computes this on the HOST with a per-atom Python loop and
numpy scatter-adds (``ddpg.py:142-185``); the JAX baseline is the einsum
formulation in ``core/distribution.py`` (one [B, A, A] intermediate for XLA
to schedule). Semantics are identical to ``categorical_projection``:

    tz   = clip(r + disc * z, v_min, v_max)
    b    = (tz - v_min) / delta
    out_j = sum_i p_i * clip(1 - |b_i - j|, 0, 1)

Batch is tiled over a 1-D grid; atoms stay whole per tile (A = 51 pads to
one lane tile). Runs under ``interpret=True`` on CPU for tests.

Measured on a v5e chip (B=256/4096, A=51): bitwise-identical to the einsum
path, but ~1.2-1.7x SLOWER — at this op size XLA's fused einsum already
keeps everything on-chip and the pallas_call dispatch dominates. The
einsum formulation therefore stays the default in the learner. The
promised follow-up fusion EXISTS: ``ops/projection_ce.py`` folds the
projection into the cross-entropy loss reduction (forward + custom VJP,
``--projection pallas_ce``), removing the proj [B, A] HBM round trip this
standalone kernel still pays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from d4pg_tpu.core.distribution import CategoricalSupport

_TILE_B = 64


def _projection_kernel(p_ref, r_ref, d_ref, out_ref, *, v_min, v_max, n_atoms):
    delta = (v_max - v_min) / (n_atoms - 1)
    p = p_ref[:]  # [TB, A]
    r = r_ref[:]  # [TB, 1]
    d = d_ref[:]  # [TB, 1]
    # TPU iota is integer-only; cast after.
    atoms = v_min + delta * jax.lax.broadcasted_iota(
        jnp.int32, (1, n_atoms), 1
    ).astype(jnp.float32)  # [1, A]
    tz = jnp.clip(r + d * atoms, v_min, v_max)  # [TB, A]
    b = (tz - v_min) / delta  # [TB, A] fractional source positions
    j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, n_atoms), 2).astype(
        jnp.float32
    )  # [1,1,A]
    w = jnp.clip(1.0 - jnp.abs(b[:, :, None] - j), 0.0, 1.0)  # [TB, A, A]
    out_ref[:] = jnp.sum(p[:, :, None] * w, axis=1)


@functools.partial(jax.jit, static_argnums=(0, 4))
def projection_pallas(
    support: CategoricalSupport,
    target_probs: Array,
    rewards: Array,
    discounts: Array,
    interpret: bool = False,
) -> Array:
    """Drop-in Pallas variant of ``core.distribution.categorical_projection``.

    target_probs: [B, A]; rewards/discounts: [B]. B is padded up to the
    batch tile internally; [B, A] comes back exact.
    """
    n = target_probs.shape[0]
    a = support.n_atoms
    pad = (-n) % _TILE_B
    p = jnp.pad(target_probs.astype(jnp.float32), ((0, pad), (0, 0)))
    r = jnp.pad(rewards.astype(jnp.float32), (0, pad))[:, None]
    d = jnp.pad(discounts.astype(jnp.float32), (0, pad))[:, None]
    total = n + pad

    kernel = functools.partial(
        _projection_kernel,
        v_min=float(support.v_min),
        v_max=float(support.v_max),
        n_atoms=a,
    )
    out = pl.pallas_call(
        kernel,
        grid=(total // _TILE_B,),
        in_specs=[
            pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((total, a), jnp.float32),
        interpret=interpret,
    )(p, r, d)
    return out[:n]
