"""Pallas TPU kernel for the PER stratified prefix-sum descent.

The dealt plane's sample step (``replay/device_sampler.py``) is a batch
of inverse-CDF descents through the device sum tree — a memory-bound
gather loop with log2(capacity) dependent rounds. The baseline arm keeps
it as plain ``jnp`` gathers (``device_per.descend``), which XLA lowers to
one dynamic-gather per level; this kernel is the Pallas arm of the
``--sampler`` autotune surface (``ops/autotune.select_sampler``): the
whole sum tree is pinned in VMEM for the duration of a query tile, so
the log2(N) rounds never re-touch HBM.

TPU VMEM has no vectorized dynamic gather, so each level's
``left_sum = tree[2 * node]`` is computed as a chunked ONE-HOT
contraction over the tree: for every tree chunk, ``where(j == left,
tree_j, 0)`` summed over the chunk. Exactly one summand is nonzero and
float32 ``x + 0.0 == x`` is exact, so the result is BITWISE the gathered
value — the kernel and the ``jnp`` descent arm agree bit-for-bit, which
is what lets the seeded-stream oracle pin either arm against the host
dealer (tests/test_devsample.py).

Fit bound: the tree block is ``2 * capacity`` float32 in VMEM (~16 MB
per core), so capacity ≲ 1.5M slots — above that the kernel refuses and
the autotuner falls back to the ``jnp`` arm. Runs under
``interpret=True`` on CPU for tests; on CPU the autotuner never selects
it (interpret mode measures the emulator, not a kernel — same policy as
``ops/projection.py``, which is also honest about losing its race: the
one-hot contraction does O(capacity) work per level against the
gather's O(1), so this arm only wins where VMEM residency beats HBM
gather latency, an empirical fact ``--sampler auto`` measures on chip).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

_TILE_Q = 128  # queries per grid step
_CHUNK = 512  # tree nodes per one-hot contraction round

# VMEM budget for the resident tree block (bytes); past this the caller
# must use the jnp gather arm (pallas_fits / select_sampler gate it).
_VMEM_TREE_BYTES = 12 * 1024 * 1024


def pallas_fits(capacity: int) -> bool:
    """Whether the [2 * capacity] float32 tree block fits the VMEM budget."""
    return 2 * int(capacity) * 4 <= _VMEM_TREE_BYTES


def _descent_kernel(tree_ref, mass_ref, idx_ref, *, levels, cap):
    p = mass_ref[:]  # [TQ]
    node = jnp.ones(p.shape, jnp.int32)
    tree = tree_ref[:]  # [2 * cap], VMEM-resident across all levels
    for _ in range(levels):
        left = node * 2
        # one-hot gather of tree[left], chunked so the [TQ, chunk]
        # compare/select temporary stays small; only the hit chunk
        # contributes a nonzero summand (bitwise-exact, see module doc)
        left_sum = jnp.zeros(p.shape, jnp.float32)
        for c0 in range(0, 2 * cap, _CHUNK):
            c = min(_CHUNK, 2 * cap - c0)
            j = c0 + jax.lax.broadcasted_iota(jnp.int32, (p.shape[0], c), 1)
            hit = j == left[:, None]
            left_sum = left_sum + jnp.sum(
                jnp.where(hit, tree[c0:c0 + c][None, :], 0.0), axis=1)
        # the shared tie rule (device_per.descend): mass >= left sum
        # descends RIGHT — left is even, so ``left + 1`` is ``left | 1``
        go_right = p >= left_sum
        p = jnp.where(go_right, p - left_sum, p)
        node = jnp.where(go_right, left + 1, left)
    idx_ref[:] = node - cap


@functools.partial(jax.jit, static_argnums=(2,))
def descend_pallas(sum_tree: Array, mass: Array,
                   interpret: bool = False) -> Array:
    """Drop-in Pallas variant of ``device_per.descend`` (flat queries).

    sum_tree: [2 * capacity] float32; mass: [Q] float32 prefix masses.
    Q pads up to the query tile internally; [Q] int32 slots come back
    exact and bitwise-equal to the jnp descent arm.
    """
    cap = sum_tree.shape[0] // 2
    levels = int(math.log2(cap))  # jaxlint: disable=host-sync-in-jit (shape: static under jit)
    q = mass.shape[0]
    pad = (-q) % _TILE_Q
    m = jnp.pad(mass.astype(jnp.float32), (0, pad))
    total_q = q + pad

    kernel = functools.partial(_descent_kernel, levels=levels, cap=cap)
    idx = pl.pallas_call(
        kernel,
        grid=(total_q // _TILE_Q,),
        in_specs=[
            pl.BlockSpec((2 * cap,), lambda i: (0,)),
            pl.BlockSpec((_TILE_Q,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((_TILE_Q,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((total_q,), jnp.int32),
        interpret=interpret,
    )(sum_tree, m)
    return idx[:q]
