"""Pallas TPU kernel fusing the categorical Bellman projection INTO the
cross-entropy loss reduction — the follow-through on ``ops/projection.py``'s
"template for future fusions" note (VERDICT r3 #8).

The standalone projection kernel loses to XLA's fused einsum because it
still writes the projected distribution ``proj`` [B, A] back to HBM only
for the loss to immediately re-read it. Fusing the reduction removes that
round trip in BOTH directions:

    forward:  td_b = -sum_j proj_bj * log(q_bj + eps)
              proj_bj = sum_i p_bi * clip(1 - |b_bi - j|, 0, 1)
    backward: dq = -g * proj / (q + eps)        (recomputed in VMEM)
              dp_i = -g * sum_j w_ij * log(q_j + eps)

so the [TB, A, A] interpolation weights AND ``proj`` exist only in VMEM,
per batch tile, in both passes (rematerialized in the backward kernel —
the standard Pallas flash-attention trade: recompute on-chip instead of
storing off-chip).

Semantics match ``core.losses.cross_entropy_per_sample(
categorical_projection(...), q)`` exactly, INCLUDING the gradient
convention of the learner (``learner/update.py`` stop-gradients the
projection): the returned VJP treats the projected target as CONSTANT —
zero cotangents for target_probs/rewards/discounts. That is the reference
semantics (``ddpg.py:214-217``: the target distribution is a detached
numpy array) and the only way this kernel is used; a caller wanting
gradients THROUGH the projection must use the einsum formulation.

Reference scope: ``ddpg.py:142-185`` (host projection loop) +
``ddpg.py:217`` (cross-entropy) — here a single fused device kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl

from d4pg_tpu.core.distribution import CategoricalSupport

_TILE_B = 64
_LOG_EPS = 1e-10  # matches core/losses.py and the reference (ddpg.py:217)


def _weights_tile(r, d, *, v_min, v_max, n_atoms):
    """Interpolation weights w [TB, A, A] for one batch tile (VMEM-only)."""
    delta = (v_max - v_min) / (n_atoms - 1)
    atoms = v_min + delta * jax.lax.broadcasted_iota(
        jnp.int32, (1, n_atoms), 1
    ).astype(jnp.float32)  # [1, A]
    tz = jnp.clip(r + d * atoms, v_min, v_max)  # [TB, A]
    b = (tz - v_min) / delta
    j = jax.lax.broadcasted_iota(jnp.int32, (1, 1, n_atoms), 2).astype(
        jnp.float32
    )
    return jnp.clip(1.0 - jnp.abs(b[:, :, None] - j), 0.0, 1.0)


def _fwd_kernel(p_ref, r_ref, d_ref, q_ref, td_ref, *, v_min, v_max, n_atoms):
    w = _weights_tile(r_ref[:], d_ref[:], v_min=v_min, v_max=v_max,
                      n_atoms=n_atoms)
    proj = jnp.sum(p_ref[:][:, :, None] * w, axis=1)  # [TB, A]
    logq = jnp.log(q_ref[:] + _LOG_EPS)
    td_ref[:] = -jnp.sum(proj * logq, axis=-1, keepdims=True)  # [TB, 1]


def _bwd_kernel(p_ref, r_ref, d_ref, q_ref, g_ref, dq_ref, *,
                v_min, v_max, n_atoms):
    w = _weights_tile(r_ref[:], d_ref[:], v_min=v_min, v_max=v_max,
                      n_atoms=n_atoms)
    proj = jnp.sum(p_ref[:][:, :, None] * w, axis=1)
    dq_ref[:] = -g_ref[:] * proj / (q_ref[:] + _LOG_EPS)


def _pad_operands(support, target_probs, rewards, discounts, pred_probs):
    n = target_probs.shape[0]
    pad = (-n) % _TILE_B
    p = jnp.pad(target_probs.astype(jnp.float32), ((0, pad), (0, 0)))
    r = jnp.pad(rewards.astype(jnp.float32), (0, pad))[:, None]
    d = jnp.pad(discounts.astype(jnp.float32), (0, pad))[:, None]
    q = jnp.pad(pred_probs.astype(jnp.float32), ((0, pad), (0, 0)),
                constant_values=1.0)  # log(1+eps)=~0 on pad rows
    return p, r, d, q, n, n + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 5))
def projection_ce_pallas(
    support: CategoricalSupport,
    target_probs: Array,
    rewards: Array,
    discounts: Array,
    pred_probs: Array,
    interpret: bool = False,
) -> Array:
    """Per-sample distributional TD error (cross-entropy vs the projected
    Bellman target), projection and reduction fused in one kernel.

    target_probs/pred_probs: [B, A]; rewards/discounts: [B] -> td [B].
    Gradients flow to ``pred_probs`` ONLY (see module docstring).
    """
    td, _ = _fwd(support, target_probs, rewards, discounts, pred_probs,
                 interpret)
    return td


def _fwd(support, target_probs, rewards, discounts, pred_probs, interpret):
    a = support.n_atoms
    p, r, d, q, n, total = _pad_operands(
        support, target_probs, rewards, discounts, pred_probs)
    # `support` is a nondiff_argnums operand: a plain Python NamedTuple at
    # trace time, so float() here is static config math, not a device sync
    kernel = functools.partial(
        _fwd_kernel, v_min=float(support.v_min), v_max=float(support.v_max),  # jaxlint: disable=host-sync-in-jit
        n_atoms=a)
    td = pl.pallas_call(
        kernel,
        grid=(total // _TILE_B,),
        in_specs=[
            pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((total, 1), jnp.float32),
        interpret=interpret,
    )(p, r, d, q)
    return td[:n, 0], (target_probs, rewards, discounts, pred_probs)


def _bwd(support, interpret, res, g):
    target_probs, rewards, discounts, pred_probs = res
    a = support.n_atoms
    p, r, d, q, n, total = _pad_operands(
        support, target_probs, rewards, discounts, pred_probs)
    gpad = jnp.pad(g.astype(jnp.float32), (0, total - n))[:, None]
    # `support` is static at trace time (see _fwd): host config math
    kernel = functools.partial(
        _bwd_kernel, v_min=float(support.v_min), v_max=float(support.v_max),  # jaxlint: disable=host-sync-in-jit
        n_atoms=a)
    dq = pl.pallas_call(
        kernel,
        grid=(total // _TILE_B,),
        in_specs=[
            pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
            pl.BlockSpec((_TILE_B, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE_B, a), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((total, a), jnp.float32),
        interpret=interpret,
    )(p, r, d, q, gpad)
    # projected target is CONSTANT by contract (reference: detached numpy
    # target, ddpg.py:214); cotangents for it and the Bellman operands are
    # zero, matching stop_gradient(categorical_projection(...)) exactly
    zeros_p = jnp.zeros_like(target_probs)
    zeros_r = jnp.zeros_like(rewards)
    zeros_d = jnp.zeros_like(discounts)
    return zeros_p, zeros_r, zeros_d, dq[:n].astype(pred_probs.dtype)


projection_ce_pallas.defvjp(_fwd, _bwd)
