"""Flax (Linen) network definitions: actor, distributional critics, encoders."""

from d4pg_tpu.models.init import fanin_init
from d4pg_tpu.models.actor import Actor
from d4pg_tpu.models.critic import (
    CategoricalCritic,
    MixtureOfGaussianCritic,
    MoGParams,
)
from d4pg_tpu.models.encoder import PixelEncoder, PixelActor, PixelCategoricalCritic

__all__ = [
    "fanin_init",
    "Actor",
    "CategoricalCritic",
    "MixtureOfGaussianCritic",
    "MoGParams",
    "PixelEncoder",
    "PixelActor",
    "PixelCategoricalCritic",
]
