"""Weight initializers.

Parity: the reference's ``fanin_init`` (``models.py:6-9``) draws
N(0, 1/sqrt(fan_in)) for hidden layers, and the output layers use small
normal draws — N(0, 3e-3) for the actor head (``models.py:30``) and
N(0, 3e-4) for the critic head (``models.py:73``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def fanin_init(dtype=jnp.float32):
    """N(0, 1/sqrt(fan_in)) initializer for [fan_in, fan_out] kernels.

    Note the reference's std: torch ``Tensor.normal_(0, v)`` takes a *std* of
    ``1/sqrt(fanin)`` (``models.py:8-9``) — i.e. variance 1/fanin — which is
    what we reproduce here.
    """

    def init(key, shape, dtype=dtype):
        fan_in = shape[0]
        return (1.0 / jnp.sqrt(jnp.asarray(fan_in, dtype))) * jax.random.normal(
            key, shape, dtype
        )

    return init


def scaled_normal(std: float, dtype=jnp.float32):
    """N(0, std) initializer for output heads (``models.py:30, 73``)."""

    def init(key, shape, dtype=dtype):
        return std * jax.random.normal(key, shape, dtype)

    return init
