"""Distributional critic networks Z(s, a).

Parity: the reference critic (``models.py:51-88``): state through a 256-wide
first layer, the action concatenated at the *second* layer (``models.py:80``,
per the DDPG paper), two more 256-wide ReLU layers, then a distribution head:

  - ``categorical``: a ``n_atoms``-way softmax over fixed support bins
    (``models.py:61-62, 82-83``), fan-in init on hidden kernels and
    N(0, 3e-4) on the head (``models.py:73``).
  - ``mixture_of_gaussian``: an empty TODO stub in the reference
    (``models.py:63-65, 85-87``; ``ddpg.py:48-50, 224-226``). Implemented
    for real here: the head emits component logits, means and softplus stds
    of a K-component Gaussian mixture over returns.

The categorical critic returns *probabilities* (post-softmax) to match the
reference's forward (``models.py:82``); ``logits`` are also exposed since the
cross-entropy loss is more stable computed from log-softmax.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import flax.linen as nn
import jax.numpy as jnp

from d4pg_tpu.models.init import fanin_init, scaled_normal


class _CriticTorso(nn.Module):
    """Shared state/action MLP torso: s -> 256 -> [.,a] -> 256 -> 256."""

    hidden: Sequence[int] = (256, 256, 256)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(self.dtype)
        x = nn.relu(
            nn.Dense(self.hidden[0], kernel_init=fanin_init(), dtype=self.dtype, name="fc1")(x)
        )
        x = jnp.concatenate([x, action.astype(self.dtype)], axis=-1)
        for i, width in enumerate(self.hidden[1:]):
            x = nn.relu(
                nn.Dense(width, kernel_init=fanin_init(), dtype=self.dtype, name=f"fc{i + 2}")(x)
            )
        return x


class CategoricalCritic(nn.Module):
    """Z(s, a) as a categorical distribution over ``n_atoms`` return bins."""

    n_atoms: int = 51
    hidden: Sequence[int] = (256, 256, 256)
    final_init_std: float = 3e-4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, obs: jnp.ndarray, action: jnp.ndarray, return_logits: bool = False
    ) -> jnp.ndarray:
        x = _CriticTorso(self.hidden, self.dtype, name="torso")(obs, action)
        logits = nn.Dense(
            self.n_atoms,
            kernel_init=scaled_normal(self.final_init_std),
            dtype=self.dtype,
            name="head",
        )(x).astype(jnp.float32)
        return logits if return_logits else nn.softmax(logits, axis=-1)


class MoGParams(NamedTuple):
    """Parameters of a K-component Gaussian mixture over returns."""

    log_weights: jnp.ndarray  # [..., K] log mixture weights (log-softmaxed)
    means: jnp.ndarray  # [..., K]
    stds: jnp.ndarray  # [..., K] (positive)


class MixtureOfGaussianCritic(nn.Module):
    """Z(s, a) as a mixture of Gaussians — the reference's unimplemented
    second distribution family, built for real."""

    n_components: int = 5
    hidden: Sequence[int] = (256, 256, 256)
    final_init_std: float = 3e-4
    min_std: float = 1e-3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jnp.ndarray, action: jnp.ndarray) -> MoGParams:
        x = _CriticTorso(self.hidden, self.dtype, name="torso")(obs, action)
        head = nn.Dense(
            3 * self.n_components,
            kernel_init=scaled_normal(self.final_init_std),
            dtype=self.dtype,
            name="head",
        )(x).astype(jnp.float32)
        logits, means, raw_std = jnp.split(head, 3, axis=-1)
        return MoGParams(
            log_weights=nn.log_softmax(logits, axis=-1),
            means=means,
            stds=nn.softplus(raw_std) + self.min_std,
        )
