"""Deterministic policy network pi(s) -> a in (-1, 1)^act_dim.

Parity: the reference actor (``models.py:15-41``): MLP with hidden widths
256-256-256, tanh-bounded output, fan-in init on hidden kernels, N(0, 3e-3)
on the output kernel. The reference forgot the activation between its second
and third hidden layers (``models.py:36-37`` — two consecutive Linears);
per SURVEY.md §7 we do NOT reproduce that quirk: every hidden layer here is
followed by ReLU.

TPU notes: hidden widths are configurable (default 256) and should be kept
multiples of 128 so XLA tiles the matmuls onto the MXU cleanly; compute dtype
is configurable for bfloat16 inference on actors.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from d4pg_tpu.models.init import fanin_init, scaled_normal


class Actor(nn.Module):
    act_dim: int
    hidden: Sequence[int] = (256, 256, 256)
    final_init_std: float = 3e-3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jnp.ndarray) -> jnp.ndarray:
        x = obs.astype(self.dtype)
        for i, width in enumerate(self.hidden):
            x = nn.Dense(
                width, kernel_init=fanin_init(), dtype=self.dtype, name=f"fc{i + 1}"
            )(x)
            x = nn.relu(x)
        x = nn.Dense(
            self.act_dim,
            kernel_init=scaled_normal(self.final_init_std),
            dtype=self.dtype,
            name="out",
        )(x)
        return jnp.tanh(x).astype(jnp.float32)
