"""Convolutional pixel encoder and pixel actor/critic wrappers.

The reference has no pixel path, but BASELINE.md config #4 (DM-Control
cheetah-run from pixels, conv encoder) requires one. This is the standard
continuous-control conv stack (SAC-AE/DrQ-style): four 3x3 conv layers with
stride 2 then 1, ReLU, flattened through a linear projection + LayerNorm +
tanh into a compact latent that feeds the MLP actor/critic.

TPU notes: convs run on the MXU via XLA's conv-as-matmul lowering; NHWC
layout; channel count 32 keeps im2col tiles well-shaped. The encoder latent
is the natural place to introduce a ``model`` mesh axis if the trunk is ever
scaled up (SURVEY.md §2 mesh mandate).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from d4pg_tpu.models.actor import Actor
from d4pg_tpu.models.critic import CategoricalCritic


class PixelEncoder(nn.Module):
    latent_dim: int = 50
    channels: Sequence[int] = (32, 32, 32, 32)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, pixels: jnp.ndarray) -> jnp.ndarray:
        # pixels: [..., H, W, C] uint8 or float
        x = pixels.astype(self.dtype) / 255.0
        for i, ch in enumerate(self.channels):
            stride = 2 if i == 0 else 1
            x = nn.Conv(
                ch, (3, 3), strides=(stride, stride), dtype=self.dtype, name=f"conv{i + 1}"
            )(x)
            x = nn.relu(x)
        x = x.reshape(x.shape[: -3] + (-1,))
        x = nn.Dense(self.latent_dim, dtype=self.dtype, name="proj")(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln")(x)
        return jnp.tanh(x).astype(jnp.float32)


class PixelActor(nn.Module):
    """Encoder + MLP actor for pixel observations.

    ``detach_encoder`` stops the gradient at the latent (SAC-AE/DrQ: the
    policy loss must not train the conv encoder — ``--share_encoder``
    ties this module's encoder subtree to the critic's, which the critic
    loss trains). The param tree is identical either way."""

    act_dim: int
    latent_dim: int = 50
    channels: Sequence[int] = (32, 32, 32, 32)
    hidden: Sequence[int] = (256, 256, 256)
    dtype: jnp.dtype = jnp.float32
    detach_encoder: bool = False

    @nn.compact
    def __call__(self, pixels: jnp.ndarray) -> jnp.ndarray:
        z = PixelEncoder(self.latent_dim, tuple(self.channels),
                         dtype=self.dtype, name="encoder")(pixels)
        if self.detach_encoder:
            z = jax.lax.stop_gradient(z)
        return Actor(self.act_dim, self.hidden, dtype=self.dtype, name="actor")(z)


class PixelCategoricalCritic(nn.Module):
    """Encoder + categorical critic for pixel observations."""

    n_atoms: int = 51
    latent_dim: int = 50
    channels: Sequence[int] = (32, 32, 32, 32)
    hidden: Sequence[int] = (256, 256, 256)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self, pixels: jnp.ndarray, action: jnp.ndarray, return_logits: bool = False
    ) -> jnp.ndarray:
        z = PixelEncoder(self.latent_dim, tuple(self.channels),
                         dtype=self.dtype, name="encoder")(pixels)
        return CategoricalCritic(self.n_atoms, self.hidden, dtype=self.dtype,
                                 name="critic")(z, action, return_logits)
