"""IO: structured metrics bus -> TensorBoard, and Orbax checkpoint/resume.

Parity: the reference's three overlapping logging mechanisms (TensorBoard
scalars ``main.py:59-66, 352-353``; print telemetry ``main.py:349-350``;
pickle train_logs, commented out, ``main.py:355-364``) unified behind one
bus; and its save-only ``torch.save`` checkpointing (``main.py:367-368``)
replaced by full-train-state Orbax checkpoints WITH a resume path
(SURVEY.md §5: the reference has "no load path, no resume").
"""

from d4pg_tpu.io.metrics import CsvLogger, MetricsBus, TensorBoardSink
from d4pg_tpu.io.checkpoint import CheckpointManager

__all__ = [
    "MetricsBus",
    "TensorBoardSink",
    "CsvLogger",
    "CheckpointManager",
]
