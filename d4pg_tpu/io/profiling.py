"""Profiling: XLA trace capture + step-rate tracking.

SURVEY.md §5: the reference's only timing is wall-clock deltas into a dict
that is never persisted (``main.py:250, 359``). Here: ``jax.profiler``
traces on demand (viewable in TensorBoard/Perfetto) and an EWMA'd
grad-steps/sec meter — the north-star metric (BASELINE.md) — cheap enough
to leave on.
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def xla_trace(log_dir: str | None):
    """Capture an XLA profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """EWMA steps/sec over explicitly bracketed update spans.

    ``start()`` ... ``stop(n)`` measures ONLY the bracketed region, so the
    reported rate is pure update throughput — not diluted by eval/collect/
    checkpoint time happening between brackets.
    """

    def __init__(self, alpha: float = 0.9):
        self._alpha = alpha
        self._t0: float | None = None
        self.rate: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int) -> float | None:
        if self._t0 is None:
            return self.rate
        dt = time.perf_counter() - self._t0
        self._t0 = None
        if dt > 0 and n_steps > 0:
            inst = n_steps / dt
            self.rate = (
                inst if self.rate is None
                else self._alpha * self.rate + (1 - self._alpha) * inst
            )
        return self.rate
