"""Profiling: XLA trace capture, step-rate tracking, perf sentinels.

SURVEY.md §5: the reference's only timing is wall-clock deltas into a dict
that is never persisted (``main.py:250, 359``). Here: ``jax.profiler``
traces on demand (viewable in TensorBoard/Perfetto) and an EWMA'd
grad-steps/sec meter — the north-star metric (BASELINE.md) — cheap enough
to leave on.

The sentinels are the runtime complement of the static ``jaxlint``
pass (``d4pg_tpu/lint``): the linter catches hazards it can see in the
AST; the sentinels catch what it can't — a hot loop that recompiles in
steady state (``RecompileSentinel``, wired into ``bench.py`` and the
learner tests), round-trips data between host and device per step
(``TransferSentinel``), or compiles to a program that silently reshards
a tree between layouts (``ReshardSentinel``, the dynamic twin of the
``sharding-spec-drift`` lint family the way RecompileSentinel twins
``recompile-hazard``).
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def xla_trace(log_dir: str | None):
    """Capture an XLA profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """EWMA steps/sec over explicitly bracketed update spans.

    ``start()`` ... ``stop(n)`` measures ONLY the bracketed region, so the
    reported rate is pure update throughput — not diluted by eval/collect/
    checkpoint time happening between brackets.
    """

    def __init__(self, alpha: float = 0.9):
        self._alpha = alpha
        self._t0: float | None = None
        self.rate: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int) -> float | None:
        if self._t0 is None:
            return self.rate
        dt = time.perf_counter() - self._t0
        self._t0 = None
        if dt > 0 and n_steps > 0:
            inst = n_steps / dt
            self.rate = (
                inst if self.rate is None
                else self._alpha * self.rate + (1 - self._alpha) * inst
            )
        return self.rate


class RecompileError(AssertionError):
    """A region that must be compile-free triggered XLA compilation."""


class RecompileSentinel:
    """Counts XLA backend compilations inside the bracketed region.

    Zero steady-state recompilation is a core throughput invariant of this
    stack (every surprise compile stalls the learner for seconds): after
    warmup, wrap the hot loop and call :meth:`assert_clean`.

    Detection uses ``jax.monitoring``'s event stream — every backend
    compile records a ``/jax/core/compile/backend_compile_duration``
    event, and cache hits record nothing — so ANY jitted callable
    (including scans/shard_maps nested in it) is observed without
    instrumenting the callable itself.

        with RecompileSentinel() as sentinel:
            for _ in range(n):
                state, metrics = update(state, batch)
        sentinel.assert_clean()
    """

    _EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        self.compilations = 0
        self._active = False

    def _on_event(self, event: str, duration: float, **_kw) -> None:
        if self._active and event == self._EVENT:
            self.compilations += 1

    def __enter__(self) -> "RecompileSentinel":
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self._on_event)
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        self._active = False
        from jax._src import monitoring

        try:
            monitoring._unregister_event_duration_listener_by_callback(
                self._on_event)
        except (AttributeError, ValueError):
            pass  # older jax: listener stays registered but inert (_active)
        # publish the bracketed count into the unified registry: bench
        # artifacts and the fleet report read the same ledger instead of
        # each keeping a private copy of "were there recompiles"
        from d4pg_tpu.obs.registry import REGISTRY

        REGISTRY.counter("profiling.recompiles").inc(self.compilations)

    def assert_clean(self, what: str = "steady-state region") -> None:
        if self.compilations:
            raise RecompileError(
                f"{what} triggered {self.compilations} XLA compilation(s) "
                "after warmup — a static-shape or weak-type mismatch is "
                "defeating the jit cache")


class ReshardError(AssertionError):
    """A path that must keep one layout compiled to resharding copies."""


class ReshardSentinel:
    """Counts resharding collectives in a jitted callable's compiled HLO.

    The static ``sharding-spec-drift`` family flags trees that the SOURCE
    places under two different partition factories; this sentinel is its
    dynamic twin — it reads what XLA actually compiled.  A clean fused
    learner path contains gradient ``all-reduce``s (expected: that IS
    data parallelism) but no ``all-to-all`` or ``collective-permute``:
    those only appear when GSPMD had to move a tree between layouts
    mid-program, i.e. an implicit reshard paying a full device-to-device
    copy every step.

        sentinel = ReshardSentinel()
        sentinel.inspect(fn, *warmup_args)   # fn.lower(...).compile()
        sentinel.assert_clean("fused learner path")
        assert sentinel.steady_state_reshards == 0
    """

    # Ops that MOVE data between layouts.  all-reduce/all-gather are
    # deliberately absent: gradient reduction and merge broadcasts are
    # the collectives the program is SUPPOSED to contain.
    _RESHARD_OPS = ("all-to-all", "collective-permute")

    def __init__(self):
        self.reshards = 0
        self.ops: dict[str, int] = {}

    @property
    def steady_state_reshards(self) -> int:
        return self.reshards

    def inspect(self, fn, *args, **kwargs) -> int:
        """Lower+compile ``fn`` for ``args`` and scan the HLO text.
        ``lower`` never executes (and never consumes donated buffers), so
        this is safe to run against live training state."""
        lowered = fn.lower(*args, **kwargs)
        try:
            text = lowered.compile().as_text()
        except Exception:  # backends without compiled-text introspection
            text = lowered.as_text()
        return self.inspect_text(text)

    def inspect_text(self, hlo_text: str) -> int:
        found = 0
        for op in self._RESHARD_OPS:
            n = hlo_text.count(op)
            if n:
                self.ops[op] = self.ops.get(op, 0) + n
                found += n
        self.reshards += found
        # same unified ledger as the other sentinels: bench artifacts and
        # the fleet report read one counter instead of private copies
        from d4pg_tpu.obs.registry import REGISTRY

        REGISTRY.counter("profiling.reshards").inc(found)
        return found

    def assert_clean(self, what: str = "steady-state path") -> None:
        if self.reshards:
            detail = ", ".join(f"{op} x{n}"
                               for op, n in sorted(self.ops.items()))
            raise ReshardError(
                f"{what} compiled to {self.reshards} resharding "
                f"collective(s) ({detail}) — a tree is produced under one "
                f"sharding spec and consumed under another; route both "
                f"through the same parallel/partition.py factory")


class TransferSentinel:
    """Counts explicit host<->device transfers in the bracketed region.

    Patches ``jax.device_put`` / ``jax.device_get`` for the duration of
    the context and tallies calls (``h2d`` / ``d2h``). Implicit transfers
    (``np.asarray`` on a device array, scalar coercion) bypass those entry
    points; pass ``guard="disallow"`` to make jax raise on them instead —
    note the guard is inert on the CPU backend, where host and device
    memory are one and the same.

        with TransferSentinel() as t:
            run_fused_chunk()
        assert t.total == 0
    """

    def __init__(self, guard: str | None = None):
        self.h2d = 0
        self.d2h = 0
        self._guard = guard
        self._stack: contextlib.ExitStack | None = None

    @property
    def total(self) -> int:
        return self.h2d + self.d2h

    def __enter__(self) -> "TransferSentinel":
        import jax

        self._orig_put, self._orig_get = jax.device_put, jax.device_get

        def counted_put(*a, **kw):
            self.h2d += 1
            return self._orig_put(*a, **kw)

        def counted_get(*a, **kw):
            self.d2h += 1
            return self._orig_get(*a, **kw)

        jax.device_put, jax.device_get = counted_put, counted_get
        self._stack = contextlib.ExitStack()
        if self._guard:
            self._stack.enter_context(jax.transfer_guard(self._guard))
        return self

    def __exit__(self, *exc) -> None:
        import jax

        jax.device_put, jax.device_get = self._orig_put, self._orig_get
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        from d4pg_tpu.obs.registry import REGISTRY

        REGISTRY.counter("profiling.explicit_h2d").inc(self.h2d)
        REGISTRY.counter("profiling.explicit_d2h").inc(self.d2h)
