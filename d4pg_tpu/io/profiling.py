"""Profiling: XLA trace capture, step-rate tracking, perf sentinels.

SURVEY.md §5: the reference's only timing is wall-clock deltas into a dict
that is never persisted (``main.py:250, 359``). Here: ``jax.profiler``
traces on demand (viewable in TensorBoard/Perfetto) and an EWMA'd
grad-steps/sec meter — the north-star metric (BASELINE.md) — cheap enough
to leave on.

The two sentinels are the runtime complement of the static ``jaxlint``
pass (``d4pg_tpu/lint``): the linter catches hazards it can see in the
AST; the sentinels catch what it can't — a hot loop that recompiles in
steady state (``RecompileSentinel``, wired into ``bench.py`` and the
learner tests) or round-trips data between host and device per step
(``TransferSentinel``).
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def xla_trace(log_dir: str | None):
    """Capture an XLA profiler trace into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """EWMA steps/sec over explicitly bracketed update spans.

    ``start()`` ... ``stop(n)`` measures ONLY the bracketed region, so the
    reported rate is pure update throughput — not diluted by eval/collect/
    checkpoint time happening between brackets.
    """

    def __init__(self, alpha: float = 0.9):
        self._alpha = alpha
        self._t0: float | None = None
        self.rate: float | None = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, n_steps: int) -> float | None:
        if self._t0 is None:
            return self.rate
        dt = time.perf_counter() - self._t0
        self._t0 = None
        if dt > 0 and n_steps > 0:
            inst = n_steps / dt
            self.rate = (
                inst if self.rate is None
                else self._alpha * self.rate + (1 - self._alpha) * inst
            )
        return self.rate


class RecompileError(AssertionError):
    """A region that must be compile-free triggered XLA compilation."""


class RecompileSentinel:
    """Counts XLA backend compilations inside the bracketed region.

    Zero steady-state recompilation is a core throughput invariant of this
    stack (every surprise compile stalls the learner for seconds): after
    warmup, wrap the hot loop and call :meth:`assert_clean`.

    Detection uses ``jax.monitoring``'s event stream — every backend
    compile records a ``/jax/core/compile/backend_compile_duration``
    event, and cache hits record nothing — so ANY jitted callable
    (including scans/shard_maps nested in it) is observed without
    instrumenting the callable itself.

        with RecompileSentinel() as sentinel:
            for _ in range(n):
                state, metrics = update(state, batch)
        sentinel.assert_clean()
    """

    _EVENT = "/jax/core/compile/backend_compile_duration"

    def __init__(self):
        self.compilations = 0
        self._active = False

    def _on_event(self, event: str, duration: float, **_kw) -> None:
        if self._active and event == self._EVENT:
            self.compilations += 1

    def __enter__(self) -> "RecompileSentinel":
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self._on_event)
        self._active = True
        return self

    def __exit__(self, *exc) -> None:
        self._active = False
        from jax._src import monitoring

        try:
            monitoring._unregister_event_duration_listener_by_callback(
                self._on_event)
        except (AttributeError, ValueError):
            pass  # older jax: listener stays registered but inert (_active)
        # publish the bracketed count into the unified registry: bench
        # artifacts and the fleet report read the same ledger instead of
        # each keeping a private copy of "were there recompiles"
        from d4pg_tpu.obs.registry import REGISTRY

        REGISTRY.counter("profiling.recompiles").inc(self.compilations)

    def assert_clean(self, what: str = "steady-state region") -> None:
        if self.compilations:
            raise RecompileError(
                f"{what} triggered {self.compilations} XLA compilation(s) "
                "after warmup — a static-shape or weak-type mismatch is "
                "defeating the jit cache")


class TransferSentinel:
    """Counts explicit host<->device transfers in the bracketed region.

    Patches ``jax.device_put`` / ``jax.device_get`` for the duration of
    the context and tallies calls (``h2d`` / ``d2h``). Implicit transfers
    (``np.asarray`` on a device array, scalar coercion) bypass those entry
    points; pass ``guard="disallow"`` to make jax raise on them instead —
    note the guard is inert on the CPU backend, where host and device
    memory are one and the same.

        with TransferSentinel() as t:
            run_fused_chunk()
        assert t.total == 0
    """

    def __init__(self, guard: str | None = None):
        self.h2d = 0
        self.d2h = 0
        self._guard = guard
        self._stack: contextlib.ExitStack | None = None

    @property
    def total(self) -> int:
        return self.h2d + self.d2h

    def __enter__(self) -> "TransferSentinel":
        import jax

        self._orig_put, self._orig_get = jax.device_put, jax.device_get

        def counted_put(*a, **kw):
            self.h2d += 1
            return self._orig_put(*a, **kw)

        def counted_get(*a, **kw):
            self.d2h += 1
            return self._orig_get(*a, **kw)

        jax.device_put, jax.device_get = counted_put, counted_get
        self._stack = contextlib.ExitStack()
        if self._guard:
            self._stack.enter_context(jax.transfer_guard(self._guard))
        return self

    def __exit__(self, *exc) -> None:
        import jax

        jax.device_put, jax.device_get = self._orig_put, self._orig_get
        if self._stack is not None:
            self._stack.close()
            self._stack = None
        from d4pg_tpu.obs.registry import REGISTRY

        REGISTRY.counter("profiling.explicit_h2d").inc(self.h2d)
        REGISTRY.counter("profiling.explicit_d2h").inc(self.d2h)
