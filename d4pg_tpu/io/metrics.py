"""Structured metrics bus with pluggable sinks.

One ``log(step, {...})`` call fans out to every sink: TensorBoard (the
reference's ``SummaryWriter`` scalars, ``main.py:17,66,352-353``), CSV (the
shape its offline plots consume: ``(step, avg_return, curr_return)`` rows,
``plots/plots.py:29-37``), and stdout. The bus is the "one structured
metrics bus" SURVEY.md §5 mandates in place of the reference's three
overlapping half-wired mechanisms.
"""

from __future__ import annotations

import csv
import os
import time
from typing import Mapping, Protocol


class MetricsSink(Protocol):
    def write(self, step: int, metrics: Mapping[str, float]) -> None: ...
    def close(self) -> None: ...


class TensorBoardSink:
    """TensorBoard scalars, lazily importing the writer."""

    def __init__(self, log_dir: str):
        # The writer only needs tensorboard's protobuf stub, but its lazy
        # compat layer imports the FULL tensorflow package when present —
        # which hard-segfaults in a process that already loaded MuJoCo's
        # EGL stack (the dm_control pixel path). Registering the `notf`
        # marker module makes tensorboard use its TF stub unconditionally.
        import sys
        import types

        sys.modules.setdefault(
            "tensorboard.compat.notf", types.ModuleType("tensorboard.compat.notf")
        )
        from torch.utils.tensorboard import SummaryWriter  # baked-in torch

        self._writer = SummaryWriter(log_dir)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        for name, value in metrics.items():
            self._writer.add_scalar(name, float(value), int(step))

    def close(self) -> None:
        self._writer.close()


class CsvLogger:
    """CSV rows compatible with the reference's offline plotting
    (``plots/plots.py:29-37`` reads ``step,avg_return,curr_return``)."""

    def __init__(self, path: str, fieldnames: list[str]):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file = open(path, "a", newline="")
        self._writer = csv.writer(self._file)
        self._fields = fieldnames

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        row = [step] + [metrics.get(f, "") for f in self._fields]
        self._writer.writerow(row)
        self._file.flush()

    def close(self) -> None:
        self._file.close()


class _StdoutSink:
    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        parts = " ".join(f"{k}={v:.4g}" for k, v in metrics.items())
        print(f"[step {step}] {parts}", flush=True)

    def close(self) -> None:
        pass


class MetricsBus:
    """Sink fan-out with crash containment: a sink that raises in
    ``write()``/``close()`` must never kill the learner loop (a full
    disk under the CSV logger or a wedged TensorBoard writer is an
    observability failure, not a training failure). A raising sink is
    logged ONCE, disabled for the rest of the run, and counted in the
    unified registry (``metrics_bus.sink_failures``) so the loss of
    telemetry is itself telemetered."""

    def __init__(self, sinks: list | None = None, echo: bool = False):
        self._sinks: list = list(sinks or [])
        if echo:
            self._sinks.append(_StdoutSink())
        self._t0 = time.monotonic()
        self._dead: list = []

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def _disable(self, sink, op: str, err: Exception) -> None:
        from d4pg_tpu.obs.registry import REGISTRY

        if sink in self._sinks:
            self._sinks.remove(sink)
            self._dead.append(sink)
        REGISTRY.counter("metrics_bus.sink_failures").inc()
        print(f"metrics sink {type(sink).__name__} disabled after "
              f"{op}() raised {type(err).__name__}: {err}", flush=True)

    def log(self, step: int, metrics: Mapping[str, float]) -> None:
        for sink in list(self._sinks):
            try:
                sink.write(step, metrics)
            except Exception as e:  # noqa: BLE001 — containment is the point
                self._disable(sink, "write", e)

    @property
    def elapsed(self) -> float:
        return time.monotonic() - self._t0

    def close(self) -> None:
        # dead sinks get a best-effort close too (they may hold an fd)
        for sink in list(self._sinks) + list(self._dead):
            try:
                sink.close()
            except Exception as e:  # noqa: BLE001
                self._disable(sink, "close", e)
        self._sinks = []
        self._dead = []
