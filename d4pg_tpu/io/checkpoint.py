"""Orbax checkpointing of the FULL train state, with resume.

The reference saves only actor/critic ``state_dict`` every cycle and has no
load path at all (``main.py:367-368``, SURVEY.md C20). Here the checkpoint
captures everything needed for exact resume (SURVEY.md §5 mandate): the
complete ``D4PGState`` (params, targets, both optimizer states, PRNG key,
step — the step also drives PER beta annealing, so that schedule resumes
exactly) plus user metadata (env steps, episode count).
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

# Sidecar frame shape comes from the declared wire registry; see
# core/wire.py and ``python -m d4pg_tpu.lint --wire``.
from d4pg_tpu.core.wire import (
    SIDECAR_HEAD as _SIDECAR_HEAD,
    SIDECAR_MAGIC as _SIDECAR_MAGIC,
    SIDECAR_VERSION,
)
from d4pg_tpu.learner.state import D4PGState

# -- replay sidecar (crash-recovery plane) ---------------------------------
#
# The ReplayService snapshot travels NEXT TO the orbax checkpoint, not
# inside it (the `extra` payload couples replay availability to the orbax
# retention window — see train._save_host_replay's history). The sidecar
# is a pickle framed with a magic + CRC32 footer so a torn write or bit
# rot is REJECTED with a clean error instead of feeding a half-snapshot
# into load_state_dict (where it would surface as a shape error deep in
# the buffer, or worse, not at all).


class SnapshotCorruptError(RuntimeError):
    """A replay sidecar whose bytes fail the integrity check (bad magic,
    unknown version, CRC mismatch, or an unpicklable body). Callers treat
    it like a missing sidecar — learner-only resume — but LOUDLY: silent
    acceptance of a torn snapshot would poison the restored buffer."""


def replay_sidecar_path(run_dir: str, process_index: int) -> str:
    return os.path.join(run_dir, f"replay_p{process_index}.pkl")


def save_replay_sidecar(run_dir: str, process_index: int, step: int,
                        snap: dict) -> str:
    """Atomically persist one host's replay snapshot, stamped with the
    learner step of its cut. Write-then-rename (a crash mid-save leaves
    the previous sidecar intact) with the CRC frame described above.
    Returns the sidecar path."""
    payload = pickle.dumps({"step": int(step), "snap": snap},
                           protocol=pickle.HIGHEST_PROTOCOL)
    head = _SIDECAR_HEAD.pack(_SIDECAR_MAGIC, SIDECAR_VERSION,
                              zlib.crc32(payload) & 0xFFFFFFFF)
    path = replay_sidecar_path(run_dir, process_index)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(head + payload)
    os.replace(tmp, path)
    return path


def load_replay_sidecar(run_dir: str,
                        process_index: int) -> tuple[dict, int] | None:
    """Read one host's replay sidecar: ``(snap, snap_step)``, or None
    when the file does not exist (the learner-only resume path). Raises
    ``SnapshotCorruptError`` on any integrity failure. Sidecars written
    before the CRC frame (a bare pickle) still load — the frame is
    additive, not a format break."""
    path = replay_sidecar_path(run_dir, process_index)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:4] == _SIDECAR_MAGIC:
        if len(blob) < _SIDECAR_HEAD.size:
            raise SnapshotCorruptError(f"{path}: truncated sidecar header")
        _magic, version, crc = _SIDECAR_HEAD.unpack_from(blob, 0)
        if version != SIDECAR_VERSION:
            raise SnapshotCorruptError(
                f"{path}: unknown sidecar version {version}")
        payload = blob[_SIDECAR_HEAD.size:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise SnapshotCorruptError(
                f"{path}: CRC mismatch — torn write or bit rot; "
                "refusing the snapshot")
    else:
        payload = blob  # pre-CRC legacy sidecar: bare pickle
    try:
        d = pickle.loads(payload)
        snap, step = d["snap"], int(d.get("step", -1))
    except SnapshotCorruptError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(f"{path}: undecodable sidecar ({e})")
    return snap, step


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 active_processes: set[int] | None = None):
        """``active_processes``: in a multi-host runtime, the processes
        participating in checkpoint io. The training driver saves the
        (host-replicated) state from process 0 only, so it passes ``{0}``
        — otherwise Orbax's internal barriers would wait on processes
        that never construct a manager."""
        self._dir = os.path.abspath(directory)
        # created here, not by Orbax: `create=True` is unsupported when
        # `active_processes` restricts the participant set
        os.makedirs(self._dir, exist_ok=True)
        mp_kwargs = (
            dict(create=False,
                 multiprocessing_options=ocp.options.MultiprocessingOptions(
                     primary_host=0, active_processes=active_processes,
                     barrier_sync_key_prefix="ckpt-p0"))
            if active_processes is not None else dict(create=True)
        )
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, **mp_kwargs),
        )

    @staticmethod
    def _to_host(state: D4PGState) -> dict[str, Any]:
        """Typed PRNG keys don't serialize as arrays; carry raw key data."""
        d = state._asdict()
        d["key"] = jax.random.key_data(d["key"])
        return jax.tree_util.tree_map(np.asarray, d)

    def save(self, state: D4PGState, extra: dict[str, Any] | None = None) -> None:
        """Checkpoint at the state's own learner step."""
        step = int(state.step)
        payload = {
            "state": self._to_host(state),
            "extra": dict(extra or {}),
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    @property
    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template: D4PGState) -> tuple[D4PGState, dict[str, Any]]:
        """Restore the latest checkpoint; ``template`` provides the pytree
        structure/dtypes (a freshly init'd state)."""
        step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        # Two passes: a raw restore recovers the (schema-free) extra dict,
        # then a typed restore against the template rebuilds the real
        # containers (optax NamedTuple states etc.) — a raw-only restore
        # would hand back plain dicts that break continued training.
        # A bare ``restore(step)`` fails on a freshly-constructed manager
        # (no handler registered for the "default" item); StandardRestore
        # without a target does the schema-free read.
        raw = self._mgr.restore(step, args=ocp.args.StandardRestore())
        target = {"state": self._to_host(template), "extra": raw["extra"]}
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        d = restored["state"]
        d["key"] = jax.random.wrap_key_data(d["key"])
        return D4PGState(**d), dict(restored["extra"] or {})

    def close(self) -> None:
        self._mgr.close()
