"""Typed experiment configuration + CLI front-end.

Parity: the reference's argparse surface (``main.py:31-56``) and its
config-mutating hooks (``main.py:84-99, 379-380``), as a frozen dataclass
with per-env presets (SURVEY.md §5 config-system mandate). Every reference
flag maps to a field; flags the reference exposes but never wires live
(``--ou_theta/--ou_sigma/--ou_mu``, SURVEY.md C6) are wired for real via
``noise='ou'``. Run-dir naming encodes the config like the reference's
``runs/exp_<env>__PER?_HER?_<n>N_<k>Workers`` (``main.py:59-66``).
"""

from __future__ import annotations

import argparse
import dataclasses

import numpy as np

from d4pg_tpu.envs.presets import get_preset, has_preset
from d4pg_tpu.learner.state import D4PGConfig


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    # env
    env: str = "Pendulum-v1"  # --env
    # episode horizon; None = from the env preset when one is curated, else
    # 200 (the reference's --max_steps default). An explicit value always
    # wins over the preset.
    max_steps: int | None = None  # --max_steps
    num_envs: int = 4  # vectorized pool width (reference: 1)
    her: bool = False  # --her
    her_ratio: float = 0.8  # main.py:165
    # pixel-obs rendering size (dm_control adapter) and conv-encoder width;
    # the 84px/32ch DrQ defaults cost ~40 GFLOP per grad step — smaller
    # settings make pixel training tractable on modest hosts
    pixel_size: int = 84
    encoder_width: int = 32
    # frames stacked along the channel axis for pixel envs (FrameStack
    # wrapper). 1 = raw single frames (a POMDP for dynamic tasks —
    # velocities are invisible); 3 is the DrQ/D4PG-pixels convention and
    # the right setting for dm_control pixel control.
    frame_stack: int = 1
    # DrQ random-shift augmentation inside the jit'd update (pixel envs
    # only): 'none' or 'shift'; the shift radius should roughly scale with
    # the frame size (DrQ's 4px is calibrated to 84px frames)
    augment: str = "none"
    augment_pad: int = 4
    # tie the actor's conv encoder to the critic's, trained by the critic
    # loss only (SAC-AE/DrQ; pixels only — see learner/state.py)
    share_encoder: bool = False
    reward_scale: float = 1.0
    # replay
    memory_size: int = 1_000_000  # --rmsize
    batch_size: int = 64  # --bsize
    warmup: int = 5000  # --warmup (main.py:200-207)
    prioritized_replay: bool = True  # --p_replay
    per_alpha: float = 0.6  # ddpg.py:81
    per_beta0: float = 0.4  # ddpg.py:84
    per_beta_steps: int = 100_000  # ddpg.py:85
    # n-step return horizon; None = from a curated env preset, else 3
    n_steps: int | None = None  # --n_steps
    # 'device': transition ring in accelerator HBM (host keeps PER trees,
    # picks indices; per-dispatch H2D is O(indices) not O(batch bytes));
    # 'auto' selects device on an accelerator single-device learner.
    replay_storage: str = "auto"
    # Fully-fused replay+learn path (learner/fused.py): PER trees join the
    # ring in HBM and sample/gather/update/priority-write-back all run
    # inside the scanned dispatch — zero per-chunk host round trips, zero
    # priority staleness. 'auto' = on whenever storage resolves to device
    # and the learner is single-device; 'off' keeps host trees.
    fused_replay: str = "auto"
    # K learner updates fused into one device dispatch via lax.scan.
    # Dispatch latency dominates a tunneled/PCIe learner, so throughput
    # scales ~linearly in K (fused path on one v5e chip: ~36k/~67k/~176k
    # steps/sec at K=8/16/40 — bench.py's shipped-default measurement;
    # run-to-run tunnel variance ~10%). 40 = one dispatch per HER-paper cycle
    # (main.py:303-307's 40 train steps). On the fused path priorities
    # still update per-step INSIDE the scan (zero staleness); the host
    # pipeline's write-back lags <= (depth+1)K, default 3K. Async weight staleness <= K.
    # Composes with data_parallel (batches sharded P(None, 'data')).
    # 1 = exact reference dispatch semantics (write-back every step).
    updates_per_dispatch: int = 40
    # Multi-learner plane (learner/replica.py + learner/aggregator.py):
    # N replicas each own a full D4PGState (their OWN optimizer state and
    # PRNG key) and sample the shared ReplayService concurrently; an
    # aggregator merges their version-stamped updates into the ONE
    # WeightStore stream with IMPACT-style staleness weighting (arXiv
    # 1912.00167). 1 = the legacy fused single-learner loop (same code:
    # both paths drive learner/loop.FusedLoop). N > 1 requires the
    # host-sampled replay path (fused device replay is single-consumer).
    learners: int = 1  # --learners
    # Sample-on-ingest (docs/architecture.md "Sample-on-ingest"): PER
    # sampling runs on the receive path — the commit thread deals
    # ready-to-train blocks into per-replica rings inside its own
    # buffer-lock window, and replicas feed TD priorities back through a
    # generation-fenced write-back queue. Requires the host replay path
    # (--fused_replay off) with prioritized replay.
    sample_on_ingest: bool = False
    # Sample-path arm for --sample_on_ingest (the third autotune
    # surface, ops/autotune.select_sampler): 'auto' resolves via the
    # static policy + (on TPU) a startup descent micro-benchmark;
    # 'scan' = device jnp gather descent fused behind the commit
    # dispatch; 'pallas' = the VMEM-resident descent kernel
    # (ops/sampler_descent.py); 'host' = the PR-12 host SampleDealer
    # (the fallback arm — host tree math, pinned bitwise-equal to the
    # device path under the seeded-stream oracle). Device arms require
    # --fused_replay with --ingest_shards 1 (the commit thread owns
    # every device handle); 'host' requires the host replay path.
    sampler: str = "auto"
    # 'async': clipped importance-weighted staleness correction, no
    # barrier; 'sync': plain N-way averaging barrier per round
    agg_mode: str = "async"
    # staleness-weight clip: a stale update's weight is
    # max(1/(1+lag), 1/agg_clip) — the floor keeps a lagging replica's
    # vote bounded away from zero (>= 1; higher tolerates more staleness)
    agg_clip: float = 8.0
    # How replica updates reach the merge (learner/mesh_replicas.py):
    # 'collective' = mesh-native — replica states sharded along the
    # 'replica' mesh axis, the merge an on-device collective (requires
    # the replicas to share one single-host mesh); 'socket' = the PR-10
    # host-thread aggregator over 0xD4AB frames (works anywhere; the
    # cross-host fallback); 'auto' = collective when a mesh is present
    # and single-host, socket otherwise.
    agg_transport: str = "auto"
    # algorithm
    gamma: float = 0.99  # --gamma
    tau: float = 0.001  # --tau
    # HER-recipe action-L2 penalty on the actor loss (0 = reference objective)
    action_l2: float = 0.0
    lr_actor: float = 1e-4
    lr_critic: float = 1e-3
    adam_b1: float = 0.9
    adam_b2: float = 0.999  # reference (0.9, 0.9) available via flags
    v_min: float | None = None  # --v_min (None: from preset)
    v_max: float | None = None  # --v_max
    n_atoms: int = 51  # --n_atoms
    critic_family: str = "categorical"
    # Categorical Bellman-projection impl: 'auto' (default) runs the
    # startup micro-autotuner (ops/autotune.py) which times einsum /
    # pallas / pallas_ce on the actual shapes and picks the winner
    # (BENCH_r05: einsum wins at the bench shape — but that is a measured
    # fact of (batch, atoms, chip), not a constant); an explicit variant
    # is the escape hatch and is honored verbatim. Non-TPU backends and
    # mesh learners resolve to einsum without timing (see ops/autotune.py
    # policy). The selection is logged at startup.
    projection: str = "auto"
    hidden: tuple = (256, 256, 256)
    compute_dtype: str = "float32"  # 'bfloat16' for MXU-native matmuls
    # exploration
    noise: str = "gaussian"  # 'gaussian' | 'ou'
    # per-tick probability of a uniform random action (HER-recipe
    # epsilon-greedy; 0 = reference's additive-noise-only exploration)
    random_eps: float = 0.0
    # Running observation standardization (envs/normalizer.py): actors
    # store normalized rows, eval applies the same stats; off = reference
    # behavior (no normalization anywhere). Vector obs only (the pixel
    # encoder normalizes by /255). HER-recipe component for Fetch/Hand.
    normalize_obs: bool = False
    normalize_clip: float = 5.0  # +-clip after standardization (HER paper)
    epsilon_0: float = 0.3  # random_process.py:11
    min_epsilon: float = 0.01
    epsilon_horizon: int = 5000
    ou_theta: float = 0.25  # --ou_theta (main.py:36, dead in reference)
    ou_sigma: float = 0.05  # --ou_sigma
    ou_mu: float = 0.0  # --ou_mu
    # Backend for actor/evaluator inference: 'cpu' pins the per-tick policy
    # forward to host CPU (the accelerator stays the learner's; a per-step
    # device round trip costs more than the MLP forward), 'default' follows
    # the default backend (see ActorConfig.device).
    actor_device: str = "cpu"
    # loop shape (main.py:299-312)
    n_epochs: int = 20  # --n_eps
    n_cycles: int = 50
    episodes_per_cycle: int = 16
    train_steps_per_cycle: int = 40
    eval_trials: int = 10
    # Evaluate on a background thread (the reference's separate evaluator
    # process, main.py:395-397); 0 = inline on the learner thread.
    concurrent_eval: bool = True
    # distributed
    n_workers: int = 1  # --n_workers (in-process actor threads)
    # Multi-host runtime (jax.distributed): every host starts the same
    # train command with its own --process_id; process 0's host:port is
    # the coordinator. Empty coordinator = single-process (default).
    coordinator: str = ""
    # Backend selection for the learner: 'auto' probes the accelerator in a
    # subprocess (a wedged tunnel hangs backend init forever — observed on
    # this image) and falls back to CPU; 'accel' skips the probe; 'cpu'
    # forces the host backend. The probe runs on the CLI path only
    # (train.main); programmatic train() callers get 'cpu' honored but no
    # probing.
    platform: str = "auto"
    num_processes: int = 1
    process_id: int = 0
    # Spawned local actor PROCESSES connecting through the TCP plane
    # (implies --serve): real parallelism for host-bound env stepping,
    # unlike in-process actor threads which share the learner's GIL.
    actor_procs: int = 0
    data_parallel: int = 1  # learner mesh data axis (1 = single device)
    async_actors: bool = False  # decoupled D4PG-paper actor/learner loop
    serve: bool = False  # accept remote actors (actor_main.py) over TCP
    serve_host: str = "127.0.0.1"  # bind address; set the DCN iface for fleets
    serve_secret: str = ""  # shared secret gating remote peers ('' = open)
    serve_transitions_port: int = 0  # 0 = ephemeral
    serve_weights_port: int = 0
    # Serving plane (docs/architecture.md "Serving plane"): stand up the
    # continuous-batching PolicyInferenceServer next to the transition/
    # weight servers so remote actors launched with ``--policy_port``
    # query greedy actions instead of acting locally. Window/row-budget
    # knobs bound the batcher's coalescing; the staleness SLA is the
    # declared freshness bound (breaches are counted, not fatal).
    serve_policy: bool = False
    serve_policy_port: int = 0  # 0 = ephemeral
    serve_policy_window_s: float = 0.002
    serve_policy_max_rows: int = 256
    serve_policy_sla_s: float = 1.0
    # Elastic traffic plane (docs/architecture.md "Elastic traffic
    # plane"): run the obs-driven autoscaler thread next to the serving/
    # ingest planes — it polls the obs-registry providers and live-
    # adjusts the serving batch limits, ingest shard depth, dealer
    # pacing, and active learner-replica count through their bounded
    # setters, journaling every decision in a replayable ScalingLedger.
    # Off = every capacity knob stays at its startup value (the
    # pre-elastic behaviour, bit for bit).
    autoscale: bool = False
    autoscale_interval_s: float = 0.25
    # Weight-broadcast version window (docs/architecture.md "Weight
    # plane"): the server keeps this many recent versions so pullers
    # inside the window receive per-tensor deltas instead of full
    # snapshots; pullers outside it (or across a learner restart's
    # generation bump) fall back to a full frame.
    weight_window: int = 8
    # Receiver-side ingest shards (docs/architecture.md "Sharded
    # receiver"): K SO_REUSEPORT listeners + K decode/stage workers + one
    # ordered merge-commit thread. 1 = the legacy single-drain plane.
    ingest_shards: int = 1
    # Wire-to-grad tracing (docs/architecture.md "Observability plane"):
    # arms the learner-side trace recorder and stamps grad-consumption
    # spans after each fused dispatch; remote actors sample frames at
    # this rate when launched with ``--codec raw --trace_sample <f>``.
    # 0 = fully inert (no recorder, no per-chunk hook).
    trace_sample: float = 0.0
    profile_dir: str = ""  # capture an XLA trace of the first cycle
    # io
    log_dir: str = "runs"  # --log_dir
    seed: int = 0
    checkpoint_every: int = 1  # cycles between checkpoints (main.py:367)
    # Also checkpoint the replay buffer (contents + PER priorities) for
    # EXACT elastic recovery — without it a resumed learner retrains from
    # an empty buffer through a fresh warmup. Off by default: the payload
    # is the whole ring (GBs at 1M Humanoid transitions).
    checkpoint_replay: bool = False
    # Ring payloads ride only every Nth checkpoint: the snapshot holds the
    # buffer lock (stalling actor ingest) and for a device-resident ring
    # pays a full D2H copy, so per-cycle would be pathological. A resume
    # whose latest checkpoint lacks the payload just re-runs warmup.
    checkpoint_replay_every: int = 10
    resume: bool = False
    debug: bool = False  # --debug
    # One-flag parity mode: the reference's own hyperparameters — v_min/
    # v_max from its per-env hook (main.py:84-99), Adam betas (0.9, 0.9)
    # (shared_adam.py:4), lr 1e-3 for both nets (main.py:384-385,
    # n_workers=1), no reward scaling, and single-dispatch updates (exact
    # per-step priority write-back like ddpg.py:252-255).
    strict_reference: bool = False

    def run_name(self) -> str:
        """Config-encoded run dir (parity: ``main.py:59-64``). Resolves
        first so a preset-defaulted n_steps (None until resolve) encodes
        identically on resolved and unresolved configs."""
        cfg = self.resolve()
        return (
            f"exp_{cfg.env}_"
            f"{'_PER' if cfg.prioritized_replay else ''}"
            f"{'_HER' if cfg.her else ''}"
            f"_{cfg.n_steps}N_{cfg.n_workers}Workers"
        )

    def resolve(self) -> "ExperimentConfig":
        """Fill v_min/v_max (+ reward scale / horizon) from the env preset
        when unset (the ``configure_env_params`` hook, ``main.py:84-99``).
        ``strict_reference`` switches to the reference's own preset values
        and training hyperparameters wholesale."""
        preset = get_preset(self.env, strict=self.strict_reference)
        curated = has_preset(self.env, strict=self.strict_reference)
        updates: dict = {}
        if self.v_min is None:
            updates["v_min"] = preset.v_min
        if self.v_max is None:
            updates["v_max"] = preset.v_max
        if self.reward_scale == 1.0 and preset.reward_scale != 1.0:
            updates["reward_scale"] = preset.reward_scale
        # horizon / n-step: unset (None) -> curated preset value, else the
        # reference defaults (200 / 3); explicit values always win, and the
        # fallback preset's own field defaults never masquerade as curation
        if self.max_steps is None:
            updates["max_steps"] = preset.max_steps if curated else 200
        if self.n_steps is None:
            updates["n_steps"] = preset.n_step if curated else 3
        if self.strict_reference:
            updates.update(
                reward_scale=1.0,
                lr_actor=1e-3,  # main.py:384-385 at n_workers=1
                lr_critic=1e-3,
                adam_b1=0.9,  # shared_adam.py:4
                adam_b2=0.9,
                updates_per_dispatch=1,  # per-step write-back, ddpg.py:252-255
            )
        return dataclasses.replace(self, **updates) if updates else self

    def learner_config(self, obs_dim: int | tuple, act_dim: int) -> D4PGConfig:
        """``obs_dim`` is an int (vector obs) or an [H, W, C] tuple, which
        selects the conv-encoder pixel path (BASELINE.md config #4)."""
        resolved = self.resolve()
        pixels = not np.isscalar(obs_dim)
        projection = self.projection
        if projection == "auto":
            # D4PGConfig is the jit-static config — 'auto' must resolve to
            # a concrete variant BEFORE it is built. The autotuner times
            # the candidates on the actual (batch, atoms) shapes on TPU;
            # mesh/multi-host and non-TPU backends resolve statically to
            # einsum (see ops/autotune.py). Explicit flags bypass all this.
            from d4pg_tpu.ops.autotune import select_projection

            mesh = (self.data_parallel > 1 or self.num_processes > 1
                    or bool(self.coordinator))
            projection = select_projection(
                "auto", batch_size=self.batch_size,
                v_min=float(resolved.v_min), v_max=float(resolved.v_max),
                n_atoms=self.n_atoms, mesh=mesh).selected
        return D4PGConfig(
            obs_dim=int(np.prod(obs_dim)) if pixels else obs_dim,
            pixels=pixels,
            obs_shape=tuple(obs_dim) if pixels else (),
            act_dim=act_dim,
            v_min=float(resolved.v_min),
            v_max=float(resolved.v_max),
            n_atoms=self.n_atoms,
            hidden=tuple(self.hidden),
            critic_family=self.critic_family,
            projection=projection,
            augment=self.augment,
            augment_pad=self.augment_pad,
            share_encoder=self.share_encoder,
            encoder_channels=(self.encoder_width,) * 4,
            lr_actor=self.lr_actor,
            lr_critic=self.lr_critic,
            adam_b1=self.adam_b1,
            adam_b2=self.adam_b2,
            compute_dtype=self.compute_dtype,
            tau=self.tau,
            gamma=self.gamma,
            action_l2=self.action_l2,
        )


def _add_bool_flag(parser: argparse.ArgumentParser, name: str, default: bool, help_: str):
    """0/1 int flags like the reference's --p_replay/--her/--multithread
    (``main.py:44`` quirk: --debug as type=bool parses any string truthy —
    not reproduced)."""
    parser.add_argument(f"--{name}", type=int, choices=(0, 1),
                        default=int(default), help=help_)


def build_parser() -> argparse.ArgumentParser:
    d = ExperimentConfig()
    p = argparse.ArgumentParser(
        prog="d4pg_tpu.train",
        description="TPU-native D4PG (capability parity with ajgupta93/d4pg-pytorch)",
    )
    p.add_argument("--env", default=d.env)
    p.add_argument("--max_steps", type=int, default=d.max_steps)
    p.add_argument("--num_envs", type=int, default=d.num_envs)
    _add_bool_flag(p, "her", d.her, "hindsight experience replay")
    p.add_argument("--her_ratio", type=float, default=d.her_ratio)
    p.add_argument("--pixel_size", type=int, default=d.pixel_size,
                   help="dm_control pixel render height/width")
    p.add_argument("--encoder_width", type=int, default=d.encoder_width,
                   help="conv-encoder channel width (4 layers)")
    p.add_argument("--frame_stack", type=int, default=d.frame_stack,
                   help="frames stacked channel-wise for pixel envs "
                        "(1 = raw frames; 3 = DrQ/D4PG-pixels convention "
                        "— single frames hide velocities)")
    p.add_argument("--augment", choices=("none", "shift"), default=d.augment,
                   help="batch image augmentation in the update (pixel "
                        "envs): 'shift' = DrQ random shift")
    p.add_argument("--augment_pad", type=int, default=d.augment_pad,
                   help="shift radius in pixels (DrQ uses 4 at 84px; "
                        "scale with --pixel_size)")
    _add_bool_flag(p, "share_encoder", d.share_encoder,
                   "critic-trained shared conv encoder (SAC-AE/DrQ; "
                   "pixel envs)")
    p.add_argument("--rmsize", type=int, default=d.memory_size, dest="memory_size")
    p.add_argument("--bsize", type=int, default=d.batch_size, dest="batch_size")
    p.add_argument("--warmup", type=int, default=d.warmup)
    _add_bool_flag(p, "p_replay", d.prioritized_replay, "prioritized replay")
    p.add_argument("--per_alpha", type=float, default=d.per_alpha)
    p.add_argument("--per_beta0", type=float, default=d.per_beta0)
    p.add_argument("--per_beta_steps", type=int, default=d.per_beta_steps)
    p.add_argument("--n_steps", type=int, default=d.n_steps)
    p.add_argument("--replay_storage", choices=("auto", "host", "device"),
                   default=d.replay_storage)
    p.add_argument("--fused_replay", choices=("auto", "on", "off"),
                   default=d.fused_replay)
    p.add_argument("--updates_per_dispatch", type=int,
                   default=d.updates_per_dispatch)
    p.add_argument("--gamma", type=float, default=d.gamma)
    p.add_argument("--tau", type=float, default=d.tau)
    p.add_argument("--action_l2", type=float, default=d.action_l2)
    p.add_argument("--lr_actor", type=float, default=d.lr_actor)
    p.add_argument("--lr_critic", type=float, default=d.lr_critic)
    p.add_argument("--adam_b1", type=float, default=d.adam_b1)
    p.add_argument("--adam_b2", type=float, default=d.adam_b2)
    p.add_argument("--v_min", type=float, default=None)
    p.add_argument("--v_max", type=float, default=None)
    p.add_argument("--n_atoms", type=int, default=d.n_atoms)
    p.add_argument("--critic_family", choices=("categorical", "mog"),
                   default=d.critic_family)
    p.add_argument("--projection",
                   choices=("auto", "einsum", "pallas", "pallas_ce"),
                   default=d.projection,
                   help="categorical Bellman-projection impl: 'auto' "
                        "(default) micro-autotunes on the actual shapes "
                        "at startup; or pin the MXU einsum, the VMEM "
                        "Pallas projection kernel, or pallas_ce "
                        "(projection fused into the cross-entropy loss, "
                        "forward + backward)")
    p.add_argument("--compute_dtype", choices=("float32", "bfloat16"),
                   default=d.compute_dtype)
    p.add_argument("--noise", choices=("gaussian", "ou"), default=d.noise)
    p.add_argument("--epsilon_0", type=float, default=d.epsilon_0)
    p.add_argument("--random_eps", type=float, default=d.random_eps)
    _add_bool_flag(p, "normalize_obs", d.normalize_obs,
                   "running observation standardization")
    p.add_argument("--normalize_clip", type=float, default=d.normalize_clip)
    p.add_argument("--ou_theta", type=float, default=d.ou_theta)
    p.add_argument("--ou_sigma", type=float, default=d.ou_sigma)
    p.add_argument("--ou_mu", type=float, default=d.ou_mu)
    p.add_argument("--actor_device", choices=("cpu", "default"),
                   default=d.actor_device)
    p.add_argument("--n_eps", type=int, default=d.n_epochs, dest="n_epochs")
    p.add_argument("--n_cycles", type=int, default=d.n_cycles)
    p.add_argument("--episodes_per_cycle", type=int, default=d.episodes_per_cycle)
    p.add_argument("--train_steps_per_cycle", type=int,
                   default=d.train_steps_per_cycle)
    p.add_argument("--eval_trials", type=int, default=d.eval_trials)
    _add_bool_flag(p, "concurrent_eval", d.concurrent_eval,
                   "evaluate on a background thread")
    p.add_argument("--n_workers", type=int, default=d.n_workers)
    p.add_argument("--actor_procs", type=int, default=d.actor_procs)
    p.add_argument("--coordinator", default=d.coordinator)
    p.add_argument("--platform", choices=("auto", "accel", "cpu"),
                   default=d.platform)
    p.add_argument("--num_processes", type=int, default=d.num_processes)
    p.add_argument("--process_id", type=int, default=d.process_id)
    p.add_argument("--data_parallel", type=int, default=d.data_parallel)
    _add_bool_flag(p, "async_actors", d.async_actors,
                   "decoupled actor/learner loop")
    _add_bool_flag(p, "serve", d.serve, "accept remote actors over TCP")
    p.add_argument("--serve_host", default=d.serve_host)
    p.add_argument("--serve_secret", default=d.serve_secret)
    p.add_argument("--serve_transitions_port", type=int,
                   default=d.serve_transitions_port)
    p.add_argument("--serve_weights_port", type=int, default=d.serve_weights_port)
    _add_bool_flag(p, "serve_policy", d.serve_policy,
                   "serve greedy actions to remote actors "
                   "(--policy_port) via the continuous-batching "
                   "policy server")
    p.add_argument("--serve_policy_port", type=int,
                   default=d.serve_policy_port)
    p.add_argument("--serve_policy_window_s", type=float,
                   default=d.serve_policy_window_s,
                   help="continuous-batching window: the first pending "
                        "request waits at most this long for riders")
    p.add_argument("--serve_policy_max_rows", type=int,
                   default=d.serve_policy_max_rows,
                   help="row budget per fused serving dispatch")
    p.add_argument("--serve_policy_sla_s", type=float,
                   default=d.serve_policy_sla_s,
                   help="declared params-freshness SLA: batches served "
                        "from an older snapshot count sla_breaches")
    _add_bool_flag(p, "autoscale", d.autoscale,
                   "run the obs-driven autoscaler (elastic/autoscaler): "
                   "live-adjust serving batch limits, ingest depth, "
                   "dealer pacing and active replica count from "
                   "registry signals, every decision ledgered")
    p.add_argument("--autoscale_interval_s", type=float,
                   default=d.autoscale_interval_s,
                   help="autoscaler control-loop period")
    p.add_argument("--weight_window", type=int, default=d.weight_window,
                   help="weight-broadcast delta window: recent versions "
                        "kept server-side so in-window pullers get "
                        "per-tensor deltas instead of full snapshots")
    p.add_argument("--ingest_shards", type=int, default=d.ingest_shards,
                   help="receiver-side ingest shards: K SO_REUSEPORT "
                        "listeners + K decode/stage workers + one ordered "
                        "merge-commit thread (1 = legacy single drain)")
    p.add_argument("--trace_sample", type=float, default=d.trace_sample,
                   help="arm wire-to-grad trace spans (obs/trace): the "
                        "learner records per-stage latency histograms for "
                        "frames remote actors sample at this rate over "
                        "the raw codec (0 = off)")
    p.add_argument("--learners", type=int, default=d.learners,
                   help="learner replicas: N>1 runs each on its own "
                        "thread against the shared replay service, with "
                        "an aggregator merging their updates into the "
                        "single versioned weight stream (1 = legacy "
                        "fused single-learner loop)")
    p.add_argument("--agg_mode", choices=("async", "sync"),
                   default=d.agg_mode,
                   help="update aggregation: 'async' = IMPACT-style "
                        "clipped staleness-weighted correction, 'sync' = "
                        "N-way averaging barrier")
    p.add_argument("--agg_clip", type=float, default=d.agg_clip,
                   help="staleness-weight clip (async mode): a stale "
                        "update's weight is max(1/(1+lag), 1/clip)")
    p.add_argument("--agg_transport", choices=("auto", "socket", "collective"),
                   default=d.agg_transport,
                   help="how replica updates reach the merge: "
                        "'collective' = mesh-native on-device merge over "
                        "the 'replica' mesh axis (replicas share one "
                        "single-host mesh), 'socket' = host-thread "
                        "aggregator over 0xD4AB frames (cross-host "
                        "fallback), 'auto' = collective when a mesh is "
                        "present and single-host")
    _add_bool_flag(p, "sample_on_ingest", d.sample_on_ingest,
                   "fuse PER sampling into the receive path: the commit "
                   "thread deals ready-to-train blocks to the learner "
                   "replicas (host replay + prioritized only)")
    p.add_argument("--sampler", choices=("auto", "scan", "pallas", "host"),
                   default=d.sampler,
                   help="sample-path arm for --sample_on_ingest: 'scan' = "
                        "device jnp gather descent fused behind the commit "
                        "dispatch, 'pallas' = VMEM-resident descent kernel, "
                        "'host' = PR-12 host SampleDealer (fallback), "
                        "'auto' = static policy + TPU descent "
                        "micro-benchmark (ops/autotune.select_sampler)")
    p.add_argument("--profile_dir", default=d.profile_dir)
    p.add_argument("--log_dir", default=d.log_dir)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--reward_scale", type=float, default=d.reward_scale)
    _add_bool_flag(p, "checkpoint_replay", d.checkpoint_replay,
                   "include the replay buffer in checkpoints")
    p.add_argument("--checkpoint_replay_every", type=int,
                   default=d.checkpoint_replay_every)
    _add_bool_flag(p, "resume", d.resume, "resume from latest checkpoint")
    _add_bool_flag(p, "debug", d.debug, "debug logging")
    _add_bool_flag(p, "strict_reference", d.strict_reference,
                   "reference hyperparameter parity mode")
    return p


def parse_args(argv=None) -> ExperimentConfig:
    ns = vars(build_parser().parse_args(argv))
    ns["her"] = bool(ns["her"])
    ns["prioritized_replay"] = bool(ns.pop("p_replay"))
    ns["resume"] = bool(ns["resume"])
    ns["checkpoint_replay"] = bool(ns["checkpoint_replay"])
    ns["debug"] = bool(ns["debug"])
    ns["async_actors"] = bool(ns["async_actors"])
    ns["serve"] = bool(ns["serve"])
    ns["serve_policy"] = bool(ns["serve_policy"])
    ns["concurrent_eval"] = bool(ns["concurrent_eval"])
    ns["strict_reference"] = bool(ns["strict_reference"])
    ns["normalize_obs"] = bool(ns["normalize_obs"])
    ns["sample_on_ingest"] = bool(ns["sample_on_ingest"])
    ns["autoscale"] = bool(ns["autoscale"])
    return ExperimentConfig(**ns)
