"""Structured run logger with persistence.

Parity: the notebook ``Logger`` (``plotUtil.ipynb`` cell 0): named-series
logs keyed by a run name, a wall-clock timestamp per point, persistence on
every ``log()`` call, and cross-run comparison loading. JSONL instead of
pickle: append-only (a crash can't truncate the whole history, unlike the
reference's rewrite-the-pickle-per-log), diffable, and readable without
unpickling arbitrary code.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict


class RunLogger:
    def __init__(self, path: str, run_name: str):
        self.run_name = run_name
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.series: dict[str, list] = defaultdict(list)
        self._file = open(path, "a")

    def log(self, series: str, step: int, value: float) -> None:
        """Append one point and persist it immediately (the reference
        persists per log() call too, cell 0)."""
        point = {"run": self.run_name, "series": series, "step": int(step),
                 "value": float(value), "time": time.time()}
        self.series[series].append((int(step), float(value)))
        self._file.write(json.dumps(point) + "\n")
        self._file.flush()

    def close(self) -> None:
        self._file.close()

    @staticmethod
    def load(path: str) -> dict[str, dict[str, list]]:
        """Load a JSONL log into {run: {series: [(step, value), ...]}}."""
        runs: dict[str, dict[str, list]] = defaultdict(lambda: defaultdict(list))
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                p = json.loads(line)
                runs[p["run"]][p["series"]].append((p["step"], p["value"]))
        return {r: dict(s) for r, s in runs.items()}
