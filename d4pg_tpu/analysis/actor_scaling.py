"""Measure local actor-process scaling: env-steps/sec vs --actor_procs.

The reference scales acting by forking N full worker processes sharing one
model in OS shared memory (``main.py:399-405``); here N spawned actor
processes stream transitions to the learner's TCP plane
(``train.py --actor_procs``). This tool boots ONLY the ingest plane (replay
service + transition receiver + weight server, no learner) and counts
arriving env steps over a fixed window:

    python -m d4pg_tpu.analysis.actor_scaling --procs 1 2 4 --seconds 10

It also renders the FLEET scaling curve from a ``bench_fleet`` artifact
(``python bench.py --fleet``, ``d4pg_tpu/fleet``) — rows/s vs N with p99
send latency and the per-N loss/recovery counters, as a table and
optionally a PNG:

    python -m d4pg_tpu.analysis.actor_scaling \\
        --fleet docs/evidence/fleet/fleet_<stamp>.json --plot fleet.png
"""

from __future__ import annotations

import argparse
import dataclasses
import multiprocessing as mp
import time


def measure(n_procs: int, seconds: float, env: str = "point",
            num_envs: int = 8, max_steps: int = 200) -> float:
    from d4pg_tpu.actor_main import run_local_actor_process
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.distributed import ReplayService, WeightStore
    from d4pg_tpu.distributed.transport import TransitionReceiver
    from d4pg_tpu.distributed.weight_server import WeightServer
    from d4pg_tpu.replay import ReplayBuffer
    from d4pg_tpu.train import infer_dims

    cfg = ExperimentConfig(env=env, num_envs=num_envs, max_steps=max_steps,
                           v_min=-5.0, v_max=0.0)
    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    service = ReplayService(
        ReplayBuffer(1_000_000, obs_dim, act_dim, obs_dtype=obs_dtype))
    weights = WeightStore()
    receiver = TransitionReceiver(
        lambda b, aid, count: service.add(b, actor_id=aid,
                                          count_env_steps=count),
        host="127.0.0.1")
    weight_server = WeightServer(weights, host="127.0.0.1")

    ctx = mp.get_context("spawn")
    procs = []
    for i in range(n_procs):
        p = ctx.Process(
            target=run_local_actor_process,
            args=(dataclasses.replace(cfg, seed=1000 * (i + 1)), "127.0.0.1",
                  receiver.port, weight_server.port, f"scale-{i}", None),
            daemon=True,
        )
        p.start()
        procs.append(p)

    # let the fleet finish jax/env startup before the measurement window
    deadline = time.monotonic() + 120.0
    while service.env_steps < n_procs * num_envs and time.monotonic() < deadline:
        time.sleep(0.1)
    start_steps = service.env_steps
    t0 = time.monotonic()
    time.sleep(seconds)
    rate = (service.env_steps - start_steps) / (time.monotonic() - t0)

    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5.0)
    receiver.close()
    weight_server.close()
    service.close()
    return rate


def measure_budget(obs_dim: int = 376, act_dim: int = 17, rows: int = 8,
                   frames: int = 2000) -> dict:
    """Per-component cost of one transition frame on the streaming plane:
    encode (pickle), socket+decode+ingest-callback (loopback TCP through
    the real ``TransitionReceiver``), and the replay ``service.add`` — the
    measured budget for where actor fan-out saturates (VERDICT r4 #5).
    Frame shape = one actor tick of ``rows`` Humanoid-sized transitions."""
    import threading

    import numpy as np

    from d4pg_tpu.distributed import ReplayService
    from d4pg_tpu.distributed.transport import (
        TransitionReceiver,
        TransitionSender,
        _encode,
    )
    from d4pg_tpu.replay import ReplayBuffer
    from d4pg_tpu.replay.uniform import TransitionBatch

    rng = np.random.default_rng(0)
    batch = TransitionBatch(
        obs=rng.standard_normal((rows, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (rows, act_dim)).astype(np.float32),
        reward=rng.standard_normal(rows).astype(np.float32),
        next_obs=rng.standard_normal((rows, obs_dim)).astype(np.float32),
        done=np.zeros(rows, np.float32),
        discount=np.full(rows, 0.99, np.float32),
    )
    out = {"rows_per_frame": rows, "obs_dim": obs_dim}

    payload = _encode("budget", batch, True)
    out["frame_bytes"] = len(payload)
    t0 = time.monotonic()
    for _ in range(frames):
        _encode("budget", batch, True)
    out["encode_us_per_frame"] = 1e6 * (time.monotonic() - t0) / frames

    # socket + decode + the PRODUCTION ingest callback (service.add, as
    # measure() and train.py wire it), through the real receiver thread;
    # the clock stops only when every row is INSERTED in the buffer (the
    # service drain thread's work counts — it shares the learner core)
    service = ReplayService(ReplayBuffer(1_000_000, obs_dim, act_dim))
    got = threading.Event()
    n_recv = 0

    def on_batch(b, aid, count):
        nonlocal n_recv
        service.add(b, actor_id=aid, count_env_steps=count)
        n_recv += 1
        if n_recv >= frames:
            got.set()

    receiver = TransitionReceiver(on_batch, host="127.0.0.1")
    sender = TransitionSender("127.0.0.1", receiver.port, actor_id="budget")
    sender.send(batch)  # connection warmup
    while n_recv < 1:
        time.sleep(0.01)
    n_recv, t0 = 0, time.monotonic()
    target = len(service.buffer) + frames * rows
    for _ in range(frames):
        sender.send(batch)
    if not got.wait(timeout=120.0):
        raise RuntimeError(
            f"ingest stalled: {n_recv}/{frames} frames in 120s")
    deadline = time.monotonic() + 30.0
    while len(service.buffer) < target:  # drain-thread completion
        if time.monotonic() > deadline:
            raise RuntimeError("replay drain stalled")
        time.sleep(0.001)
    out["socket_ingest_us_per_frame"] = 1e6 * (time.monotonic() - t0) / frames
    sender.close()
    receiver.close()
    service.close()

    # the raw locked buffer insert alone (the drain thread's inner cost)
    buf = ReplayBuffer(1_000_000, obs_dim, act_dim)
    buf.add(batch)
    t0 = time.monotonic()
    for _ in range(frames):
        buf.add(batch)
    out["buffer_insert_us_per_frame"] = 1e6 * (time.monotonic() - t0) / frames

    total_us = (out["encode_us_per_frame"]
                + out["socket_ingest_us_per_frame"])
    # encode happens actor-side (parallel across procs); the learner-side
    # serial section is socket+decode+service.add+insert — the measured
    # wall above — so IT sets the plane ceiling
    out["plane_ceiling_env_steps_per_sec"] = (
        rows * 1e6 / out["socket_ingest_us_per_frame"])
    out["single_actor_env_steps_per_sec"] = rows * 1e6 / total_us
    return out


def fleet_table(artifact: dict) -> str:
    """Format a ``bench_fleet`` artifact (``fleet/sweep.py``) as the
    actor-scaling table: rows/s vs N with latency, losses, recovery."""
    header = (f"{'actors':>7} {'rows/s':>8} {'demand':>8} {'p50ms':>7} "
              f"{'p99ms':>7} {'drops':>7} {'sheds':>6} {'retry':>6} "
              f"{'crash':>6} {'readmit':>8} {'recov_s':>8}")
    lines = [header]
    for row in artifact["sweep"]:
        lat = row["send_latency_ms"]
        drops = row["drops"]
        rec = row["recovery"]
        lines.append(
            f"{row['n_actors']:>7} {row['rows_per_sec']:>8,.0f} "
            f"{row['demand_rows_per_sec']:>8,.0f} "
            f"{lat['p50'] if lat['p50'] is not None else float('nan'):>7.2f} "
            f"{lat['p99'] if lat['p99'] is not None else float('nan'):>7.2f} "
            f"{drops['chaos_rows'] + drops['backpressure_rows']:>7} "
            f"{drops['shed_rows']:>6} {row['retries']:>6} "
            f"{row['crashes']:>6} {row['readmissions']:>8} "
            + (f"{rec['mean_s']:>8.2f}" if rec["mean_s"] is not None
               else f"{'—':>8}"))
    shard = artifact.get("shard_sweep")
    if shard:
        lines.append("")
        lines.append(shard_table(shard))
    return "\n".join(lines)


def shard_table(shard: dict) -> str:
    """Format the ``shard_sweep`` block: rows/s vs ingest shards K at
    fixed N, with per-shard rate, speedup/efficiency vs K=1, and the
    margin over the priced single-core ceiling."""
    ceiling = shard.get("single_core_ceiling_rows_per_sec", 5200.0)
    header = (f"ingest shards @ N={shard['n_actors']} "
              f"(offered {shard['offered_rows_per_sec']:,.0f} rows/s, "
              f"ceiling {ceiling:,.0f}/core)\n"
              f"{'K':>3} {'codec':>6} {'rows/s':>8} {'per-shard':>10} "
              f"{'vs K=1':>7} {'eff':>6} {'vs ceil':>8} {'p99ms':>8} "
              f"{'deadlk':>7}")
    lines = [header]
    for row, sc in zip(shard["sweep"], shard["scaling"]):
        lat = row["send_latency_ms"]
        lines.append(
            f"{row['ingest_shards']:>3} {row['codec']:>6} "
            f"{row['rows_per_sec']:>8,.0f} "
            f"{sc['rows_per_sec_per_shard']:>10,.0f} "
            f"{sc['speedup_vs_k1'] if sc['speedup_vs_k1'] is not None else float('nan'):>6.2f}x "
            f"{sc['efficiency'] if sc['efficiency'] is not None else float('nan'):>6.2f} "
            f"{sc['vs_ceiling']:>7.2f}x "
            f"{lat['p99'] if lat['p99'] is not None else float('nan'):>8.2f} "
            f"{row['deadlocks']:>7}")
    return "\n".join(lines)


def plot_fleet(artifact: dict, out_png: str) -> str:
    """Rows/s-vs-N scaling curve (with the offered demand line) and p99
    send latency on a twin axis; returns the written path."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = artifact["sweep"]
    n = [r["n_actors"] for r in rows]
    rate = [r["rows_per_sec"] for r in rows]
    demand = [r["demand_rows_per_sec"] for r in rows]
    p99 = [r["send_latency_ms"]["p99"] for r in rows]
    fig, ax = plt.subplots(figsize=(7, 4.2))
    ax.plot(n, rate, "o-", label="ingested rows/s")
    ax.plot(n, demand, "--", color="gray", label="offered demand")
    ax.set_xscale("log", base=2)
    ax.set_xticks(n, [str(v) for v in n])
    ax.set_xlabel("actors (throttled sender lanes)")
    ax.set_ylabel("rows/s into the replay service")
    ax2 = ax.twinx()
    ax2.plot(n, p99, "s:", color="tab:red", label="p99 send latency")
    ax2.set_ylabel("p99 send latency (ms)")
    h1, l1 = ax.get_legend_handles_labels()
    h2, l2 = ax2.get_legend_handles_labels()
    ax.legend(h1 + h2, l1 + l2, loc="upper left")
    ax.set_title("Fleet plane scaling under chaos "
                 f"(seed {artifact['config']['chaos']['seed']})")
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)
    return out_png


def main(argv=None):
    ap = argparse.ArgumentParser(prog="d4pg_tpu.analysis.actor_scaling")
    ap.add_argument("--procs", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--env", default="point",
                    help="'point-slow:<ms>' emulates a physics-bound env "
                         "so the plane, not the host core, is measured")
    ap.add_argument("--num_envs", type=int, default=8)
    ap.add_argument("--budget", action="store_true",
                    help="measure the per-component frame budget instead "
                         "of the scaling table")
    ap.add_argument("--fleet", default=None, metavar="ARTIFACT_JSON",
                    help="render the fleet scaling table from a "
                         "bench_fleet artifact instead of measuring")
    ap.add_argument("--plot", default=None, metavar="OUT_PNG",
                    help="with --fleet: also write the scaling curve PNG")
    ns = ap.parse_args(argv)
    if ns.fleet:
        import json

        with open(ns.fleet) as f:
            artifact = json.load(f)
        print(fleet_table(artifact))
        if ns.plot:
            print(f"wrote {plot_fleet(artifact, ns.plot)}")
        return
    if ns.budget:
        budget = measure_budget()
        for key, val in budget.items():
            sval = f"{val:,.1f}" if isinstance(val, float) else str(val)
            print(f"{key:>34}: {sval}")
        return
    print(f"{'procs':>6} {'env-steps/sec':>14}")
    base = None
    for n in ns.procs:
        rate = measure(n, ns.seconds, env=ns.env, num_envs=ns.num_envs)
        base = base or rate
        print(f"{n:>6} {rate:>14.0f}   ({rate / base:.2f}x)")


if __name__ == "__main__":
    main()
