"""Measure local actor-process scaling: env-steps/sec vs --actor_procs.

The reference scales acting by forking N full worker processes sharing one
model in OS shared memory (``main.py:399-405``); here N spawned actor
processes stream transitions to the learner's TCP plane
(``train.py --actor_procs``). This tool boots ONLY the ingest plane (replay
service + transition receiver + weight server, no learner) and counts
arriving env steps over a fixed window:

    python -m d4pg_tpu.analysis.actor_scaling --procs 1 2 4 --seconds 10
"""

from __future__ import annotations

import argparse
import dataclasses
import multiprocessing as mp
import time


def measure(n_procs: int, seconds: float, env: str = "point",
            num_envs: int = 8, max_steps: int = 200) -> float:
    from d4pg_tpu.actor_main import run_local_actor_process
    from d4pg_tpu.config import ExperimentConfig
    from d4pg_tpu.distributed import ReplayService, WeightStore
    from d4pg_tpu.distributed.transport import TransitionReceiver
    from d4pg_tpu.distributed.weight_server import WeightServer
    from d4pg_tpu.replay import ReplayBuffer
    from d4pg_tpu.train import infer_dims

    cfg = ExperimentConfig(env=env, num_envs=num_envs, max_steps=max_steps,
                           v_min=-5.0, v_max=0.0)
    obs_dim, act_dim, obs_dtype = infer_dims(cfg)
    service = ReplayService(
        ReplayBuffer(1_000_000, obs_dim, act_dim, obs_dtype=obs_dtype))
    weights = WeightStore()
    receiver = TransitionReceiver(
        lambda b, aid, count: service.add(b, actor_id=aid,
                                          count_env_steps=count),
        host="127.0.0.1")
    weight_server = WeightServer(weights, host="127.0.0.1")

    ctx = mp.get_context("spawn")
    procs = []
    for i in range(n_procs):
        p = ctx.Process(
            target=run_local_actor_process,
            args=(dataclasses.replace(cfg, seed=1000 * (i + 1)), "127.0.0.1",
                  receiver.port, weight_server.port, f"scale-{i}", None),
            daemon=True,
        )
        p.start()
        procs.append(p)

    # let the fleet finish jax/env startup before the measurement window
    deadline = time.monotonic() + 120.0
    while service.env_steps < n_procs * num_envs and time.monotonic() < deadline:
        time.sleep(0.1)
    start_steps = service.env_steps
    t0 = time.monotonic()
    time.sleep(seconds)
    rate = (service.env_steps - start_steps) / (time.monotonic() - t0)

    for p in procs:
        p.terminate()
    for p in procs:
        p.join(timeout=5.0)
    receiver.close()
    weight_server.close()
    service.close()
    return rate


def main(argv=None):
    ap = argparse.ArgumentParser(prog="d4pg_tpu.analysis.actor_scaling")
    ap.add_argument("--procs", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--env", default="point")
    ap.add_argument("--num_envs", type=int, default=8)
    ns = ap.parse_args(argv)
    print(f"{'procs':>6} {'env-steps/sec':>14}")
    base = None
    for n in ns.procs:
        rate = measure(n, ns.seconds, env=ns.env, num_envs=ns.num_envs)
        base = base or rate
        print(f"{n:>6} {rate:>14.0f}   ({rate / base:.2f}x)")


if __name__ == "__main__":
    main()
