"""Offline analysis: run loggers and EWMA return plots.

Replaces the reference's ``plots/plots.py`` (CSV scan -> EWMA -> PNG) and
the ``plotUtil.ipynb`` ``Logger`` class (named-series dict logs with pickle
persistence and comparison plots) with importable, tested equivalents.
"""

from d4pg_tpu.analysis.ewma import ewma
from d4pg_tpu.analysis.logger import RunLogger
from d4pg_tpu.analysis.plots import load_returns_csv, plot_runs

__all__ = ["ewma", "RunLogger", "load_returns_csv", "plot_runs"]
