"""Vectorized exponentially-weighted moving average.

Parity: the reference's smoothing in ``plots/plots.py:6-21`` (vectorized
EWMA with bias-corrected warmup) and the 0.95/0.05 online tracking at
``main.py:131, 346``.
"""

from __future__ import annotations

import numpy as np


def ewma(x: np.ndarray, alpha: float = 0.95) -> np.ndarray:
    """Bias-corrected EWMA: y_t = (1-a) * sum_k a^k x_{t-k} / (1 - a^{t+1}).

    Matches the reference's formulation (scaling factors + cumulative
    offset, ``plots/plots.py:8-21``) without its O(T^2) scaling-matrix
    construction for long series.
    """
    x = np.asarray(x, np.float64)
    n = x.shape[0]
    if n == 0:
        return x.astype(np.float64)
    # recursive form, numerically robust for long series
    out = np.empty(n, np.float64)
    acc = 0.0
    for t in range(n):
        acc = alpha * acc + (1.0 - alpha) * x[t]
        out[t] = acc / (1.0 - alpha ** (t + 1))
    return out
