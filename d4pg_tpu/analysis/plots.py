"""EWMA return plots from run CSVs.

Parity: ``plots/plots.py:24-48`` — scan a directory for return CSVs, apply
EWMA smoothing, write ``<name>.png`` — generalized to overlay multiple runs
(the notebook's DDPG-vs-DistDDPG comparison, cell 1). Run as
``python -m d4pg_tpu.analysis.plots <run_dir> [<run_dir> ...]``.
"""

from __future__ import annotations

import csv
import os
import sys

import numpy as np

from d4pg_tpu.analysis.ewma import ewma


def load_returns_csv(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Read (step, avg_return[, ...]) rows; returns (steps, returns)."""
    steps, rets = [], []
    with open(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            try:
                step, ret = float(row[0]), float(row[1])
            except (ValueError, IndexError):
                continue  # header or malformed row
            steps.append(step)
            rets.append(ret)
    return np.asarray(steps), np.asarray(rets)


def plot_runs(
    runs: dict[str, tuple[np.ndarray, np.ndarray]],
    out_path: str,
    alpha: float = 0.95,
    title: str = "returns",
) -> str:
    """Overlay EWMA-smoothed return curves; writes a PNG, returns its path."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for name, (steps, rets) in sorted(runs.items()):
        if len(steps) == 0:
            continue
        ax.plot(steps, ewma(rets, alpha), label=name)
        ax.plot(steps, rets, alpha=0.2)
    ax.set_xlabel("learner step")
    ax.set_ylabel("avg test return (EWMA)")
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m d4pg_tpu.analysis.plots <run_dir> [...]")
        raise SystemExit(2)
    runs = {}
    for run_dir in argv:
        csv_path = os.path.join(run_dir, "returns.csv")
        if os.path.exists(csv_path):
            runs[os.path.basename(run_dir.rstrip("/"))] = load_returns_csv(csv_path)
        else:
            print(f"skip {run_dir}: no returns.csv")
    if not runs:
        print("error: no run dir contained a returns.csv")
        raise SystemExit(1)
    out = plot_runs(runs, out_path="returns.png")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
