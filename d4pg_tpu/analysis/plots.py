"""EWMA return plots from run CSVs.

Parity: ``plots/plots.py:24-48`` — scan a directory for return CSVs, apply
EWMA smoothing, write ``<name>.png`` — generalized to overlay multiple runs
(the notebook's DDPG-vs-DistDDPG comparison, cell 1). Run as
``python -m d4pg_tpu.analysis.plots <run_dir> [<run_dir> ...]``.
"""

from __future__ import annotations

import csv
import os
import sys

import numpy as np

from d4pg_tpu.analysis.ewma import ewma


def load_returns_csv(
    path: str, column: int = 1
) -> tuple[np.ndarray, np.ndarray]:
    """Read (step, avg_return[, ewma[, success_rate]]) rows; returns
    (steps, values) for the requested data ``column`` (1 = avg return,
    3 = success rate for sparse-reward/HER runs)."""
    steps, vals = [], []
    with open(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            try:
                step, val = float(row[0]), float(row[column])
            except (ValueError, IndexError):
                continue  # header, malformed, or column absent in old runs
            steps.append(step)
            vals.append(val)
    return np.asarray(steps), np.asarray(vals)


def plot_runs(
    runs: dict[str, tuple[np.ndarray, np.ndarray]],
    out_path: str,
    alpha: float = 0.95,
    title: str = "returns",
    ylabel: str | None = None,
) -> str:
    """Overlay EWMA-smoothed return curves; writes a PNG, returns its path."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 5))
    for name, (steps, rets) in sorted(runs.items()):
        if len(steps) == 0:
            continue
        ax.plot(steps, ewma(rets, alpha), label=name)
        ax.plot(steps, rets, alpha=0.2)
    ax.set_xlabel("learner step")
    ax.set_ylabel(ylabel or "avg test return (EWMA)")
    ax.set_title(title)
    ax.legend()
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path


def main(argv=None) -> None:
    argv = sys.argv[1:] if argv is None else argv
    success = "--success" in argv
    argv = [a for a in argv if a != "--success"]
    if not argv:
        print("usage: python -m d4pg_tpu.analysis.plots [--success] "
              "<run_dir> [...]")
        raise SystemExit(2)
    column = 3 if success else 1
    runs = {}
    for run_dir in argv:
        csv_path = os.path.join(run_dir, "returns.csv")
        if not os.path.exists(csv_path):
            print(f"skip {run_dir}: no returns.csv")
            continue
        steps, vals = load_returns_csv(csv_path, column=column)
        if len(steps) == 0:
            # e.g. --success against a pre-success-column CSV: surface it
            # instead of silently plotting an empty axes
            print(f"skip {run_dir}: no data in column {column}")
            continue
        runs[os.path.basename(run_dir.rstrip("/"))] = (steps, vals)
    if not runs:
        print("error: no run dir contained a returns.csv")
        raise SystemExit(1)
    out = plot_runs(
        runs,
        out_path="success.png" if success else "returns.png",
        title="success rate" if success else "returns",
        ylabel="eval success rate (EWMA)" if success else None,
    )
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
