"""Versioned weight distribution from learner to actors/evaluator.

Replaces the reference's shared-memory ``state_dict`` pulls
(``sync_local_global`` ``ddpg.py:118-120``; evaluator copy
``main.py:113-114``): the learner *publishes* actor params with a version
number; actors/evaluators *pull* when they see a newer version. Host-side
numpy copies keep the store process-agnostic (the same interface backs a
DCN broadcast: publish serializes once, subscribers fetch).
"""

from __future__ import annotations

import threading
from typing import Any

import jax
import numpy as np


class WeightStore:
    """Thread-safe versioned parameter store (single-writer, many-reader)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._version = 0
        self._params: Any = None
        self._step = 0
        self._norm_stats: tuple | None = None

    def publish(self, params: Any, step: int, to_host: bool = True,
                norm_stats: tuple | None = None) -> int:
        """Learner-side: publish new actor params. ``to_host=True`` pulls
        device arrays to host numpy (a BLOCKING D2H sync) so readers never
        hold device references. The fused learner path instead publishes
        ``to_host=False`` with an on-device copy: the copy dispatch is
        async, so back-to-back chunk dispatches never stall; in-process
        readers jit-apply device params directly, and host consumers (the
        TCP weight server) ``np.asarray`` lazily off the learner thread.
        Returns the new version."""
        host = (jax.tree_util.tree_map(lambda x: np.asarray(x), params)
                if to_host else params)
        with self._lock:
            self._version += 1
            self._params = host
            self._step = int(step)
            if norm_stats is not None:
                # (mean, std) snapshot of the replay-side obs normalizer;
                # piggybacked to remote actors by the WeightServer
                self._norm_stats = norm_stats
            return self._version

    @property
    def norm_stats(self) -> tuple | None:
        """Latest published (mean, std) acting statistics, or None when
        observation normalization is off. In-process readers holding the
        live RunningMeanStd ignore this; the TCP weight plane ships it."""
        with self._lock:
            return self._norm_stats

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def step(self) -> int:
        """Learner step at last publish (replaces the shared global_count,
        ``main.py:386``)."""
        with self._lock:
            return self._step

    def get(self) -> tuple[int, Any]:
        """Reader-side: (version, params) — params None until first publish."""
        with self._lock:
            return self._version, self._params

    def snapshot(self) -> tuple[int, Any, int]:
        """(version, params, step) read atomically — use when the caller
        needs the step the params were published at (e.g. eval lag
        accounting); reading ``.step`` separately can observe a newer
        publish."""
        with self._lock:
            return self._version, self._params, self._step

    def get_if_newer(self, have_version: int) -> tuple[int, Any] | None:
        with self._lock:
            if self._version > have_version:
                return self._version, self._params
            return None
