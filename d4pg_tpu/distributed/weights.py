"""Versioned weight distribution from learner to actors/evaluator.

Replaces the reference's shared-memory ``state_dict`` pulls
(``sync_local_global`` ``ddpg.py:118-120``; evaluator copy
``main.py:113-114``): the learner *publishes* actor params with a version
number; actors/evaluators *pull* when they see a newer version. Host-side
numpy copies keep the store process-agnostic (the same interface backs a
DCN broadcast: publish serializes once, subscribers fetch).

The store additionally carries the weight plane's crash-fencing state
(``weight_plane.py``): a **generation** (the PR-7 idiom — a restarted
learner's store is constructed at ``generation+1``, so version numbers
that rewind across a crash are disambiguated by the pair
``(generation, version)``) and a monotonic **publish timestamp** (the
anchor for the plane's pull→publish staleness histogram). Relays
republish upstream snapshots verbatim via ``publish_versioned`` —
version, step, generation and the ORIGINAL publish timestamp all pass
through, so staleness measured at a fan-out leaf is end-to-end, not
per-hop.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import numpy as np

from d4pg_tpu.core.locking import TieredLock


class WeightStore:
    """Thread-safe versioned parameter store (single-writer, many-reader).

    All state sits under one declared-tier lock (``wstore`` — the weight
    plane's innermost tier): a server's frame cache refreshes from the
    store while holding its own ``wserve`` cache lock, so the store lock
    must admit acquisition below it."""

    def __init__(self, generation: int = 0):
        self._store_lock = TieredLock("wstore")
        self._version = 0
        self._params: Any = None
        self._step = 0
        self._norm_stats: tuple | None = None
        self._generation = int(generation)
        self._published_ts = 0.0

    def publish(self, params: Any, step: int, to_host: bool = True,
                norm_stats: tuple | None = None) -> int:
        """Learner-side: publish new actor params. ``to_host=True`` pulls
        device arrays to host numpy (a BLOCKING D2H sync) so readers never
        hold device references. The fused learner path instead publishes
        ``to_host=False`` with an on-device copy: the copy dispatch is
        async, so back-to-back chunk dispatches never stall; in-process
        readers jit-apply device params directly, and host consumers (the
        TCP weight server) ``np.asarray`` lazily off the learner thread.
        Returns the new version."""
        host = (jax.tree_util.tree_map(lambda x: np.asarray(x), params)
                if to_host else params)
        now = time.monotonic()
        with self._store_lock:
            self._version += 1
            self._params = host
            self._step = int(step)
            self._published_ts = now
            if norm_stats is not None:
                # (mean, std) snapshot of the replay-side obs normalizer;
                # piggybacked to remote actors by the WeightServer
                self._norm_stats = norm_stats
            return self._version

    def publish_versioned(self, params: Any, version: int, step: int,
                          norm_stats: tuple | None = None,
                          generation: int | None = None,
                          publish_ts: float | None = None) -> None:
        """Relay-side: republish an UPSTREAM snapshot verbatim — version,
        generation and the original monotonic publish timestamp pass
        through unchanged (end-to-end staleness, not per-hop). Version
        may rewind when ``generation`` advances (a restarted learner
        publishes v1 of generation g+1); within a generation the relay's
        puller only hands over strictly newer versions."""
        now = time.monotonic()
        with self._store_lock:
            self._version = int(version)
            self._params = params
            self._step = int(step)
            self._published_ts = float(publish_ts) if publish_ts else now
            if norm_stats is not None:
                self._norm_stats = norm_stats
            if generation is not None:
                self._generation = int(generation)

    @property
    def norm_stats(self) -> tuple | None:
        """Latest published (mean, std) acting statistics, or None when
        observation normalization is off. In-process readers holding the
        live RunningMeanStd ignore this; the TCP weight plane ships it."""
        with self._store_lock:
            return self._norm_stats

    @property
    def version(self) -> int:
        with self._store_lock:
            return self._version

    @property
    def generation(self) -> int:
        """Crash-fencing generation (PR-7 idiom): bumped by constructing
        the restarted learner's store at ``generation+1``; rides every
        weight-plane frame so a relay can never serve a pre-crash
        version as current."""
        with self._store_lock:
            return self._generation

    @property
    def step(self) -> int:
        """Learner step at last publish (replaces the shared global_count,
        ``main.py:386``)."""
        with self._store_lock:
            return self._step

    def get(self) -> tuple[int, Any]:
        """Reader-side: (version, params) — params None until first publish."""
        with self._store_lock:
            return self._version, self._params

    def snapshot(self) -> tuple[int, Any, int]:
        """(version, params, step) read atomically — use when the caller
        needs the step the params were published at (e.g. eval lag
        accounting); reading ``.step`` separately can observe a newer
        publish."""
        with self._store_lock:
            return self._version, self._params, self._step

    def snapshot_ex(self) -> dict:
        """The weight plane's atomic read: version, params, step,
        generation, publish timestamp and norm stats under ONE lock
        round trip — a publish landing between separate reads would pair
        generation-g params with a generation-g+1 stamp, which is
        exactly the fencing breach the pair exists to prevent."""
        with self._store_lock:
            return {
                "version": self._version,
                "params": self._params,
                "step": self._step,
                "generation": self._generation,
                "published_ts": self._published_ts,
                "norm_stats": self._norm_stats,
            }

    def get_if_newer(self, have_version: int) -> tuple[int, Any] | None:
        with self._store_lock:
            if self._version > have_version:
                return self._version, self._params
            return None
