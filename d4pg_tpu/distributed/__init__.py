"""Distributed actor–learner runtime.

The reference's runtime is N fork'd worker processes that are each actor AND
learner, racing hogwild updates into shared memory (``main.py:371-405``,
SURVEY.md C15/C18). The TPU-native architecture decouples the roles per the
D4PG paper shape the reference only gestures at (SURVEY.md §2):

  - a single synchronous **learner** owning the replay buffer and the jit'd
    (sharded) update;
  - N **actors** that pull versioned weights and stream folded transitions
    into the learner's replay service — in-process threads on one host, or
    socket transport across TPU-VM hosts over DCN;
  - an **evaluator** that periodically copies weights and reports greedy
    returns with the reference's 0.95/0.05 EWMA (``main.py:131``);
  - heartbeats + stateless-restartable actors for failure detection
    (SURVEY.md §5 — the reference has none).
"""

from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.distributed.weight_plane import (
    WeightPlaneClient,
    WeightPlaneServer,
    WeightRelay,
    WeightWireChaos,
)
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.actor import ActorConfig, ActorWorker
from d4pg_tpu.distributed.evaluator import AsyncEvaluator, Evaluator
from d4pg_tpu.distributed.transport import (
    CoalescingSender,
    TransitionReceiver,
    TransitionSender,
)

__all__ = [
    "WeightStore",
    "WeightPlaneClient",
    "WeightPlaneServer",
    "WeightRelay",
    "WeightWireChaos",
    "ReplayService",
    "ActorConfig",
    "ActorWorker",
    "AsyncEvaluator",
    "Evaluator",
    "CoalescingSender",
    "TransitionReceiver",
    "TransitionSender",
]
