"""Weight distribution over the network (learner -> remote actors).

Completes the DCN plane: ``transport.py`` streams transitions actor ->
learner; this module broadcasts versioned actor params learner -> actors.
Same length-prefixed frame format, request/response over TCP:

  client sends  [u32 magic][i64 have_version]
  server replies[u32 magic][u32 len][payload]   (len==0: not newer)

payload = npz of the flattened param pytree + version + step. The treedef
is reconstructed client-side from sorted flat keys, so only arrays cross
the wire. Replaces the reference's shared-memory ``state_dict`` pulls
(``ddpg.py:118-120``, ``main.py:113-114``) for the cross-host case.

This module is the v1 (full-snapshot npz) protocol; the delta/quantized/
relay superset lives in ``weight_plane.py`` (``WeightPlaneServer``
answers BOTH magics on one port, so v1 clients never break). The serve
path memoizes the serialized frame by (version, codec) with single-flight
fill under the declared ``wserve`` tier lock: N pullers of version v cost
one flatten+savez, not N.
"""

from __future__ import annotations

import io
import socket
import threading
import time
import zipfile

import numpy as np

from d4pg_tpu.core.locking import TieredLock
# Frame shapes come from the declared wire registry (weights-v1 rows);
# see core/wire.py and ``python -m d4pg_tpu.lint --wire``.
from d4pg_tpu.core.wire import (
    MAGIC_WEIGHTS_V1 as _MAGIC,
    WEIGHTS_V1_REQ as _REQ,
    WEIGHTS_V1_RESP as _RESP,
)
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event


def _flatten(params) -> dict[str, np.ndarray]:
    """Flatten a nested dict pytree to {'a/b/c': array}. Delegates to
    ``partition.named_flat`` — the wire keys ARE the partition-rule key
    grammar, so the sharding table and the weight codec cannot drift."""
    from d4pg_tpu.parallel.partition import named_flat

    return named_flat(params)


def _unflatten(flat: dict[str, np.ndarray]):
    from d4pg_tpu.parallel.partition import named_unflat

    return named_unflat(flat)


from d4pg_tpu.distributed.transport import (
    MAX_PAYLOAD,
    ConnRegistry,
    ProtocolError,
    ReconnectingClient,
    _recv_exact,
    server_handshake,
)


class WeightServer(ConnRegistry):
    """Serves a WeightStore's latest params to remote pullers.

    Binds loopback by default (pass the DCN interface for cross-host
    fleets); optional shared ``secret`` gates pullers with the same
    HMAC handshake as the transition plane."""

    def __init__(self, store: WeightStore, host: str = "127.0.0.1",
                 port: int = 0, secret: str | None = None):
        super().__init__()
        self._store = store
        self._secret = secret
        # Frame memo, guarded by the declared ``wserve`` tier lock
        # (above ``wstore``: the fill path snapshots the store while
        # holding it). Holding the lock ACROSS the fill is the
        # single-flight: concurrent pullers of the same version block on
        # the lock and find the finished frame, instead of each paying
        # flatten+savez. Keyed (version, codec) — v1 has one codec, the
        # plane subclass reuses the same lock for its per-codec caches.
        self._frame_lock = TieredLock("wserve")
        self._frame_memo: tuple[tuple[int, str], bytes] | None = None
        self.frame_encodes = 0  # fills (cache misses); serves can exceed it
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen()
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._conn_threads: list[threading.Thread] = []
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def _accept(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    self._server.settimeout(0.2)
                    conn, _ = self._server.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                self._register_conn(conn)
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
                t = threading.Thread(target=self._serve, args=(conn,),
                                     daemon=True)
                self._conn_threads.append(t)
                t.start()
        except Exception as e:
            contained_crash("weights.accept", e)

    def _legacy_frame(self, have: int) -> bytes | None:
        """The memoized v1 response body for a puller at ``have``: None
        when nothing newer exists, else the (version, 'npz')-keyed npz
        frame — filled single-flight under ``_frame_lock``."""
        with self._frame_lock:
            # snapshot_ex() reads (version, params, step, norm) under one
            # store lock: a publish landing between separate reads would
            # stamp step-N params with a newer step, corrupting the
            # client's staleness accounting.
            snap = self._store.snapshot_ex()
            version, params = snap["version"], snap["params"]
            if params is None or version <= have:
                return None
            key = (version, "npz")
            if self._frame_memo is not None and self._frame_memo[0] == key:
                return self._frame_memo[1]
            flat = _flatten(params)
            norm = snap["norm_stats"]
            if norm is not None:
                # piggyback acting statistics (obs normalization):
                # remote actors must standardize policy inputs with
                # the same stats the learner's replay rows use
                flat["__norm_mean__"] = np.asarray(norm[0])
                flat["__norm_std__"] = np.asarray(norm[1])
                if len(norm) > 2:  # clip radius travels with stats
                    flat["__norm_clip__"] = np.float64(norm[2])
            buf = io.BytesIO()
            np.savez(
                buf,
                __version__=np.int64(version),
                __step__=np.int64(snap["step"]),
                **flat,
            )
            payload = buf.getvalue()
            self._frame_memo = (key, payload)
            self.frame_encodes += 1
            return payload

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        except Exception as e:
            contained_crash("weights.serve", e)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                if not server_handshake(conn, self._secret):
                    return
                while not self._stop.is_set():
                    req = _recv_exact(conn, _REQ.size)
                    if req is None:
                        return
                    magic, have = _REQ.unpack(req)
                    if magic != _MAGIC:
                        return
                    payload = self._legacy_frame(have)
                    if payload is None:
                        conn.sendall(_RESP.pack(_MAGIC, 0))
                        continue
                    conn.sendall(_RESP.pack(_MAGIC, len(payload)) + payload)
        except OSError:
            return  # peer died mid-frame (actor terminated); drop it
        finally:
            self._unregister_conn(conn)

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        self._shutdown_conns()
        # Join conn threads so their teardown work (the plane subclass
        # sheds in-flight trace spans in its _serve finally) completes
        # before close() returns — otherwise a trace snapshot taken
        # right after close() races the sweeps and reports orphans.
        for t in self._conn_threads:
            t.join(timeout=2.0)
        self._conn_threads.clear()


class WeightClient(ReconnectingClient):
    """Actor-side puller mirroring the WeightStore reader interface, so a
    remote actor constructs its WeightStore-shaped view from the wire.

    Degrades to STALE weights while the learner is down (VERDICT r3 #5):
    a failed pull drops the socket and returns None — "nothing newer" —
    so the actor keeps acting on its last weights instead of crashing;
    each subsequent pull attempts one quick reconnect. Only after
    ``down_timeout`` seconds of continuous unreachability does it raise
    (a permanently-gone learner should stop the fleet, not spin it on
    stale policies forever). Deterministic wire-format violations
    (``ProtocolError``: bad magic, oversized payload) are NOT absorbed —
    they surface at the first frame, since reconnecting cannot heal a
    version/config fault. The initial connect fails fast, surfacing
    config errors at startup.

    Stale-degradation entry/exit is recorded on the flight-recorder ring
    (``weight_stale_enter``/``weight_stale_exit``), so a silent-stale
    period shows up in a chaos postmortem with its duration instead of
    leaving a gap between ordinary pull events."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0,
                 secret: str | None = None, down_timeout: float = 300.0,
                 reconnect_interval: float = 10.0):
        self._down_timeout = down_timeout
        self._down_since: float | None = None
        self._ever_pulled = False
        # reconnects are rate-limited: the pull runs ON the acting thread,
        # and against a black-holing peer (no RST — e.g. a rebooting VM)
        # each attempt blocks for up to connect_timeout. At most one
        # blocked attempt per interval; pulls in between return None
        # immediately so rollouts continue on stale weights.
        self._reconnect_interval = reconnect_interval
        self._next_reconnect = 0.0
        super().__init__(host, port, connect_timeout, secret)
        self.step = 0
        self.norm_stats: tuple | None = None  # (mean, std) when served

    def get_if_newer(self, have_version: int):
        with self._lock:
            self._check_open()
            if (self._sock is None and self._ever_pulled
                    and time.monotonic() < self._next_reconnect):
                return None  # between rate-limited reconnect attempts
            try:
                if self._sock is None:
                    self._next_reconnect = (time.monotonic()
                                            + self._reconnect_interval)
                    self._connect()
                payload = self._pull(have_version)
                # the server ANSWERED (even "nothing newer"): the secret
                # and protocol are good, stale-degradation is armed
                self._ever_pulled = True
                if self._down_since is not None:
                    record_event("weight_stale_exit",
                                 addr=f"{self._addr[0]}:{self._addr[1]}",
                                 down_s=round(
                                     time.monotonic() - self._down_since, 3))
                self._down_since = None
            except ProtocolError:
                self._drop_sock()
                raise
            except (OSError, ConnectionError):
                self._drop_sock()
                self._check_open()
                if not self._ever_pulled:
                    # no pull has EVER succeeded — there are no stale
                    # weights to act on, and a server that drops a fresh
                    # connection before its first answer is a config/auth
                    # fault (e.g. wrong --secret: the handshake rejection
                    # looks like a close from here). Fail fast.
                    raise
                now = time.monotonic()
                if self._down_since is None:
                    self._down_since = now
                    record_event("weight_stale_enter",
                                 addr=f"{self._addr[0]}:{self._addr[1]}",
                                 have_version=int(have_version))
                if now - self._down_since > self._down_timeout:
                    raise ConnectionError(
                        f"weight server unreachable for "
                        f"{self._down_timeout:.0f}s at "
                        f"{self._addr[0]}:{self._addr[1]}")
                return None  # act on stale weights; retry next pull
        if payload is None:
            return None
        try:
            with np.load(io.BytesIO(payload)) as z:
                flat = {k: z[k] for k in z.files if not k.startswith("__")}
                version = int(z["__version__"])
                step = int(z["__step__"])
                norm: tuple | None = None
                if "__norm_mean__" in z.files:
                    norm = (z["__norm_mean__"], z["__norm_std__"])
                    if "__norm_clip__" in z.files:
                        norm += (float(z["__norm_clip__"]),)
        except (ValueError, KeyError, OSError, zipfile.BadZipFile) as e:
            # hostile-but-well-framed body (garbage npz bytes, missing
            # __version__/__step__ members): a deterministic protocol
            # fault, not downtime — drop the socket so the next pull
            # reconnects instead of reading a desynced stream, and
            # surface it like every other wire-format violation.
            with self._lock:
                self._drop_sock()
            raise ProtocolError(f"corrupt weight payload: {e}") from e
        # commit only after the whole body parsed: a torn parse must not
        # leave self.step ahead of the weights the actor is acting on
        self.step = step
        if norm is not None:
            self.norm_stats = norm
        return version, _unflatten(flat)

    def _pull(self, have_version: int) -> bytes | None:
        """One request/response on the live socket; raises on any break."""
        self._sock.sendall(_REQ.pack(_MAGIC, int(have_version)))
        head = _recv_exact(self._sock, _RESP.size)
        if head is None:
            raise ConnectionError("weight server closed the connection")
        magic, length = _RESP.unpack(head)
        if magic != _MAGIC or length > MAX_PAYLOAD:
            raise ProtocolError("corrupt weight stream")
        if length == 0:
            return None
        payload = _recv_exact(self._sock, length)
        if payload is None:
            raise ConnectionError("truncated weight payload")
        return payload
