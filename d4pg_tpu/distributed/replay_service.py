"""Replay service: the learner-side ingest point for actor transitions.

Replaces the reference's per-process private replay buffers (each hogwild
worker kept its own, ``ddpg.py:78-89``) with ONE central service the actors
stream into — the D4PG-paper architecture. Ingest is a bounded queue drained
by a background thread, so actor `add` calls never block the learner's
sample path; heartbeats give the failure detection the reference lacks
(SURVEY.md §5: "a dead worker just ends").
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np

from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch


class ReplayService:
    def __init__(
        self,
        buffer: ReplayBuffer,
        ingest_capacity: int = 256,
        heartbeat_timeout: float = 30.0,
        obs_norm=None,
        shed_watermark: float | None = None,
    ):
        """``shed_watermark`` (fraction of ``ingest_capacity``, fleet-plane
        degradation): when the ingest queue stands at or above the
        watermark, ``add`` sheds the OLDEST queued batch to admit the
        newest instead of blocking the caller — a stalled drain degrades
        the replay distribution (newest-biased, counted in ``sheds``/
        ``shed_rows``) rather than wedging 256 receiver threads. None
        (default) keeps the block-or-False contract of the training
        loop."""
        self.buffer = buffer
        # Optional RunningMeanStd (envs/normalizer.py). The drain thread is
        # the SINGLE writer: it folds every ingested row (local, spawned or
        # remote actors alike — they all stream RAW observations) into the
        # statistics and inserts the rows normalized, so the learner only
        # ever samples standardized data. Actors receive read-only
        # statistics for their policy input via the weight channel.
        self.obs_norm = obs_norm
        self._queue: queue.Queue = queue.Queue(maxsize=ingest_capacity)
        self._env_steps = 0
        self._lock = threading.Lock()
        # Guards ALL buffer mutation/reads: the drain thread's add() races
        # the learner thread's sample()/update_priorities() otherwise
        # (segment-tree aggregates are multi-word updates).
        self._buffer_lock = threading.Lock()
        # Batches accepted into the queue but not yet inserted; counted on
        # the producer side so flush() can't slip through the window between
        # queue-pop and buffer insert.
        self._pending = 0
        self._heartbeats: dict[str, float] = {}
        self._heartbeat_timeout = heartbeat_timeout
        # Fleet-plane degradation + recovery state (all under self._lock):
        # evicted actors are remembered so a resumed heartbeat RE-ADMITS
        # them (and records the outage length) instead of counting them
        # dead forever; shed counters surface every dropped batch.
        self._shed_at = (
            None if shed_watermark is None
            else max(1, min(ingest_capacity,
                            int(shed_watermark * ingest_capacity))))
        self._evicted: dict[str, float] = {}
        self._recovery_s: list[float] = []
        self.sheds = 0
        self.shed_rows = 0
        self.evictions = 0
        self.readmissions = 0
        self._stop = threading.Event()
        self._drain_thread = threading.Thread(target=self._drain, daemon=True)
        self._drain_thread.start()

    # -- actor-facing ------------------------------------------------------
    def add(self, batch: TransitionBatch, actor_id: str = "local",
            block: bool = True, timeout: float | None = 5.0,
            count_env_steps: bool = True) -> bool:
        """Enqueue transitions (backpressure via the bounded queue). Returns
        False if the queue stayed full past ``timeout``.

        ``count_env_steps=False`` for rows that do not correspond to fresh
        environment interaction (HER relabels) — otherwise the env_steps
        counter inflates by (1 + her_ratio)x in HER runs.

        With a ``shed_watermark`` configured, ``add`` NEVER blocks: a
        queue at the watermark sheds its oldest batch (counted) to admit
        this one, and the call returns True."""
        self.heartbeat(actor_id)
        if batch.obs.shape[0] == 0:
            return True
        with self._lock:
            self._pending += 1
        item = (actor_id, batch, count_env_steps)
        if self._shed_at is not None:
            return self._put_shedding(item)
        try:
            self._queue.put(item, block=block, timeout=timeout)
            return True
        except queue.Full:
            with self._lock:
                self._pending -= 1
            return False

    def _put_shedding(self, item) -> bool:
        """Admit ``item``, shedding the oldest queued batch while the queue
        stands at/above the watermark — bounded work, never blocks."""
        while True:
            if self._queue.qsize() < self._shed_at:
                try:
                    self._queue.put_nowait(item)
                    return True
                except queue.Full:
                    pass  # racing producers filled it; fall through to shed
            try:
                _aid, old_batch, _cnt = self._queue.get_nowait()
            except queue.Empty:
                continue  # the drain thread beat us to it; retry the put
            with self._lock:
                self.sheds += 1
                self.shed_rows += old_batch.obs.shape[0]
                self._pending -= 1  # shed batches never reach the drain

    def heartbeat(self, actor_id: str) -> None:
        now = time.monotonic()
        with self._lock:
            evicted_at = self._evicted.pop(actor_id, None)
            if evicted_at is not None:
                # the actor came back: re-admit and record the outage
                self.readmissions += 1
                if len(self._recovery_s) < 10_000:
                    self._recovery_s.append(now - evicted_at)
            self._heartbeats[actor_id] = now

    # -- learner-facing ----------------------------------------------------
    def sample(self, batch_size: int, beta: float = 0.4,
               weight_base: float | None = None):
        """PER: (batch, weights, idx, generation); uniform: batch. Mirrors
        the learner's buffer-kind dispatch (``ddpg.py:187-197``); the
        generation snapshot guards the priority write-back against the
        drain thread overwriting a sampled slot in flight."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                batch, w, idx = self.buffer.sample(
                    batch_size, beta=beta, weight_base=weight_base)
                return batch, w, idx, self.buffer.generation[idx].copy()
            return self.buffer.sample(batch_size)

    def sample_chunk(self, k: int, batch_size: int, beta: float = 0.4,
                     weight_base: float | None = None):
        """K stacked batches in one storage gather: (batches [K, B, ...],
        weights-or-None, idx [K, B], generation-or-None [K, B]) — the
        K-updates-per-dispatch sample path (``learner/pipeline.py``). The
        generation snapshot lets the deferred priority write-back skip
        slots the drain thread overwrote in flight."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                batches, w, idx = self.buffer.sample_chunk(
                    k, batch_size, beta=beta, weight_base=weight_base)
                return batches, w, idx, self.buffer.generation[idx].copy()
            batches, _, idx = self.buffer.sample_chunk(k, batch_size)
            return batches, None, idx, None

    def weight_base(self) -> float | None:
        """The local shard's IS-weight base ``z`` (see
        ``PrioritizedReplayBuffer.weight_base``); None for uniform replay."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                return self.buffer.weight_base()
            return None

    def update_priorities(
        self,
        idx: np.ndarray,
        priorities: np.ndarray,
        generation: np.ndarray | None = None,
    ) -> None:
        if isinstance(self.buffer, PrioritizedReplayBuffer):
            with self._buffer_lock:
                self.buffer.update_priorities(idx, priorities,
                                              generation=generation)

    def drain_device(self) -> int:
        """Flush ALL rows staged by a fused-path buffer
        (``replay/fused_buffer.py``) onto the device. Called by the
        LEARNER thread at cycle/chunk boundaries — it is the single owner
        of the device handles, so the drain thread's ``add`` only stages
        host rows and never dispatches device work."""
        drain = getattr(self.buffer, "drain", None)
        if drain is None:
            return 0
        with self._buffer_lock:
            return drain()

    def ingest_commit(self) -> int:
        """Land the in-flight staged block (one jitted ring-write + tree
        insert dispatch; no explicit H2D). Learner thread, called right
        BEFORE a fused-chunk dispatch so the chunk samples the freshest
        rows. No-op (0) for buffers without the block-drain API."""
        commit = getattr(self.buffer, "commit_staged", None)
        if commit is None:
            return 0
        with self._buffer_lock:
            return commit()

    def ingest_stage(self) -> int:
        """Start the H2D transfer of the next staged block (ONE
        ``jax.device_put``). Learner thread, called right AFTER a fused
        chunk is dispatched so the transfer overlaps the chunk's compute
        — the ≤ 1 explicit-H2D-per-chunk schedule
        (``learner/pipeline.IngestOverlap``). Falls back to a full
        synchronous drain for buffers without the block API (sharded
        fused replay), preserving the old per-chunk semantics there."""
        stage = getattr(self.buffer, "stage_block", None)
        if stage is None:
            return self.drain_device()
        with self._buffer_lock:
            return stage()

    def replay_state(self) -> dict:
        """Buffer contents + priorities for checkpointing (learner
        thread; SURVEY.md §5 elastic recovery)."""
        with self._buffer_lock:
            return self.buffer.state_dict()

    def load_replay_state(self, d: dict) -> None:
        with self._buffer_lock:
            self.buffer.load_state_dict(d)

    @property
    def env_steps(self) -> int:
        with self._lock:
            return self._env_steps

    def set_env_steps(self, n: int) -> None:
        """Seed the env-step counter (checkpoint resume)."""
        with self._lock:
            self._env_steps = int(n)

    def __len__(self) -> int:
        with self._buffer_lock:
            return len(self.buffer)

    def wait_until(self, min_size: int, timeout: float = 300.0) -> bool:
        """Block until the buffer holds ``min_size`` transitions (warmup
        gate, ``main.py:200-207``)."""
        deadline = time.monotonic() + timeout
        while len(self.buffer) < min_size:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def dead_actors(self) -> list[str]:
        """Actors currently considered dead: heartbeat-stale ones plus the
        evicted-and-not-yet-returned set. An evicted actor that resumes
        heartbeating (or streaming — ``add`` heartbeats) is RE-ADMITTED by
        ``heartbeat`` and drops out of this list; before that fix an
        eviction was permanent and a restarted actor with the same id
        stayed counted dead forever."""
        now = time.monotonic()
        with self._lock:
            stale = [
                a for a, t in self._heartbeats.items()
                if now - t > self._heartbeat_timeout
            ]
            return stale + [a for a in self._evicted if a not in stale]

    def evict_dead(self) -> list[str]:
        """Move heartbeat-stale actors into the evicted set (their next
        heartbeat re-admits them and records the outage as a recovery
        sample). Returns the newly evicted ids. Called periodically by the
        fleet monitor; idempotent between actor state changes."""
        now = time.monotonic()
        with self._lock:
            stale = [
                a for a, t in self._heartbeats.items()
                if now - t > self._heartbeat_timeout
            ]
            for a in stale:
                del self._heartbeats[a]
                self._evicted[a] = now
                self.evictions += 1
            return stale

    def evicted_actors(self) -> list[str]:
        with self._lock:
            return list(self._evicted)

    def ingest_stats(self) -> dict:
        """Degradation/recovery counters for the fleet plane: sheds,
        evictions, re-admissions, recovery times, live queue depth."""
        with self._lock:
            return {
                "env_steps": self._env_steps,
                "pending": self._pending,
                "queue_depth": self._queue.qsize(),
                "sheds": self.sheds,
                "shed_rows": self.shed_rows,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "recovery_s": list(self._recovery_s),
                "live_actors": len(self._heartbeats),
                "evicted": len(self._evicted),
            }

    # -- internals ---------------------------------------------------------
    # Max batches folded into one coalesced insert pass: bounds the lock
    # hold (the learner's sample path waits on the same lock) while still
    # amortizing it ~64x under a streaming fleet.
    _COALESCE = 64

    def _drain(self) -> None:
        while not self._stop.is_set():
            try:
                batches = [self._queue.get(timeout=0.1)]
            except queue.Empty:
                continue
            # Coalesce: take everything already queued (up to _COALESCE)
            # so a streaming fleet pays ONE lock acquisition and one
            # normalizer fold per group instead of per actor send — the
            # ingest plane's host-side amortization, matching the
            # block-granular device drain downstream.
            while len(batches) < self._COALESCE:
                try:
                    batches.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            try:
                if self.obs_norm is not None:
                    # Only obs rows feed the estimator; next_obs is
                    # normalized but never folded in. The episode-FINAL
                    # next_obs is thereby excluded — intentional: there is
                    # no row-level marker for "truly final" here (done=1
                    # tags every n-step fold of a terminal AND HER success
                    # relabels mid-trajectory, so done-gating would weight
                    # terminal-adjacent states 2-5x instead), and the
                    # omission is one state in T per episode. Stats fold
                    # BEFORE any of the group's rows are normalized, in
                    # arrival order — same estimator as the per-batch loop.
                    for j, (aid, batch, cnt) in enumerate(batches):
                        self.obs_norm.update(batch.obs)
                        batches[j] = (aid, batch._replace(
                            obs=self.obs_norm.normalize(batch.obs),
                            next_obs=self.obs_norm.normalize(batch.next_obs),
                        ), cnt)
                with self._buffer_lock:
                    for _aid, batch, _cnt in batches:
                        self.buffer.add(batch)
            finally:
                with self._lock:
                    for _, batch, count in batches:
                        if count:
                            self._env_steps += batch.obs.shape[0]
                    self._pending -= len(batches)

    def flush(self, timeout: float = 5.0) -> None:
        """Block until every accepted batch has been inserted."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return
            time.sleep(0.005)

    def close(self) -> None:
        self.flush()
        self._stop.set()
        self._drain_thread.join(timeout=2.0)
