"""Replay service: the learner-side ingest point for actor transitions.

Replaces the reference's per-process private replay buffers (each hogwild
worker kept its own, ``ddpg.py:78-89``) with ONE central service the actors
stream into — the D4PG-paper architecture. Ingest is bounded queues drained
by background workers, so actor `add` calls never block the learner's
sample path; heartbeats give the failure detection the reference lacks
(SURVEY.md §5: "a dead worker just ends").

Sharded ingest plane (``num_ingest_shards=K``; docs/architecture.md
"Sharded receiver"): admission, decode and staging are partitioned across
K shards so the receiver host can spend K cores on the frame path instead
of one. Ownership model:

  - an **ingest shard** owns: its bounded admission deque, its shed
    watermark and shed/decode counters, and one worker thread. Everything
    a shard owns is guarded by that shard's single condition variable —
    counter and queue mutate under the SAME lock, so a shard snapshot is
    always consistent. Frame decode (``transport.decode_frame``) and the
    fused path's column-major staging run on the shard worker.
  - the **commit thread** (the single writer of replay state) merges the
    shard outputs back into ONE coherent buffer: every admitted batch
    carries a global admission ticket ``seq``; the commit thread inserts
    strictly in ``seq`` order (shed or undecodable tickets are tombstoned
    so the merge never stalls on them), folds the observation normalizer
    in that same order (single-writer invariant preserved), and takes the
    buffer lock once per merged group. At K=1 this degenerates to exactly
    the old single-drain behavior: one queue, arrival order, same
    counters.
  - the **learner thread** stays the single owner of device handles
    (``stage_block``/``commit_staged``), exactly as before.

Lock order: every lock here is a ``core.locking`` tiered object from the
ONE declared hierarchy (service > buffer > commit > shard > ring;
monotone tier descent per thread). A shard condition is a LEAF lock —
neither the buffer lock, the service lock nor the merge condition may be
acquired while holding one. The commit thread acquires ``_buffer_lock``
and ``_lock`` sequentially, never nested inside a shard condition. The
discipline is enforced three ways: syntactically by the ``lock-order``
jaxlint rule, interprocedurally by the ``lock-cycle`` lock-graph pass
(``python -m d4pg_tpu.lint --locks``), and at runtime by the tier
assertions the fleet chaos smoke runs with (``core/locking.py``).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from d4pg_tpu.core.locking import TieredCondition, TieredLock
from d4pg_tpu.distributed.transport import decode_frame, raw_frame_meta_ex
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import EVENT_ADMISSION_REJECT, record_event
from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.obs.trace import RECORDER as _tracer
from d4pg_tpu.replay.prioritized import PrioritizedReplayBuffer
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch

# Seconds the ordered merge may make zero progress while shard output is
# waiting before it skips ahead to the smallest ready ticket (counted in
# ``order_breaks``). A lost ticket is a bug, but the fleet-plane rule is
# degrade-and-count, never wedge.
_ORDER_GRACE_S = 5.0


class _IngestShard:
    """One ingest shard: admission deque + counters, all owned by ``cond``.

    The worker thread and producers synchronize ONLY through ``cond``:
    producers wait on it for space (blocking mode) and the worker notifies
    after popping; counters mutate under the same lock as the queue they
    describe, so ``snapshot()`` is consistent by construction."""

    __slots__ = ("idx", "capacity", "shed_at", "cond", "q", "sheds",
                 "shed_rows", "decode_errors", "rows_in", "staged_rows",
                 "admit_fails", "sheds_by_class")

    def __init__(self, idx: int, capacity: int, shed_at: int | None):
        self.idx = idx
        self.capacity = capacity
        self.shed_at = shed_at
        self.cond = TieredCondition("shard")
        # class-attributed shed ledger (elastic admission): class name
        # -> rows shed; written under ``cond`` with the queue it
        # describes, like every other shard counter
        self.sheds_by_class: dict = {}
        # items: (seq, data, codec, actor_id, rows, count, trace); codec
        # None means ``data`` is an already-decoded TransitionBatch, else
        # it is the undecoded wire payload for ``decode_frame(data,
        # codec)``. ``trace`` is the sampled frame's trace id (or None)
        # riding the item so every later stage can stamp its span.
        self.q: deque = deque()
        self.sheds = 0
        self.shed_rows = 0
        self.decode_errors = 0
        self.rows_in = 0
        self.staged_rows = 0
        self.admit_fails = 0  # rejected admissions (full past timeout)

    def snapshot(self) -> dict:
        with self.cond:
            return {
                "shard": self.idx,
                "queue_depth": len(self.q),
                "sheds": self.sheds,
                "shed_rows": self.shed_rows,
                "decode_errors": self.decode_errors,
                "rows_in": self.rows_in,
                "staged_rows": self.staged_rows,
                "admit_fails": self.admit_fails,
                "capacity": self.capacity,
                "shed_at": self.shed_at,
                "sheds_by_class": dict(self.sheds_by_class),
            }


def _merge_class_counts(dicts) -> dict:
    """Sum per-shard ``sheds_by_class`` ledgers into one fleet view."""
    out: dict = {}
    for d in dicts:
        for k, v in d.items():
            out[k] = out.get(k, 0) + v
    return out


class ReplayService:
    def __init__(
        self,
        buffer: ReplayBuffer,
        ingest_capacity: int = 256,
        heartbeat_timeout: float = 30.0,
        obs_norm=None,
        shed_watermark: float | None = None,
        num_ingest_shards: int = 1,
        generation: int = 0,
        admission=None,
    ):
        """``shed_watermark`` (fraction of ``ingest_capacity``, fleet-plane
        degradation): when an ingest shard's deque stands at or above the
        watermark, ``add`` sheds the OLDEST queued batch to admit the
        newest instead of blocking the caller — a stalled drain degrades
        the replay distribution (newest-biased, counted in ``sheds``/
        ``shed_rows``) rather than wedging 256 receiver threads. None
        (default) keeps the block-or-False contract of the training
        loop. ``ingest_capacity`` and the watermark are PER SHARD, so
        K=1 semantics are bit-compatible with the old single queue."""
        self.buffer = buffer
        # Optional RunningMeanStd (envs/normalizer.py). The COMMIT thread
        # is the SINGLE writer: it folds every ingested row (local,
        # spawned or remote actors alike — they all stream RAW
        # observations) into the statistics in admission-ticket order and
        # inserts the rows normalized, so the learner only ever samples
        # standardized data. Actors receive read-only statistics for
        # their policy input via the weight channel.
        self.obs_norm = obs_norm
        self.num_ingest_shards = max(1, int(num_ingest_shards))
        buf_shards = getattr(buffer, "ingest_shards", 1)
        if buf_shards not in (1, self.num_ingest_shards):
            # a mismatched sharded buffer would hand one staging ring two
            # pushing workers with interleaved tickets, breaking the
            # per-ring ticket-ascending assumption of the merge commit
            raise ValueError(
                f"buffer.ingest_shards={buf_shards} must be 1 or match "
                f"num_ingest_shards={self.num_ingest_shards}")
        self._env_steps = 0
        # Rows landed in replay state, counted ONCE at commit time for
        # both the buffer-insert and direct-stage paths (the registry's
        # no-double-count ledger; see _insert_group).
        self._rows_committed = 0
        # Crash-recovery plane (all under self._lock): the service
        # generation id. Raw frames stamped with an OLDER generation are
        # fenced at admission — they were encoded against a pre-crash
        # service and may duplicate rows already inside the restored
        # snapshot (transport.py "Generation extension"). restore() bumps
        # past the snapshot's generation; a supervisor restarting WITHOUT
        # a snapshot passes ``generation`` explicitly.
        self._generation = int(generation)
        self._fenced_frames = 0
        self._fenced_rows = 0
        self._lock = TieredLock("service")
        # Guards ALL buffer mutation/reads: the commit thread's insert
        # races the learner thread's sample()/update_priorities()
        # otherwise (segment-tree aggregates are multi-word updates).
        self._buffer_lock = TieredLock("buffer")
        # Sample-on-ingest dealer (replay/sampler.SampleDealer), attached
        # via attach_dealer. Written under _buffer_lock; the replica-side
        # readers (queue_writeback) take a benign set-once atomic read —
        # forcing them through the buffer lock would reintroduce the very
        # contention the dealer removes.
        self._dealer = None
        # Batches accepted into a shard but not yet committed; counted on
        # the producer side so flush() can't slip through the window
        # between queue-pop and buffer insert.
        self._pending = 0
        self._heartbeats: dict[str, float] = {}
        self._owner: dict[str, int] = {}  # actor -> owning ingest shard
        self._heartbeat_timeout = heartbeat_timeout
        # Fleet-plane degradation + recovery state (all under self._lock):
        # evicted actors are remembered so a resumed heartbeat RE-ADMITS
        # them (and records the outage length) instead of counting them
        # dead forever; shed counters surface every dropped batch.
        shed_at = (
            None if shed_watermark is None
            else max(1, min(ingest_capacity,
                            int(shed_watermark * ingest_capacity))))
        self._shed_at = shed_at
        # watermark FRACTION retained so set_ingest_depth (the elastic
        # autoscaler's actuator) can recompute shed_at when it resizes
        # the shard deques live
        self._shed_watermark = shed_watermark
        # Optional elastic.AdmissionPolicy: priority-tagged shedding.
        # None (default) keeps the flat shed-oldest behavior bit-for-bit;
        # with a policy the shed victim is the oldest batch of the WORST
        # queued class, and every shed/reject is class-attributed in
        # sheds_by_class. Frozen/stateless, so sharing it across shard
        # conditions adds no lock edge.
        self._admission = admission
        self.evictions = 0
        self.readmissions = 0
        self._evicted: dict[str, float] = {}
        self._recovery_s: list[float] = []
        self._shards = [
            _IngestShard(i, int(ingest_capacity), shed_at)
            for i in range(self.num_ingest_shards)
        ]
        # The fused direct-stage fast path: shard workers copy rows
        # straight into the buffer's per-shard staging ring (thread-safe
        # by ring ownership — see replay/staging.MultiRingStaging) and
        # the commit thread only does the ordered accounting. Requires a
        # shard-aware buffer and no normalizer (the fold must stay
        # ticket-ordered on the single writer).
        self._direct_stage = (
            self.num_ingest_shards > 1 and obs_norm is None
            and getattr(buffer, "ingest_shards", 1) > 1
            and hasattr(buffer, "add_sharded"))
        # Ordered merge state, all under _commit_cond: per-shard output
        # deques (seq-ascending by construction), tombstoned tickets, and
        # the next ticket to commit.
        self._commit_cond = TieredCondition("commit")
        self._out: list[deque] = [deque() for _ in self._shards]
        self._skip: set[int] = set()
        self._next_seq = 0
        self._seq = itertools.count()
        self.order_breaks = 0
        self._stop = threading.Event()
        self._workers = [
            threading.Thread(target=self._worker, args=(s,), daemon=True,
                             name=f"ingest-shard-{s.idx}")
            for s in self._shards
        ]
        self._commit_thread = threading.Thread(
            target=self._commit_loop, daemon=True, name="ingest-commit")
        # compat alias: the fleet harness's deadlock verdict checks the
        # drain/commit thread's liveness under this name
        self._drain_thread = self._commit_thread
        for t in self._workers:
            t.start()
        self._commit_thread.start()
        # Unified-registry membership (d4pg_tpu/obs/registry): the
        # service's consistent snapshot IS the provider — held weakly,
        # last-registered service wins the slot, dropped on close().
        REGISTRY.register_provider("ingest", self.ingest_stats)

    # -- actor-facing ------------------------------------------------------
    def add(self, batch: TransitionBatch, actor_id: str = "local",
            block: bool = True, timeout: float | None = 5.0,
            count_env_steps: bool = True, shard: int | None = None) -> bool:
        """Enqueue transitions (backpressure via the bounded shard deque).
        Returns False if the deque stayed full past ``timeout``.

        ``count_env_steps=False`` for rows that do not correspond to fresh
        environment interaction (HER relabels) — otherwise the env_steps
        counter inflates by (1 + her_ratio)x in HER runs.

        With a ``shed_watermark`` configured, ``add`` NEVER blocks: a
        shard at the watermark sheds its oldest batch (counted) to admit
        this one, and the call returns True.

        ``shard`` pins the ingest shard (the sharded receiver passes the
        connection's shard); by default actors hash onto a stable one."""
        n = int(batch.obs.shape[0])
        s = self._route(actor_id, shard)
        self.heartbeat(actor_id, shard=s.idx)
        if n == 0:
            return True
        return self._admit(s, batch, None, actor_id, n, count_env_steps,
                           block, timeout)

    def add_payload(self, payload: bytes, shard: int = 0,
                    codec: str = "npz") -> bool:
        """Admit one UNDECODED wire frame from the sharded receiver
        (``transport.TransitionReceiver(on_payload=...)``). Raw (v2)
        frames are admitted on header metadata alone — actor id and row
        count come from ``raw_frame_meta`` — and decoded later on the
        owning shard's worker; npz frames carry no cheap header, so they
        are decoded here (the connection thread, exactly where the
        unsharded receiver decodes them).

        Backpressure matches the unsharded receiver's: with a shed
        watermark configured (fleet plane) admission never blocks — a
        full shard sheds oldest, counted; WITHOUT one (train.py default)
        a full shard blocks this connection thread up to 5 s, and a
        frame rejected past the timeout is counted in the shard's
        ``admit_fails`` rather than vanishing. A learner stall therefore
        backs pressure up into the sender exactly as at K=1."""
        trace = None
        gen = None
        if codec == "raw":
            try:
                # header-only: trace id/birth ride the v2 extension, so a
                # sampled frame is traceable (and shed-accountable with a
                # terminal span) before any column byte is parsed
                actor_id, n, count, trace, gen = raw_frame_meta_ex(payload)
            except Exception:
                s = self._shards[shard % self.num_ingest_shards]
                with s.cond:
                    s.decode_errors += 1
                record_event("decode_error", shard=s.idx, where="admission")
                return False
            data: object = payload
        else:
            try:
                actor_id, batch, count = decode_frame(payload, codec)
            except Exception:
                s = self._shards[shard % self.num_ingest_shards]
                with s.cond:
                    s.decode_errors += 1
                record_event("decode_error", shard=s.idx, where="admission")
                return False
            n, codec, data = int(batch.obs.shape[0]), None, batch
        s = self._shards[shard % self.num_ingest_shards]
        self.heartbeat(actor_id, shard=s.idx)
        fenced = False
        if gen is not None:
            # generation fence (crash recovery): a frame stamped with a
            # PRE-restart generation was encoded before the crash and
            # retried verbatim — its rows may already sit inside the
            # restored snapshot (the sender's sendall could have landed
            # before the kill). Admitting it risks a duplicate; fencing
            # it is a DECLARED loss (fenced_rows), keeping recovery
            # exactly-once w.r.t. committed rows.
            with self._lock:
                if gen < self._generation:
                    self._fenced_frames += 1
                    self._fenced_rows += n
                    fenced = True
        if fenced:
            REGISTRY.counter("ingest.rows_fenced").inc(n)
            record_event("generation_fenced", shard=s.idx, actor=actor_id,
                         rows=n, frame_gen=gen)
            if trace is not None:
                # the traced frame ends HERE: a fence is a terminal
                # outcome (like a shed), never an orphan span
                _tracer.begin(trace[0], trace[1])
                _tracer.terminal_shed(trace[0])
            return True
        if n == 0:
            return True
        return self._admit(s, data, codec, actor_id, n, count,
                           block=s.shed_at is None, timeout=5.0,
                           trace=trace)

    def _route(self, actor_id: str, shard: int | None) -> _IngestShard:
        if shard is not None:
            return self._shards[shard % self.num_ingest_shards]
        if self.num_ingest_shards == 1:
            return self._shards[0]
        return self._shards[hash(actor_id) % self.num_ingest_shards]

    def _admit(self, s: _IngestShard, data, codec, actor_id: str, rows: int,
               count: bool, block: bool, timeout: float | None,
               trace: tuple[int, float] | None = None) -> bool:
        with self._lock:
            self._pending += 1
        shed_seqs: list[int] = []
        shed_tids: list[int] = []
        shed_batches = 0
        admitted = False
        rejected_cls: str | None = None
        pol = self._admission
        with s.cond:
            if s.shed_at is not None:
                # shed admission: bounded work, never blocks. The counter
                # and the deque mutate under the same lock — the
                # consistent-snapshot contract of ingest_stats(). Without
                # a policy this is flat shed-oldest; with one the victim
                # is the oldest batch of the WORST queued class, and an
                # incoming batch that ranks below everything queued is
                # itself rejected (class-attributed) rather than evicting
                # more-protected work.
                inc_cls = (None if pol is None
                           else pol.classify_actor(actor_id))
                admitted = True
                while len(s.q) >= s.shed_at:
                    if pol is None:
                        victim = 0
                    else:
                        classes = [pol.classify_actor(it[3]) for it in s.q]
                        victim = pol.shed_victim(classes, inc_cls)
                        if victim is None:
                            admitted = False
                            rejected_cls = pol.class_name(inc_cls)
                            s.sheds_by_class[rejected_cls] = (
                                s.sheds_by_class.get(rejected_cls, 0) + rows)
                            break
                    old = s.q[victim]
                    del s.q[victim]
                    s.sheds += 1
                    s.shed_rows += old[4]
                    if pol is not None:
                        name = pol.class_name(classes[victim])
                        s.sheds_by_class[name] = (
                            s.sheds_by_class.get(name, 0) + old[4])
                    shed_seqs.append(old[0])
                    if old[6] is not None:
                        shed_tids.append(old[6][0])
                    shed_batches += 1
            elif len(s.q) >= s.capacity:
                if block:
                    deadline = (None if timeout is None
                                else time.monotonic() + timeout)
                    while (len(s.q) >= s.capacity
                           and not self._stop.is_set()):
                        remaining = (None if deadline is None
                                     else deadline - time.monotonic())
                        if remaining is not None and remaining <= 0:
                            break
                        s.cond.wait(0.1 if remaining is None
                                    else min(remaining, 0.1))
                admitted = len(s.q) < s.capacity
            else:
                admitted = True
            if admitted:
                seq = next(self._seq)
                s.q.append((seq, data, codec, actor_id, rows, count, trace))
                s.rows_in += rows
                s.cond.notify_all()
            else:
                s.admit_fails += 1
        # observability, all OUTSIDE the shard condition (obs locks are
        # terminal, but tiered hold times stay honest): admission span +
        # flight breadcrumb, terminal spans for everything shed here.
        if admitted:
            if trace is not None:
                _tracer.begin(trace[0], trace[1])
                _tracer.record_span(trace[0], "admission")
            record_event("admit", shard=s.idx, actor=actor_id, rows=rows)
            REGISTRY.counter("ingest.rows_admitted").inc(rows)
        else:
            if rejected_cls is not None:
                # class-policy rejection: a load verdict attributed to the
                # incoming batch's priority class, distinct from the
                # timeout path's admit_fail
                record_event(EVENT_ADMISSION_REJECT, plane="ingest",
                             shard=s.idx, actor=actor_id, cls=rejected_cls,
                             rows=rows)
            record_event("admit_fail", shard=s.idx, actor=actor_id,
                         rows=rows)
            if trace is not None:
                _tracer.begin(trace[0], trace[1])
                _tracer.terminal_shed(trace[0])
        if shed_seqs:
            self._tombstone(shed_seqs)
            if self._dealer is not None:
                self._dealer.mark_dead_seqs(shed_seqs)
            record_event("shed", shard=s.idx, batches=shed_batches,
                         seqs=shed_seqs[:8])
            for tid in shed_tids:
                _tracer.terminal_shed(tid)
        dropped = shed_batches + (0 if admitted else 1)
        if dropped:
            with self._lock:
                self._pending -= dropped  # sheds never reach the commit
        return admitted

    def _tombstone(self, seqs: list[int]) -> None:
        with self._commit_cond:
            self._skip.update(seqs)
            self._commit_cond.notify_all()

    def heartbeat(self, actor_id: str, shard: int | None = None) -> None:
        now = time.monotonic()
        with self._lock:
            evicted_at = self._evicted.pop(actor_id, None)
            if evicted_at is not None:
                # the actor came back: re-admit and record the outage
                self.readmissions += 1
                if len(self._recovery_s) < 10_000:
                    self._recovery_s.append(now - evicted_at)
            self._heartbeats[actor_id] = now
            if shard is not None:
                self._owner[actor_id] = shard
        if evicted_at is not None:
            record_event("readmission", actor=actor_id,
                         outage_s=round(now - evicted_at, 3))

    # -- learner-facing ----------------------------------------------------
    def sample(self, batch_size: int, beta: float = 0.4,
               weight_base: float | None = None):
        """PER: (batch, weights, idx, generation); uniform: batch. Mirrors
        the learner's buffer-kind dispatch (``ddpg.py:187-197``); the
        generation snapshot guards the priority write-back against the
        commit thread overwriting a sampled slot in flight."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                batch, w, idx = self.buffer.sample(
                    batch_size, beta=beta, weight_base=weight_base)
                return batch, w, idx, self.buffer.generation[idx].copy()
            return self.buffer.sample(batch_size)

    def sample_chunk(self, k: int, batch_size: int, beta: float = 0.4,
                     weight_base: float | None = None):
        """K stacked batches in one storage gather: (batches [K, B, ...],
        weights-or-None, idx [K, B], generation-or-None [K, B]) — the
        K-updates-per-dispatch sample path (``learner/pipeline.py``). The
        generation snapshot lets the deferred priority write-back skip
        slots the commit thread overwrote in flight."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                batches, w, idx = self.buffer.sample_chunk(
                    k, batch_size, beta=beta, weight_base=weight_base)
                return batches, w, idx, self.buffer.generation[idx].copy()
            batches, _, idx = self.buffer.sample_chunk(k, batch_size)
            return batches, None, idx, None

    def weight_base(self) -> float | None:
        """The local shard's IS-weight base ``z`` (see
        ``PrioritizedReplayBuffer.weight_base``); None for uniform replay."""
        with self._buffer_lock:
            if isinstance(self.buffer, PrioritizedReplayBuffer):
                return self.buffer.weight_base()
            return None

    def update_priorities(
        self,
        idx: np.ndarray,
        priorities: np.ndarray,
        generation: np.ndarray | None = None,
    ) -> None:
        if isinstance(self.buffer, PrioritizedReplayBuffer):
            with self._buffer_lock:
                self.buffer.update_priorities(idx, priorities,
                                              generation=generation)

    def attach_dealer(self, dealer) -> None:
        """Wire a ``replay/sampler.SampleDealer`` into the commit path.
        From here on every ordered commit mirrors its inserts into the
        dealer's slice trees and deals ready-to-train blocks into the
        per-replica rings; replicas feed TD priorities back through
        :meth:`queue_writeback` (sampler tier only — the replica sample
        path never acquires the buffer lock again)."""
        with self._buffer_lock:
            dealer.resync(self.buffer)
            self._dealer = dealer
        # Demand-driven top-up: a replica pop that frees ring room wakes
        # the commit loop (its idle tick deals the refill) instead of
        # leaving the refill to the next ingest commit or the ~10 Hz
        # timeout — a consumer faster than the commit cadence would
        # otherwise starve on an empty ring. The kick runs on the
        # replica thread with no locks held (the ring condition is
        # released before the callback fires), so taking the commit
        # condition here is a top-level acquire, not an ascent.
        for ring in dealer.rings:
            ring.on_room = self._kick_commit

    def _kick_commit(self) -> None:
        with self._commit_cond:
            self._commit_cond.notify_all()

    def queue_writeback(self, idx: np.ndarray, priorities: np.ndarray,
                        generation: np.ndarray) -> None:
        """Replica-side priority write-back on the dealt path. Enqueues
        under the ``sampler`` tier; the owning ingest shard's worker (and
        the commit thread's settle-before-draw) applies it to the slice
        trees. Generation-fenced exactly like ``update_priorities``."""
        dealer = self._dealer
        if dealer is None:
            raise RuntimeError("queue_writeback requires an attached "
                               "SampleDealer (attach_dealer)")
        dealer.queue_writeback(idx, priorities, generation)

    def drain_device(self) -> int:
        """Flush ALL rows staged by a fused-path buffer
        (``replay/fused_buffer.py``) onto the device. Called by the
        LEARNER thread at cycle/chunk boundaries — it is the single owner
        of the device handles, so the ingest workers only stage host rows
        and never dispatch device work."""
        drain = getattr(self.buffer, "drain", None)
        if drain is None:
            return 0
        with self._buffer_lock:
            return drain()

    def ingest_commit(self) -> int:
        """Land the in-flight staged block (one jitted ring-write + tree
        insert dispatch; no explicit H2D). Learner thread, called right
        BEFORE a fused-chunk dispatch so the chunk samples the freshest
        rows. No-op (0) for buffers without the block-drain API."""
        commit = getattr(self.buffer, "commit_staged", None)
        if commit is None:
            return 0
        with self._buffer_lock:
            return commit()

    def ingest_stage(self) -> int:
        """Start the H2D transfer of the next staged block (ONE
        ``jax.device_put``). Learner thread, called right AFTER a fused
        chunk is dispatched so the transfer overlaps the chunk's compute
        — the ≤ 1 explicit-H2D-per-chunk schedule
        (``learner/pipeline.IngestOverlap``). Falls back to a full
        synchronous drain for buffers without the block API (sharded
        fused replay), preserving the old per-chunk semantics there."""
        stage = getattr(self.buffer, "stage_block", None)
        if stage is None:
            return self.drain_device()
        with self._buffer_lock:
            return stage()

    def replay_state(self) -> dict:
        """Buffer contents + priorities for checkpointing (learner
        thread; SURVEY.md §5 elastic recovery)."""
        with self._buffer_lock:
            return self.buffer.state_dict()

    def load_replay_state(self, d: dict) -> None:
        with self._buffer_lock:
            self.buffer.load_state_dict(d)

    def snapshot(self, quiesce_timeout: float = 10.0) -> dict:
        """Consistent snapshot of the SERVING state at a quiesced cut:
        buffer columns + PER tree (``state_dict`` — the fused buffer
        drains its staging rings first, so ring heads collapse into the
        cut), the admission-ticket/commit floor, the row ledger and the
        service generation. The cut is quiesced by ``flush`` (every
        admitted batch committed), then captured lock-by-lock in the
        ``ingest_stats`` pattern — strictly SEQUENTIAL acquisitions, so
        the tier hierarchy gains no new edges. Restoring this dict into
        a fresh service (``restore``) resumes at exactly this cut;
        persisted next to the orbax learner checkpoint by
        ``io/checkpoint.py`` so learner and replay restore together."""
        self.flush(timeout=quiesce_timeout)
        with self._buffer_lock:
            buf = self.buffer.state_dict()
        with self._commit_cond:
            next_seq = self._next_seq
        with self._lock:
            return {
                "schema": 1,
                "buffer": buf,
                "next_seq": next_seq,
                "env_steps": self._env_steps,
                "rows_committed": self._rows_committed,
                "generation": self._generation,
            }

    def restore(self, snap: dict) -> None:
        """Load a ``snapshot`` cut into this (fresh or quiesced) service:
        buffer + PER tree, ticket floor (the admission counter resumes
        ABOVE every committed ticket, so merge order stays monotone
        across the restart) and the row ledger. The service generation
        is bumped PAST the snapshot's — every raw frame encoded against
        the pre-crash service now fences at admission."""
        if not isinstance(snap, dict) or "buffer" not in snap:
            raise ValueError("not a replay service snapshot (no buffer cut)")
        with self._buffer_lock:
            self.buffer.load_state_dict(snap["buffer"])
        floor = int(snap.get("next_seq", 0))
        with self._commit_cond:
            self._next_seq = floor
            self._seq = itertools.count(floor)
            self._skip.clear()
            for dq in self._out:
                dq.clear()
            self._commit_cond.notify_all()
        with self._lock:
            self._env_steps = int(snap.get("env_steps", 0))
            self._rows_committed = int(snap.get("rows_committed", 0))
            self._generation = max(self._generation,
                                   int(snap.get("generation", 0)) + 1)
        dealer = self._dealer
        if dealer is not None:
            # drop blocks dealt against the pre-restore state, then
            # rebuild the slice trees from the restored buffer; pending
            # write-backs die with the resync (their generations are
            # fenced by the bump above anyway)
            dealer.clear_rings()
            with self._buffer_lock:
                dealer.resync(self.buffer)

    @property
    def generation(self) -> int:
        """Current service generation (the id the receiver's greeting
        hands to connecting senders — transport.TransitionReceiver)."""
        with self._lock:
            return self._generation

    @property
    def env_steps(self) -> int:
        with self._lock:
            return self._env_steps

    def set_env_steps(self, n: int) -> None:
        """Seed the env-step counter (checkpoint resume)."""
        with self._lock:
            self._env_steps = int(n)

    def set_ingest_depth(self, capacity: int) -> None:
        """Live-resize the per-shard admission deques (elastic actuator).

        The shed watermark (when configured) is recomputed at the SAME
        fraction of the new capacity, so a deepened shard genuinely
        absorbs a flash crowd instead of shedding at the old bound.
        Each shard condition is taken and released in turn at top level
        (shard tier, nothing else held) — no new lock edges, and a
        snapshot taken mid-resize just reports the conservative
        (minimum) bound via ``ingest_stats()``."""
        cap = max(1, int(capacity))
        for s in self._shards:
            with s.cond:
                s.capacity = cap
                if s.shed_at is not None and self._shed_watermark is not None:
                    s.shed_at = max(
                        1, min(cap, int(self._shed_watermark * cap)))
                s.cond.notify_all()  # blocked adds may now fit

    def __len__(self) -> int:
        with self._buffer_lock:
            return len(self.buffer)

    def wait_until(self, min_size: int, timeout: float = 300.0) -> bool:
        """Block until the buffer holds ``min_size`` transitions (warmup
        gate, ``main.py:200-207``)."""
        deadline = time.monotonic() + timeout
        while len(self.buffer) < min_size:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.01)
        return True

    def dead_actors(self) -> list[str]:
        """Actors currently considered dead: heartbeat-stale ones plus the
        evicted-and-not-yet-returned set. An evicted actor that resumes
        heartbeating (or streaming — ``add`` heartbeats) is RE-ADMITTED by
        ``heartbeat`` and drops out of this list; before that fix an
        eviction was permanent and a restarted actor with the same id
        stayed counted dead forever."""
        now = time.monotonic()
        with self._lock:
            stale = [
                a for a, t in self._heartbeats.items()
                if now - t > self._heartbeat_timeout
            ]
            return stale + [a for a in self._evicted if a not in stale]

    def evict_dead(self) -> list[str]:
        """Move heartbeat-stale actors into the evicted set (their next
        heartbeat re-admits them and records the outage as a recovery
        sample). Returns the newly evicted ids. Called periodically by the
        fleet monitor; idempotent between actor state changes."""
        now = time.monotonic()
        with self._lock:
            stale = [
                a for a, t in self._heartbeats.items()
                if now - t > self._heartbeat_timeout
            ]
            for a in stale:
                del self._heartbeats[a]
                self._evicted[a] = now
                self.evictions += 1
        for a in stale:
            record_event("eviction", actor=a)
        return stale

    def evicted_actors(self) -> list[str]:
        with self._lock:
            return list(self._evicted)

    def ingest_stats(self) -> dict:
        """Degradation/recovery counters for the fleet plane. Snapshot
        consistency: every counter is read under the SAME lock that
        writes it — per-shard counters atomically with the queue they
        describe (one shard condition each), the env_steps/pending pair
        and heartbeat state atomically under the service lock — so the
        numbers can never show e.g. a shed whose queue pop is missing.
        Cross-shard totals are sums of per-shard-consistent snapshots."""
        per_shard = [s.snapshot() for s in self._shards]
        with self._commit_cond:
            commit_backlog = sum(len(dq) for dq in self._out)
            order_breaks = self.order_breaks
        with self._lock:
            merged = {
                "env_steps": self._env_steps,
                "rows_committed": self._rows_committed,
                "pending": self._pending,
                "evictions": self.evictions,
                "readmissions": self.readmissions,
                "recovery_s": list(self._recovery_s),
                "live_actors": len(self._heartbeats),
                "evicted": len(self._evicted),
                "generation": self._generation,
                "fenced_frames": self._fenced_frames,
                "fenced_rows": self._fenced_rows,
            }
        merged.update({
            "queue_depth": sum(p["queue_depth"] for p in per_shard),
            "sheds": sum(p["sheds"] for p in per_shard),
            "shed_rows": sum(p["shed_rows"] for p in per_shard),
            "decode_errors": sum(p["decode_errors"] for p in per_shard),
            "admit_fails": sum(p["admit_fails"] for p in per_shard),
            # class-attributed shed ledger (elastic admission): covers
            # both evicted-queued rows and policy-rejected incoming rows,
            # so it can exceed shed_rows when incoming work is bounced
            "sheds_by_class": _merge_class_counts(
                p["sheds_by_class"] for p in per_shard),
            # live per-shard deque bound — the elastic autoscaler's
            # set_ingest_depth actuator target (min across shards so a
            # mid-resize snapshot reports the conservative bound)
            "ingest_capacity": min(p["capacity"] for p in per_shard),
            "num_ingest_shards": self.num_ingest_shards,
            "commit_backlog": commit_backlog,
            "order_breaks": order_breaks,
            "per_shard": per_shard,
        })
        return merged

    # -- internals ---------------------------------------------------------
    # Max batches folded into one merged commit pass: bounds the lock
    # hold (the learner's sample path waits on the same lock) while still
    # amortizing it ~64x under a streaming fleet.
    _COALESCE = 64

    def _worker(self, s: _IngestShard) -> None:
        """Shard worker: pop a coalesced group, decode wire payloads
        (the CPU-heavy half of ingest), optionally direct-stage into the
        buffer's shard ring, and hand the group to the ordered merge.

        Backpressure discipline: at most ONE decoded group per shard sits
        in the merge's inbox — the worker waits for the commit thread to
        take its previous group before popping the next. Decode of group
        t+1 thereby overlaps the insert of group t (the pipeline), while
        a slow commit still backs pressure up into the shard deque where
        the shed watermark / blocking-add contract lives, exactly like
        the single drain thread it replaces."""
        try:
            self._worker_loop(s)
        except Exception as e:
            contained_crash("ingest.shard_worker", e)

    def _worker_loop(self, s: _IngestShard) -> None:
        while not self._stop.is_set():
            dealer = self._dealer
            if dealer is not None:
                # the owning shard drains ITS slices' priority write-back
                # queues — top-level sampler-tier acquire, no other lock
                # held, so the slice trees keep a single writer per slice
                dealer.drain_writebacks_for_shard(s.idx)
            with self._commit_cond:
                while self._out[s.idx] and not self._stop.is_set():
                    self._commit_cond.wait(timeout=0.1)
            with s.cond:
                if not s.q:
                    s.cond.wait(timeout=0.1)
                items = []
                while s.q and len(items) < self._COALESCE:
                    items.append(s.q.popleft())
                if items:
                    s.cond.notify_all()  # space freed: wake blocked adds
            if not items:
                continue
            out, dead, dead_tids, staged = [], [], [], 0
            for seq, data, codec, actor_id, rows, count, trace in items:
                tid = trace[0] if trace is not None else None
                if codec is not None:
                    try:
                        actor_id, batch, count = decode_frame(data, codec)
                    except Exception:
                        dead.append(seq)
                        if tid is not None:
                            dead_tids.append(tid)
                        continue
                    rows = int(batch.obs.shape[0])
                    if tid is not None:
                        _tracer.record_span(tid, "decode")
                else:
                    batch = data
                if self._direct_stage:
                    # rows land in the buffer's per-shard staging ring
                    # HERE, on the shard core; the commit thread only
                    # settles the ordered accounting for this ticket
                    self.buffer.add_sharded(batch, s.idx, ticket=seq)
                    staged += rows
                    batch = None
                if tid is not None:
                    # 'stage': rows copied into the shard's staging ring
                    # (direct path) or handed to the ordered-merge inbox
                    _tracer.record_span(tid, "stage")
                out.append((seq, actor_id, batch, rows, count, tid))
            if dead or staged:
                with s.cond:
                    s.decode_errors += len(dead)
                    s.staged_rows += staged
            with self._commit_cond:
                self._out[s.idx].extend(out)
                if dead:
                    self._skip.update(dead)
                self._commit_cond.notify_all()
            if dead:
                record_event("decode_error", shard=s.idx, tickets=dead[:8],
                             n=len(dead))
                if dealer is not None:
                    dealer.mark_dead_seqs(dead)
                for tid in dead_tids:
                    _tracer.terminal_shed(tid)  # tombstoned, not leaked
                with self._lock:
                    self._pending -= len(dead)

    def _pop_ready(self, group: list, shed_tids: list | None = None,
                   shed_seqs: list | None = None) -> int:
        """Pop the next run of in-ticket-order items (caller holds
        ``_commit_cond``). Tombstoned tickets are consumed and skipped.

        Returns the number of STALE tickets discarded: a ticket the
        order-break valve advanced past (its worker held the popped group
        too long) later lands at the head of its shard's deque with
        ``seq < _next_seq`` — forever unpoppable by the equality match
        below, which would gate that shard's worker on a never-emptying
        inbox and wedge the shard permanently. Degrade-and-count instead:
        drop it, count it in ``order_breaks``; the caller settles its
        ``_pending`` accounting — and the discards' terminal trace spans
        (collected into ``shed_tids``) — outside this condition."""
        stale = 0
        while len(group) < self._COALESCE:
            while self._next_seq in self._skip:
                self._skip.discard(self._next_seq)
                self._next_seq += 1
            found = None
            for dq in self._out:
                while dq and dq[0][0] < self._next_seq:
                    item = dq.popleft()
                    self.order_breaks += 1
                    stale += 1
                    if shed_tids is not None and item[5] is not None:
                        shed_tids.append(item[5])
                    if shed_seqs is not None:
                        shed_seqs.append(item[0])
                if dq and dq[0][0] == self._next_seq:
                    found = dq.popleft()
                    break
            if found is None:
                break
            group.append(found)
            self._next_seq += 1
        return stale

    def _commit_loop(self) -> None:
        """The single writer of replay state: ordered K-way merge of the
        shard outputs, normalizer fold, one buffer-lock acquisition per
        merged group."""
        try:
            self._commit_run()
        except Exception as e:
            contained_crash("ingest.commit", e)

    def _commit_run(self) -> None:
        last_progress = time.monotonic()
        while True:
            group: list = []
            shed_tids: list = []
            stale_seqs: list = []
            with self._commit_cond:
                stale = self._pop_ready(group, shed_tids, stale_seqs)
                if not group:
                    if self._stop.is_set():
                        return
                    self._commit_cond.wait(timeout=0.1)
                    stale += self._pop_ready(group, shed_tids, stale_seqs)
                if group or stale:
                    # inbox slots freed: wake gated shard workers
                    self._commit_cond.notify_all()
                backlog = any(self._out[i] for i in range(len(self._out)))
            if group:
                # merge-pop spans, recorded after the condition released
                # (the pop order inside one group is ticket order; one
                # timestamp per group is the honest granularity — the
                # commit thread popped them in one critical section)
                for item in group:
                    if item[5] is not None:
                        _tracer.record_span(item[5], "merge")
            if stale:
                # discarded tickets never reach _insert_group; settle the
                # flush() accounting here (never inside _commit_cond —
                # lock order: _lock is not taken under the merge cond)
                record_event("order_break", kind_detail="stale_discard",
                             n=stale)
                for tid in shed_tids:
                    _tracer.terminal_shed(tid)
                if self._dealer is not None:
                    self._dealer.mark_dead_seqs(stale_seqs)
                with self._lock:
                    self._pending -= stale
            if group:
                last_progress = time.monotonic()
                self._insert_group(group)
            elif (backlog and time.monotonic() - last_progress
                    > _ORDER_GRACE_S):
                # safety valve: a ticket vanished without a tombstone.
                # Skip to the smallest ready ticket (counted) rather than
                # wedging the whole ingest plane behind it.
                advanced = False
                with self._commit_cond:
                    heads = [dq[0][0] for dq in self._out if dq]
                    if heads and min(heads) > self._next_seq:
                        self.order_breaks += 1
                        advanced = True
                        self._next_seq = min(heads)
                        # tombstones below the new floor can never be
                        # consumed by _pop_ready's equality walk; prune
                        # them or the set grows for the service lifetime
                        self._skip = {t for t in self._skip
                                      if t >= self._next_seq}
                if advanced:
                    record_event("order_break", kind_detail="floor_advance")
                last_progress = time.monotonic()
            if not group and self._dealer is not None:
                # idle deal tick: settle write-backs and top the rings
                # back up even when ingest is quiet — still the commit
                # thread, still one buffer-lock window per tick
                dealer = self._dealer
                with self._buffer_lock:
                    dealt = dealer.ingest_and_deal((), self.buffer)
                if dealt:
                    dealer.publish(dealt)

    def _insert_group(self, group: list) -> None:
        dealer = self._dealer
        dealt: list = []
        try:
            if self.obs_norm is not None:
                # Only obs rows feed the estimator; next_obs is
                # normalized but never folded in. The episode-FINAL
                # next_obs is thereby excluded — intentional: there is
                # no row-level marker for "truly final" here (done=1
                # tags every n-step fold of a terminal AND HER success
                # relabels mid-trajectory, so done-gating would weight
                # terminal-adjacent states 2-5x instead), and the
                # omission is one state in T per episode. Stats fold
                # BEFORE any of the group's rows are normalized, in
                # admission-ticket order — same estimator as the
                # per-batch loop, regardless of shard interleaving.
                for j, (seq, aid, batch, rows, cnt, tid) in enumerate(group):
                    if batch is None:
                        continue
                    self.obs_norm.update(batch.obs)
                    group[j] = (seq, aid, batch._replace(
                        obs=self.obs_norm.normalize(batch.obs),
                        next_obs=self.obs_norm.normalize(batch.next_obs),
                    ), rows, cnt, tid)
            with self._buffer_lock:
                if dealer is None:
                    for _seq, _aid, batch, _rows, _cnt, _tid in group:
                        if batch is not None:  # None: already direct-staged
                            self.buffer.add(batch)
                else:
                    # sample-on-ingest: insert, mirror, settle write-backs
                    # and draw dealt blocks inside the ONE buffer-lock
                    # window this commit already owned — the collapsed
                    # ingest->insert->sample->fetch pass
                    inserts = []
                    for _seq, _aid, batch, _rows, _cnt, _tid in group:
                        if batch is not None:
                            inserts.append(
                                (self.buffer.add(batch), _seq, _tid))
                    dealt = dealer.ingest_and_deal(inserts, self.buffer)
        finally:
            committed = 0
            with self._lock:
                for _seq, _aid, _batch, rows, count, _tid in group:
                    if count:
                        self._env_steps += rows
                    committed += rows
                self._rows_committed += committed
                self._pending -= len(group)
            # The rows ledger counts each row ONCE, here, where replay
            # state changed — NEVER at direct-stage time (staged_rows is
            # a per-shard marker of which path ran, a SUBSET of these
            # rows, not an addend; summing both double-counts the fast
            # path — the K=1↔K=2 counter-equivalence test pins this).
            REGISTRY.counter("ingest.rows_committed").inc(committed)
            _tracer.mark_committed(
                [tid for *_rest, tid in group if tid is not None])
        if dealt:
            # ring pushes + deal spans AFTER every service lock released
            dealer.publish(dealt)

    def flush(self, timeout: float = 5.0) -> None:
        """Block until every accepted batch has been committed."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return
            time.sleep(0.005)

    def close(self) -> None:
        self.flush()
        self.kill()

    def kill(self) -> None:
        """SIGKILL-equivalent teardown (the chaos supervisor's weapon):
        stop the ingest threads WITHOUT flushing. Accepted-but-uncommitted
        batches are discarded, exactly what process death does to them;
        rows committed after the last durable snapshot die with the
        instance too — recovery restores that snapshot into a FRESH
        service and fences the stale generation at admission. Safe to
        call twice (provider unregistration is instance-guarded, thread
        joins are idempotent)."""
        REGISTRY.unregister_provider("ingest", self.ingest_stats)
        if self._dealer is not None:
            # closes the dealt rings too, waking any blocked replica pop
            self._dealer.close()
        self._stop.set()
        for s in self._shards:
            with s.cond:
                s.cond.notify_all()
        with self._commit_cond:
            self._commit_cond.notify_all()
        for t in self._workers:
            t.join(timeout=2.0)
        self._commit_thread.join(timeout=2.0)
