"""Actor->learner transition transport over sockets (the DCN plane).

The reference's only inter-process channel is OS shared memory on one host
(``torch.multiprocessing``, ``main.py:12,386-388``) — it cannot cross hosts.
SURVEY.md §5 mandates a real transport: actors on TPU-VM hosts stream
transition batches to the learner's replay service over the pod data
network, with backpressure.

Wire format (length-prefixed frames over TCP):
    [u32 magic][u32 payload_len][payload]
payload = npz-serialized TransitionBatch (+ actor id). TCP gives ordering
and backpressure for free; a slow learner applies backpressure through the
kernel socket buffers and the sender's bounded queue. Heartbeats ride the
same connection as empty batches.

Hardening: servers bind loopback by default (pass the DCN interface
explicitly for cross-host fleets), payload lengths are capped (the u32
frame length is peer-controlled — without a cap any peer could make the
receiver allocate 4 GiB), and an optional shared ``secret`` enables an
HMAC-SHA256 challenge-response handshake on connect so only authorized
actors can inject replay data (np.load is pickle-free, so the payloads
themselves cannot execute code).
"""

from __future__ import annotations

import hashlib
import hmac
import io
import os
import socket
import struct
import threading
from typing import Callable, Optional

import numpy as np

# All framing facts (magics, header structs, flag bits, payload cap) come
# from the declared wire registry — the single source of truth every
# plane imports; see core/wire.py and `python -m d4pg_tpu.lint --wire`.
from d4pg_tpu.core.wire import (
    F_COUNT as _F_COUNT,
    F_GEN as _F_GEN,
    F_TRACE as _F_TRACE,
    FRAME_HEADER as _HEADER,
    GEN_GREETING as _GEN_GREETING,
    MAGIC_GEN_GREETING as _MAGIC_GEN,
    MAGIC_INGEST_V1 as _MAGIC,
    MAGIC_INGEST_V2 as _MAGIC_RAW,
    MAX_PAYLOAD,
    RAW_FIELD_PRE as _RAW_FIELD_PRE,
    RAW_GEN as _RAW_GEN,
    RAW_NFIELDS as _RAW_NFIELDS,
    RAW_PRE as _RAW_PRE,
    RAW_TRACE as _RAW_TRACE,
    ingest_v2_layout as _ingest_v2_layout,
)
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.replay.uniform import TransitionBatch

_NONCE_LEN = 16
_MAC_LEN = 32  # sha256 digest

CODECS = ("npz", "raw")


def _hs_mac(secret: str, nonce: bytes) -> bytes:
    return hmac.new(secret.encode(), nonce, hashlib.sha256).digest()


def server_handshake(conn: socket.socket, secret: Optional[str],
                     timeout: float = 5.0) -> bool:
    """Server side of the connect handshake: send a fresh nonce, require
    HMAC-SHA256(secret, nonce) back. No-op (True) when no secret is set."""
    if not secret:
        return True
    nonce = os.urandom(_NONCE_LEN)
    prev = conn.gettimeout()
    conn.settimeout(timeout)
    try:
        conn.sendall(nonce)
        mac = _recv_exact(conn, _MAC_LEN)
        return mac is not None and hmac.compare_digest(
            mac, _hs_mac(secret, nonce))
    except OSError:
        return False
    finally:
        conn.settimeout(prev)


def client_handshake(sock: socket.socket, secret: Optional[str],
                     timeout: float = 5.0) -> None:
    """Client side: answer the server's nonce challenge."""
    if not secret:
        return
    prev = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        nonce = _recv_exact(sock, _NONCE_LEN)
        if nonce is None:
            raise ConnectionError("server closed during handshake")
        sock.sendall(_hs_mac(secret, nonce))
    finally:
        sock.settimeout(prev)


def _encode(actor_id: str, batch: TransitionBatch,
            count_env_steps: bool = True) -> bytes:
    buf = io.BytesIO()
    np.savez(
        buf,
        actor_id=np.frombuffer(actor_id.encode(), np.uint8),
        obs=batch.obs,
        action=batch.action,
        reward=batch.reward,
        next_obs=batch.next_obs,
        done=batch.done,
        discount=batch.discount,
        # synthetic rows (HER relabels) must not inflate the learner's
        # env-step counter (ADVICE r1: (1+her_ratio)x inflation otherwise)
        count=np.uint8(count_env_steps),
    )
    payload = buf.getvalue()
    return _HEADER.pack(_MAGIC, len(payload)) + payload


def _decode(payload: bytes) -> tuple[str, TransitionBatch, bool]:
    with np.load(io.BytesIO(payload)) as z:
        actor_id = z["actor_id"].tobytes().decode()
        batch = TransitionBatch(
            obs=z["obs"], action=z["action"], reward=z["reward"],
            next_obs=z["next_obs"], done=z["done"], discount=z["discount"],
        )
        count = bool(z["count"]) if "count" in z.files else True
    return actor_id, batch, count


# -- v2 raw column codec ---------------------------------------------------
#
# The npz codec costs ~1 ms of host CPU per 16-row Humanoid frame (zipfile
# member parsing on both ends) — measured as the dominant share of the
# ~5,200 rows/s/core ingest ceiling the fleet sweep hit. The v2 frame is
# the sharded ingest plane's native format: a fixed struct header carrying
# actor id, row count and per-field (dtype, shape), then the raw
# C-contiguous column bytes back to back. Decode is a header parse plus
# six ``np.frombuffer`` views (~30 us/frame), and — the part sharding
# needs — ``raw_frame_meta`` reads actor id / row count / count-flag from
# the header WITHOUT touching the columns, so admission can route, shed
# (with exact row accounting) and heartbeat before any decode happens.
#
# Header extension (the wire-to-grad tracing plane, d4pg_tpu/obs/trace):
# the leading byte is a FLAG byte — bit 0 is the count-env-steps flag it
# always carried (old encoders wrote exactly 0 or 1), bit 1 marks an
# optional 16-byte trace extension (u64 trace id + f64 birth timestamp)
# between the actor id and the field table. Frames WITHOUT the extension
# are byte-identical to the original v2 format and decode unchanged
# forever; the extension is readable from the header alone, so sampled
# frames are traceable at zero-decode admission time (a shed frame gets
# its terminal span without ever parsing a column).
#
# Generation extension (the crash-recovery plane): bit 2 marks an
# optional 4-byte u32 service-generation id AFTER the trace extension
# (both optional, fixed order: aid, [trace], [gen], field table). A
# sender learns the serving generation from the receiver's post-handshake
# greeting and stamps it into every frame it ENCODES; a frame encoded
# before a service crash and retried verbatim across the restart still
# carries the pre-crash generation, which is exactly how the restarted
# service fences ambiguous in-flight frames (ReplayService.add_payload)
# instead of risking a double-commit against the restored snapshot.
# Like the trace extension, it is header-only readable and absent bytes
# keep old frames byte-identical forever.

# The structs and flag bits for both extensions are declared once in
# core/wire.py (ingest-v2 row of the registry) and imported above:
# _RAW_PRE "!BB" (flags, len(aid)), _RAW_TRACE "!Qd", _RAW_GEN "!I",
# _F_COUNT/_F_TRACE/_F_GEN bits 0/1/2 of the ingest flag byte.
#
# Post-handshake receiver greeting (_MAGIC_GEN + _GEN_GREETING "!HI"):
# magic + current service generation. Opt-in on BOTH sides (receiver
# configured with a generation source, sender constructed with
# expect_generation=True) so the legacy wire conversation is untouched
# byte for byte.


def encode_raw(actor_id: str, batch: TransitionBatch,
               count_env_steps: bool = True,
               trace: tuple[int, float] | None = None,
               generation: int | None = None) -> bytes:
    aid = actor_id.encode()
    if len(aid) > 255:
        raise ValueError("actor_id longer than 255 bytes")
    flags = ((_F_COUNT if count_env_steps else 0)
             | (_F_TRACE if trace else 0)
             | (_F_GEN if generation is not None else 0))
    head = [_RAW_PRE.pack(flags, len(aid)), aid]
    if trace:
        head.append(_RAW_TRACE.pack(int(trace[0]), float(trace[1])))
    if generation is not None:
        head.append(_RAW_GEN.pack(int(generation) & 0xFFFFFFFF))
    head.append(_RAW_NFIELDS.pack(len(batch)))
    blobs = []
    for v in batch:
        a = np.ascontiguousarray(v)
        ds = a.dtype.str.encode()
        head.append(_RAW_FIELD_PRE.pack(len(ds), a.ndim) + ds
                    + struct.pack(f"!{a.ndim}I", *a.shape))
        blobs.append(a.tobytes())
    payload = b"".join(head) + b"".join(blobs)
    return _HEADER.pack(_MAGIC_RAW, len(payload)) + payload


def _raw_header(payload: bytes):
    """Parse the v2 header: (actor_id, count, [(dtype, shape)], data_off,
    trace, generation) — ``trace`` is ``(trace_id, birth_ts)`` when the
    frame carries the tracing extension, ``generation`` the u32 service
    generation when it carries the recovery extension; else None.

    Extension offsets come from the registry's declared layout
    (``wire.ingest_v2_layout``) rather than a hand-rolled running
    offset, so the header-only readers and the full decoder can never
    drift from the declared frame shape."""
    flags, laid = _RAW_PRE.unpack_from(payload, 0)
    layout = _ingest_v2_layout(flags, laid)
    actor_id = payload[layout["aid"]:layout["aid"] + laid].decode()
    trace = None
    if layout["trace"] >= 0:
        trace = _RAW_TRACE.unpack_from(payload, layout["trace"])
    generation = None
    if layout["generation"] >= 0:
        (generation,) = _RAW_GEN.unpack_from(payload, layout["generation"])
    off = layout["fields"]
    (nf,) = _RAW_NFIELDS.unpack_from(payload, off)
    off += _RAW_NFIELDS.size
    fields = []
    for _ in range(nf):
        lds, ndim = _RAW_FIELD_PRE.unpack_from(payload, off)
        off += _RAW_FIELD_PRE.size
        dtype = np.dtype(payload[off:off + lds].decode())
        off += lds
        shape = struct.unpack_from(f"!{ndim}I", payload, off)
        off += 4 * ndim
        fields.append((dtype, shape))
    return actor_id, bool(flags & _F_COUNT), fields, off, trace, generation


def raw_frame_meta(payload: bytes) -> tuple[str, int, bool]:
    """(actor_id, n_rows, count_env_steps) from the header alone — no
    column bytes touched. The admission-time accounting hook for the
    sharded receiver (shed rows are counted exactly without a decode)."""
    actor_id, n, count, _trace, _gen = raw_frame_meta_ex(payload)
    return actor_id, n, count


def raw_frame_meta_ex(payload: bytes) -> tuple[
        str, int, bool, tuple[int, float] | None, int | None]:
    """``raw_frame_meta`` plus the trace extension ``(trace_id,
    birth_ts)`` and the generation extension (each None when absent) —
    still header-only, so a sampled frame is traceable (and a stale-
    generation frame fence-able, with its terminal span) before any
    column byte is parsed."""
    actor_id, count, fields, _, trace, generation = _raw_header(payload)
    n = int(fields[0][1][0]) if fields and fields[0][1] else 0
    return actor_id, n, count, trace, generation


def decode_raw(payload: bytes) -> tuple[str, TransitionBatch, bool]:
    actor_id, count, fields, off, _trace, _gen = _raw_header(payload)
    if len(fields) != len(TransitionBatch._fields):
        raise ProtocolError(
            f"raw frame carries {len(fields)} fields, expected "
            f"{len(TransitionBatch._fields)}")
    cols = []
    for dtype, shape in fields:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        end = off + n * dtype.itemsize
        if end > len(payload):
            raise ProtocolError("raw frame truncated mid-column")
        # zero-copy read-only views into the payload: every consumer
        # copies rows onward (staging ring / storage write) anyway
        cols.append(np.frombuffer(payload, dtype, n, off).reshape(shape))
        off = end
    return actor_id, TransitionBatch(*cols), count


def decode_frame(payload: bytes, codec: str) -> tuple[str, TransitionBatch, bool]:
    """Decode one payload by codec name ('npz' | 'raw') — the hook the
    sharded ``ReplayService`` workers use for lazy decode."""
    return decode_raw(payload) if codec == "raw" else _decode(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class ProtocolError(ConnectionError):
    """A deterministic wire-format violation (bad magic, oversized frame).
    NOT retried by the reconnecting clients: a corrupt stream is a config/
    version fault that reconnecting cannot heal, so it must surface at the
    first frame rather than masquerade as network downtime."""


class ReconnectingClient:
    """Shared client-side connection management for the DCN plane: one
    socket + handshake, dropped and re-established on transport failure
    (subclasses decide retry policy), with a ``close()`` that is FINAL —
    it interrupts an in-flight retry loop and makes later calls raise
    instead of silently reconnecting."""

    def __init__(self, host: str, port: int,
                 connect_timeout: float = 10.0,
                 secret: Optional[str] = None):
        self._addr = (host, port)
        self._connect_timeout = connect_timeout
        self._secret = secret
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sock: socket.socket | None = None
        # the INITIAL connect fails fast: a wrong host/port/secret should
        # surface at startup, not spin in a retry loop
        self._connect()

    def _connect(self) -> None:
        sock = socket.create_connection(self._addr,
                                        timeout=self._connect_timeout)
        try:
            client_handshake(sock, self._secret)
            sock.settimeout(None)
        except (OSError, ConnectionError):
            sock.close()
            raise
        if self._stop.is_set():
            # close() ran while we were connecting: finalize the close
            # instead of resurrecting the client (the fd would leak and a
            # frame could be delivered after close)
            sock.close()
            raise ConnectionError(f"{type(self).__name__} is closed")
        self._sock = sock

    def _drop_sock(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _check_open(self) -> None:
        if self._stop.is_set():
            raise ConnectionError(f"{type(self).__name__} is closed")

    def close(self) -> None:
        # no lock: an in-flight retry loop holds it for up to its whole
        # retry window. Setting the stop flag makes that loop exit at its
        # next check; closing the socket out from under a blocked sendall
        # surfaces as OSError there, which the loop translates via
        # _check_open.
        self._stop.set()
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._sock = None


class TransitionSender(ReconnectingClient):
    """Actor-side client: connects to the learner host and streams batches.

    ``send`` survives learner restarts (VERDICT r3 #5): on a broken pipe it
    reconnects with exponential backoff + full jitter and resends the frame
    — a restarting learner re-attaches the whole fleet instead of stranding
    it (the reference's fleet story is ``mp.Process`` + ``join``; a dead
    parent ends everything, ``main.py:399-405``). The retry loop is
    BOUNDED twice over: ``retry_timeout`` seconds of wall clock per call
    AND ``max_retries`` reconnect attempts (None = time bound only). What
    happens at the bound is the fleet-degradation policy:

      - ``drop_on_timeout=False`` (default, the training-loop contract):
        raise ``ConnectionError`` — a learner gone past the bound is fatal.
      - ``drop_on_timeout=True`` (the fleet-plane contract): ``send``
        returns **False** and the frame is dropped with a counted metric —
        a 256-actor fleet degrades by losing replay rows (benign), never
        by wedging 256 threads on one dead receiver.

    The backoff jitter is seeded (``backoff_seed``) so fleet runs are
    reproducible; unseeded senders draw fresh entropy, which decorrelates
    a fleet-wide reconnect stampede after a learner restart.

    Delivery semantics are TCP's: the first write after a silent peer
    death can land in the kernel buffer and be lost (no app-level acks by
    design — an ack round-trip per frame would serialize the streaming
    plane), later writes observe the break and the frame in hand — the
    one encoded byte string — is retried verbatim across reconnects, so a
    frame that survives a retry is bitwise the frame that was first
    attempted. Lost-or-duplicated replay rows are both benign for ingest.

    Counters (monotonic over the sender's life, read by the fleet
    harness): ``frames_sent``, ``frames_dropped``, ``retries`` (reconnect
    attempts)."""

    def __init__(self, host: str, port: int, actor_id: str = "remote",
                 connect_timeout: float = 10.0, secret: Optional[str] = None,
                 retry_timeout: float = 300.0,
                 max_retries: Optional[int] = None,
                 drop_on_timeout: bool = False,
                 backoff_base: float = 0.2, backoff_max: float = 5.0,
                 backoff_seed: Optional[int] = None,
                 codec: str = "npz",
                 trace_sample: float = 0.0,
                 expect_generation: bool = False,
                 reconnect_jitter_s: float = 0.0):
        if codec not in CODECS:
            raise ValueError(f"unknown codec {codec!r}; one of {CODECS}")
        self.codec = codec
        self.actor_id = actor_id
        # Crash-recovery plane: when the peer receiver serves a generation
        # greeting, every (re)connect refreshes the id and raw frames are
        # stamped with it at ENCODE time — a frame retried verbatim across
        # a service restart keeps its pre-crash stamp and gets fenced.
        self._expect_generation = bool(expect_generation)
        self.generation = 0
        self._retry_timeout = retry_timeout
        self._max_retries = max_retries
        self._drop_on_timeout = drop_on_timeout
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._backoff_rng = np.random.default_rng(backoff_seed)
        # Reconnect-storm guard (crash-recovery plane): when > 0, the
        # FIRST retry of a send episode sleeps an extra seeded uniform in
        # [0, reconnect_jitter_s) before reconnecting. A service restart
        # breaks every fleet lane at the same instant; the exponential
        # backoff alone starts every lane at the same backoff_base, so
        # the first wave of reconnects still lands as a storm. A separate
        # rng keeps the pinned backoff stream bit-identical whether or
        # not the guard is armed.
        self._reconnect_jitter_s = float(reconnect_jitter_s)
        self._storm_rng = np.random.default_rng(
            None if backoff_seed is None else backoff_seed + 0x57a9)
        self.storm_jitters = 0
        self.storm_jitter_s: list[float] = []
        # Wire-to-grad tracing (obs/trace): sample this fraction of raw
        # frames and stamp them with a trace id + birth timestamp in the
        # v2 header extension. Seeded alongside the backoff rng so a
        # seeded fleet samples the same frames run to run; npz frames
        # carry no extension, so trace_sample is inert at codec='npz'.
        self._trace_sample = float(trace_sample)
        self._trace_rng = np.random.default_rng(
            None if backoff_seed is None else backoff_seed + 0x7ace)
        self._trace_salt = hash(actor_id) & 0xFFFF
        self.frames_traced = 0
        self.frames_sent = 0
        self.frames_dropped = 0
        self.retries = 0
        super().__init__(host, port, connect_timeout, secret)

    def _connect(self) -> None:
        super()._connect()
        if not self._expect_generation:
            return
        # the greeting rides the fresh socket before any frame: a missing
        # or malformed greeting is a config fault (peer not serving
        # generations), surfaced as ProtocolError — reconnecting can't heal
        sock = self._sock
        sock.settimeout(self._connect_timeout)
        try:
            raw = _recv_exact(sock, _GEN_GREETING.size)
            if raw is None:
                raise ConnectionError("peer closed before generation greeting")
            magic, gen = _GEN_GREETING.unpack(raw)
            if magic != _MAGIC_GEN:
                raise ProtocolError(
                    f"expected generation greeting, got magic {magic:#x}")
            self.generation = int(gen)
        except (OSError, ConnectionError):
            self._drop_sock()
            raise
        finally:
            if self._sock is not None:
                self._sock.settimeout(None)

    def send(self, batch: TransitionBatch, count_env_steps: bool = True,
             timeout: float | None = None) -> bool:
        """Stream one frame; True once it is handed to the kernel, False
        (``drop_on_timeout``) / ``ConnectionError`` (default) when the
        retry budget — ``timeout`` seconds (default ``retry_timeout``)
        or ``max_retries`` reconnect attempts — is exhausted first."""
        import time

        if self.codec == "raw":
            trace = None
            if (self._trace_sample > 0.0
                    and float(self._trace_rng.random()) < self._trace_sample):
                from d4pg_tpu.obs.trace import new_trace_id

                trace = (new_trace_id(self._trace_salt), time.monotonic())
                self.frames_traced += 1
            data = encode_raw(self.actor_id, batch, count_env_steps,
                              trace=trace,
                              generation=(self.generation
                                          if self._expect_generation
                                          else None))
        else:
            data = _encode(self.actor_id, batch, count_env_steps)
        with self._lock:
            self._check_open()
            budget = self._retry_timeout if timeout is None else timeout
            deadline = time.monotonic() + budget
            backoff = self._backoff_base
            attempts = 0
            while True:
                if self._sock is not None:
                    try:
                        self._sock.sendall(data)
                        self.frames_sent += 1
                        return True
                    except OSError:
                        self._drop_sock()
                self._check_open()
                now = time.monotonic()
                if now >= deadline or (self._max_retries is not None
                                       and attempts >= self._max_retries):
                    self.frames_dropped += 1
                    if self._drop_on_timeout:
                        return False
                    raise ConnectionError(
                        f"learner unreachable for {budget:.0f}s "
                        f"({attempts} reconnect attempts) "
                        f"at {self._addr[0]}:{self._addr[1]}")
                # Event.wait doubles as an interruptible sleep: close()
                # wakes the loop immediately. Upward jitter (uniform in
                # [backoff, 1.5*backoff]) de-synchronizes a fleet-wide
                # reconnect stampede; the lower bound stays the plain
                # exponential schedule so the first retry never lands
                # inside a dying peer's teardown window (a just-closed
                # listener can keep completing handshakes into its backlog
                # for a beat — connecting there loses the frame silently).
                extra = 0.0
                if attempts == 0 and self._reconnect_jitter_s > 0.0:
                    # storm guard: only the FIRST attempt of an episode
                    # pays the spread — later attempts are already
                    # de-synchronized by the exponential schedule
                    extra = (float(self._storm_rng.random())
                             * self._reconnect_jitter_s)
                    self.storm_jitters += 1
                    self.storm_jitter_s.append(extra)
                jitter = 1.0 + 0.5 * float(self._backoff_rng.random())
                self._stop.wait(
                    min(backoff * jitter + extra,
                        max(0.0, deadline - now)))
                self._check_open()
                backoff = min(backoff * 2, self._backoff_max)
                attempts += 1
                self.retries += 1
                # flight-recorder breadcrumb (obs/flight): reconnect
                # attempts are exactly the context a receiver-side
                # postmortem wants around a stall or deadlock
                record_event("transport_retry", actor=self.actor_id,
                             attempt=attempts)
                try:
                    self._connect()
                except (OSError, ConnectionError):
                    self._drop_sock()


class CoalescingSender(TransitionSender):
    """Actor-side block coalescing: many small ``send`` calls become ONE
    wire frame per block (the ingest plane's transport stage).

    Per-tick sends dominate the DCN plane's measured ~5,200 rows/s/core
    ceiling with framing + npz header overhead: each frame pays the
    length-prefixed header, the npz directory, and a receiver wakeup for
    a handful of rows. This subclass accumulates rows column-major into
    PREALLOCATED per-field arrays (allocated once from the first batch's
    shapes/dtypes — uint8 pixels stay packed; appends are slice copies,
    no per-row serialization) and flushes one contiguous frame when the
    block fills, when ``flush_interval`` elapses, or when the
    ``count_env_steps`` flag changes (the flag is per-frame on the wire,
    so HER relabels never merge with real env rows).

    Backpressure-aware sizing: the target block grows toward
    ``max_block`` while the previous flush observed TCP backpressure (a
    slow ``sendall`` means the learner is the bottleneck — bigger blocks
    amortize framing exactly when it matters) and decays toward
    ``min_block`` when sends are fast (small blocks keep ingest latency
    low when the plane has headroom).

    Degradation (``drop_on_timeout=True``): a flush whose frame times out
    is DROPPED — the rows are counted in ``dropped_rows`` and the target
    block snaps back to ``min_block`` so the next attempt ships
    sooner-and-smaller instead of letting a stalled receiver grow an
    ever-larger block behind an ever-longer wait. ``delivered_rows``
    counts the complement. This is the fleet-plane sender contract:
    shrink and shed, never block forever.
    """

    def __init__(self, host: str, port: int, actor_id: str = "remote",
                 connect_timeout: float = 10.0, secret: Optional[str] = None,
                 retry_timeout: float = 300.0, min_block: int = 64,
                 max_block: int = 4096, flush_interval: float = 0.25,
                 max_retries: Optional[int] = None,
                 drop_on_timeout: bool = False,
                 backoff_base: float = 0.2, backoff_max: float = 5.0,
                 backoff_seed: Optional[int] = None,
                 codec: str = "npz",
                 trace_sample: float = 0.0,
                 expect_generation: bool = False,
                 reconnect_jitter_s: float = 0.0):
        super().__init__(host, port, actor_id,
                         connect_timeout=connect_timeout, secret=secret,
                         retry_timeout=retry_timeout, max_retries=max_retries,
                         drop_on_timeout=drop_on_timeout,
                         backoff_base=backoff_base, backoff_max=backoff_max,
                         backoff_seed=backoff_seed, codec=codec,
                         trace_sample=trace_sample,
                         expect_generation=expect_generation,
                         reconnect_jitter_s=reconnect_jitter_s)
        self._min_block = max(1, int(min_block))
        self._max_block = max(self._min_block, int(max_block))
        self._target = self._min_block
        self._flush_interval = float(flush_interval)
        self._cols: Optional[list] = None  # per-field [max_block, ...] arrays
        self._fill = 0
        self._count_flag = True
        self._first_row_t = 0.0
        self._block_lock = threading.Lock()
        self.dropped_rows = 0
        self.delivered_rows = 0

    def _ensure_cols(self, batch: TransitionBatch) -> None:
        if self._cols is None:
            self._cols = [
                np.empty((self._max_block, *np.asarray(v).shape[1:]),
                         np.asarray(v).dtype)
                for v in batch
            ]

    def send(self, batch: TransitionBatch, count_env_steps: bool = True,
             timeout: float | None = None) -> bool:
        import time

        n = np.asarray(batch.obs).shape[0]
        if n == 0:
            return True
        ok = True
        with self._block_lock:
            self._ensure_cols(batch)
            if self._fill and count_env_steps != self._count_flag:
                ok = self._flush_locked() and ok  # flags can't share a frame
            self._count_flag = count_env_steps
            done = 0
            while done < n:
                if self._fill == 0:
                    self._first_row_t = time.monotonic()
                take = min(n - done, self._max_block - self._fill)
                for col, v in zip(self._cols, batch):
                    col[self._fill:self._fill + take] = \
                        np.asarray(v)[done:done + take]
                self._fill += take
                done += take
                if (self._fill >= self._target
                        or time.monotonic() - self._first_row_t
                        >= self._flush_interval):
                    ok = self._flush_locked() and ok
        return ok

    def flush(self) -> bool:
        """Ship any partially-filled block now (episode/shutdown
        boundaries). False when the frame was shed on timeout."""
        with self._block_lock:
            return self._flush_locked()

    def _flush_locked(self) -> bool:
        import time

        if not self._fill:
            return True
        frame = TransitionBatch(*[col[:self._fill] for col in self._cols])
        n = self._fill
        self._fill = 0
        t0 = time.monotonic()
        if not super().send(frame, count_env_steps=self._count_flag):
            # timed out under drop_on_timeout: shed the block and snap the
            # target back so the next attempt is small and immediate
            self.dropped_rows += n
            self._target = self._min_block
            return False
        self.delivered_rows += n
        dt = time.monotonic() - t0
        # > 2ms/KRow on the wire = kernel buffers pushing back: grow the
        # block so framing amortizes; fast sends decay toward min_block
        if dt > 0.002 * max(1.0, n / 1000.0):
            self._target = min(self._target * 2, self._max_block)
        else:
            self._target = max(self._target // 2, self._min_block)
        return True

    def close(self) -> None:
        try:
            self.flush()
        except (ConnectionError, OSError):
            pass  # peer already gone; pending rows are benign to lose
        super().close()


class ConnRegistry:
    """Tracking + teardown of a server's live peer connections, shared by
    ``TransitionReceiver`` and ``WeightServer``: a closed service must
    stop serving (clients observe the break and fail over to the
    replacement service), not just stop accepting."""

    def __init__(self):
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()

    def _register_conn(self, conn: socket.socket) -> None:
        with self._conns_lock:
            self._conns.add(conn)

    def _unregister_conn(self, conn: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(conn)

    def _shutdown_conns(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class TransitionReceiver(ConnRegistry):
    """Learner-side server: accepts actor connections, decodes frames, and
    forwards batches into a callback (normally ``ReplayService.add``).
    The callback receives ``(batch, actor_id, count_env_steps)``.

    Sharded mode (``num_shards=K``, the multi-core ingest plane): K
    listening sockets share the port via ``SO_REUSEPORT`` — the kernel
    spreads incoming connections across them, so accept/read work has no
    single hot socket — and every connection carries the shard index of
    the listener that accepted it (round-robin assignment from a single
    listener where ``SO_REUSEPORT`` is unavailable). With an
    ``on_payload`` callback set, frames are forwarded UNDECODED as
    ``(payload, shard, codec)`` so decode runs on the owning ingest
    shard's worker core (``ReplayService.add_payload``) instead of the
    connection thread; without it this class decodes both frame formats
    itself and calls ``on_batch`` exactly as before."""

    def __init__(
        self,
        on_batch: Callable[[TransitionBatch, str, bool], object],
        host: str = "127.0.0.1",
        port: int = 0,
        secret: Optional[str] = None,
        max_payload: int = MAX_PAYLOAD,
        num_shards: int = 1,
        on_payload: Optional[Callable[[bytes, int, str], object]] = None,
        generation: int | Callable[[], int] | None = None,
    ):
        super().__init__()
        self._on_batch = on_batch
        self._on_payload = on_payload
        # crash-recovery plane: when set (int or zero-arg callable), every
        # accepted connection is greeted with the CURRENT service
        # generation right after the auth handshake, so reconnecting
        # senders re-stamp their frames with the post-restart id
        self._generation = generation
        self._secret = secret
        self._max_payload = int(max_payload)
        # hostile/corrupt frames dropped (bad magic, oversize, decode
        # failure). Monotonic; reads are informational so no lock.
        self.frames_rejected = 0
        self.num_shards = max(1, int(num_shards))
        self._servers: list[socket.socket] = []
        self._rr = 0  # round-robin shard cursor (fallback path)
        self.reuseport = False
        bind_port = port
        for _ in range(self.num_shards):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            if self.num_shards > 1:
                try:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
                except (AttributeError, OSError):
                    # platform without SO_REUSEPORT: ONE listener,
                    # connections assigned to shards round-robin
                    if self._servers:
                        s.close()
                        break
            try:
                s.bind((host, bind_port))
            except OSError:
                s.close()
                if self._servers:
                    break  # fall back to the listeners we already have
                raise
            s.listen()
            bind_port = s.getsockname()[1]
            self._servers.append(s)
            if self.num_shards == 1:
                break
        self.reuseport = len(self._servers) == self.num_shards > 1
        self._server = self._servers[0]  # compat alias (close/tests)
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accept_threads = [
            threading.Thread(target=self._accept, args=(srv, i), daemon=True)
            for i, srv in enumerate(self._servers)
        ]
        for t in self._accept_threads:
            t.start()

    def _accept(self, server: socket.socket, listener_idx: int) -> None:
        try:
            while not self._stop.is_set():
                try:
                    server.settimeout(0.2)
                    conn, _ = server.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                if self.reuseport:
                    shard = listener_idx
                else:
                    shard = self._rr % self.num_shards
                    self._rr += 1
                # reap finished connection threads (a long-lived service
                # with a churning fleet otherwise grows this list without
                # bound)
                self._threads = [t for t in self._threads if t.is_alive()]
                self._register_conn(conn)
                t = threading.Thread(target=self._serve, args=(conn, shard),
                                     daemon=True)
                t.start()
                self._threads.append(t)
        except Exception as e:
            contained_crash("ingest.accept", e)

    def _serve(self, conn: socket.socket, shard: int = 0) -> None:
        try:
            self._serve_conn(conn, shard)
        except Exception as e:
            # a raising _on_payload/_on_batch callback must not silently
            # kill the connection thread
            contained_crash("ingest.serve", e)

    def _serve_conn(self, conn: socket.socket, shard: int = 0) -> None:
        try:
            with conn:
                if not server_handshake(conn, self._secret):
                    return  # unauthenticated peer; drop before reading frames
                if self._generation is not None:
                    gen = (self._generation() if callable(self._generation)
                           else self._generation)
                    conn.sendall(_GEN_GREETING.pack(
                        _MAGIC_GEN, int(gen) & 0xFFFFFFFF))
                while not self._stop.is_set():
                    header = _recv_exact(conn, _HEADER.size)
                    if header is None:
                        return
                    magic, length = _HEADER.unpack(header)
                    if (magic not in (_MAGIC, _MAGIC_RAW)
                            or length > self._max_payload):
                        # corrupt or hostile stream; drop the connection
                        self.frames_rejected += 1
                        return
                    payload = _recv_exact(conn, length)
                    if payload is None:
                        return
                    codec = "raw" if magic == _MAGIC_RAW else "npz"
                    if self._on_payload is not None:
                        # sharded plane: decode on the shard worker core
                        self._on_payload(payload, shard, codec)
                        continue
                    actor_id, batch, count = decode_frame(payload, codec)
                    self._on_batch(batch, actor_id, count)
        except (ProtocolError, struct.error, ValueError, TypeError):
            # hostile-but-well-framed payload rejected by decode_frame
            # (_raw_header unpack, np.dtype on a garbage name,
            # UnicodeDecodeError ⊂ ValueError): count it, drop the conn.
            # Must precede OSError — ProtocolError ⊂ ConnectionError.
            self.frames_rejected += 1
            return
        except OSError:
            return  # peer died mid-frame; not a rejection
        finally:
            self._unregister_conn(conn)

    def close(self) -> None:
        self._stop.set()
        for s in self._servers:
            try:
                s.close()
            except OSError:
                pass
        self._shutdown_conns()
        for t in self._threads:
            t.join(timeout=1.0)
