"""Actor->learner transition transport over sockets (the DCN plane).

The reference's only inter-process channel is OS shared memory on one host
(``torch.multiprocessing``, ``main.py:12,386-388``) — it cannot cross hosts.
SURVEY.md §5 mandates a real transport: actors on TPU-VM hosts stream
transition batches to the learner's replay service over the pod data
network, with backpressure.

Wire format (length-prefixed frames over TCP):
    [u32 magic][u32 payload_len][payload]
payload = npz-serialized TransitionBatch (+ actor id). TCP gives ordering
and backpressure for free; a slow learner applies backpressure through the
kernel socket buffers and the sender's bounded queue. Heartbeats ride the
same connection as empty batches.

Hardening: servers bind loopback by default (pass the DCN interface
explicitly for cross-host fleets), payload lengths are capped (the u32
frame length is peer-controlled — without a cap any peer could make the
receiver allocate 4 GiB), and an optional shared ``secret`` enables an
HMAC-SHA256 challenge-response handshake on connect so only authorized
actors can inject replay data (np.load is pickle-free, so the payloads
themselves cannot execute code).
"""

from __future__ import annotations

import hashlib
import hmac
import io
import os
import socket
import struct
import threading
from typing import Callable, Optional

import numpy as np

from d4pg_tpu.replay.uniform import TransitionBatch

_MAGIC = 0xD4F6
_HEADER = struct.Struct("!II")
_NONCE_LEN = 16
_MAC_LEN = 32  # sha256 digest
MAX_PAYLOAD = 64 << 20  # 64 MiB: far above any sane batch/param frame


def _hs_mac(secret: str, nonce: bytes) -> bytes:
    return hmac.new(secret.encode(), nonce, hashlib.sha256).digest()


def server_handshake(conn: socket.socket, secret: Optional[str],
                     timeout: float = 5.0) -> bool:
    """Server side of the connect handshake: send a fresh nonce, require
    HMAC-SHA256(secret, nonce) back. No-op (True) when no secret is set."""
    if not secret:
        return True
    nonce = os.urandom(_NONCE_LEN)
    prev = conn.gettimeout()
    conn.settimeout(timeout)
    try:
        conn.sendall(nonce)
        mac = _recv_exact(conn, _MAC_LEN)
        return mac is not None and hmac.compare_digest(
            mac, _hs_mac(secret, nonce))
    except OSError:
        return False
    finally:
        conn.settimeout(prev)


def client_handshake(sock: socket.socket, secret: Optional[str],
                     timeout: float = 5.0) -> None:
    """Client side: answer the server's nonce challenge."""
    if not secret:
        return
    prev = sock.gettimeout()
    sock.settimeout(timeout)
    try:
        nonce = _recv_exact(sock, _NONCE_LEN)
        if nonce is None:
            raise ConnectionError("server closed during handshake")
        sock.sendall(_hs_mac(secret, nonce))
    finally:
        sock.settimeout(prev)


def _encode(actor_id: str, batch: TransitionBatch,
            count_env_steps: bool = True) -> bytes:
    buf = io.BytesIO()
    np.savez(
        buf,
        actor_id=np.frombuffer(actor_id.encode(), np.uint8),
        obs=batch.obs,
        action=batch.action,
        reward=batch.reward,
        next_obs=batch.next_obs,
        done=batch.done,
        discount=batch.discount,
        # synthetic rows (HER relabels) must not inflate the learner's
        # env-step counter (ADVICE r1: (1+her_ratio)x inflation otherwise)
        count=np.uint8(count_env_steps),
    )
    payload = buf.getvalue()
    return _HEADER.pack(_MAGIC, len(payload)) + payload


def _decode(payload: bytes) -> tuple[str, TransitionBatch, bool]:
    with np.load(io.BytesIO(payload)) as z:
        actor_id = z["actor_id"].tobytes().decode()
        batch = TransitionBatch(
            obs=z["obs"], action=z["action"], reward=z["reward"],
            next_obs=z["next_obs"], done=z["done"], discount=z["discount"],
        )
        count = bool(z["count"]) if "count" in z.files else True
    return actor_id, batch, count


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class TransitionSender:
    """Actor-side client: connects to the learner host and streams batches."""

    def __init__(self, host: str, port: int, actor_id: str = "remote",
                 connect_timeout: float = 10.0, secret: Optional[str] = None):
        self.actor_id = actor_id
        self._sock = socket.create_connection((host, port), timeout=connect_timeout)
        client_handshake(self._sock, secret)
        self._sock.settimeout(None)
        self._lock = threading.Lock()

    def send(self, batch: TransitionBatch, count_env_steps: bool = True) -> None:
        data = _encode(self.actor_id, batch, count_env_steps)
        with self._lock:
            self._sock.sendall(data)

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()


class TransitionReceiver:
    """Learner-side server: accepts actor connections, decodes frames, and
    forwards batches into a callback (normally ``ReplayService.add``).
    The callback receives ``(batch, actor_id, count_env_steps)``."""

    def __init__(
        self,
        on_batch: Callable[[TransitionBatch, str, bool], object],
        host: str = "127.0.0.1",
        port: int = 0,
        secret: Optional[str] = None,
        max_payload: int = MAX_PAYLOAD,
    ):
        self._on_batch = on_batch
        self._secret = secret
        self._max_payload = int(max_payload)
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen()
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                self._server.settimeout(0.2)
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            with conn:
                if not server_handshake(conn, self._secret):
                    return  # unauthenticated peer; drop before reading frames
                while not self._stop.is_set():
                    header = _recv_exact(conn, _HEADER.size)
                    if header is None:
                        return
                    magic, length = _HEADER.unpack(header)
                    if magic != _MAGIC or length > self._max_payload:
                        return  # corrupt or hostile stream; drop the connection
                    payload = _recv_exact(conn, length)
                    if payload is None:
                        return
                    actor_id, batch, count = _decode(payload)
                    self._on_batch(batch, actor_id, count)
        except OSError:
            return  # peer died mid-frame (actor killed); just drop it

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=1.0)
