"""Replica→aggregator update transport: the multi-learner wire plane.

The third wire plane (after transitions in and weights out): a learner
replica ships its post-round params — stamped with the **basis version**
it computed against, its **epoch**, and the **store generation** it
believes is live — to the process that owns the ``Aggregator``, and gets
back the merge verdict (applied/fenced, new version, staleness weight).

Frame layout (request, client → server):

  [u32 0xD4AB][u32 replica][u32 epoch][u32 generation]
  [i64 basis_version][i64 step][i64 trace_id][f64 birth_ts]
  [u8 codec][u32 crc32][u32 len][payload]

payload = npz of the flattened param tree run through the weight
plane's v2 codec (``weight_plane.encode_flat``: raw f32 / bf16 / int8 —
the replica chooses per client, exactly like a weight puller does). The
crc32 covers the payload: a torn frame is detected, counted, shed —
never merged.

**Zero-decode fencing**: everything the server needs to fence a dead
replica's in-flight update — replica id, epoch, generation — travels in
the fixed 57-byte header (``update_frame_meta``), so a frame from a
fenced epoch is rejected before paying npz decode or crc over a
multi-MB payload. That is the replica-kill chaos hot path: kill fires
``Aggregator.fence_replica`` and the victim's last frame, replayed
verbatim, must bounce off the header check.

Ack (server → client):

  [u32 0xD4AB][u8 status][i64 version][i64 lag][f64 weight][u8 clipped]

status: 0 applied, 1 fenced, 2 torn (crc/format), 3 barrier timeout.

Tracing: when the recorder is armed, a sampled submit opens a span at
the replica (birth = encode instant), the server records ``admission``
on receipt and ``decode`` after the payload round-trip, then terminates
it: ``commit`` when the merge applies, ``shed`` when fenced or torn —
the same zero-orphan contract as the ingest and weight planes.
"""

from __future__ import annotations

import io
import socket
import threading
import time
import zipfile
import zlib

import numpy as np

# Frame shapes come from the declared wire registry (update-req
# "!IIIIqqqdBII" header with payload crc32, update-ack "!IBqqdB");
# see core/wire.py and ``python -m d4pg_tpu.lint --wire``.
from d4pg_tpu.core.wire import (
    MAGIC_UPDATE as _UPD_MAGIC,
    UPDATE_ACK as _UPD_ACK,
    UPDATE_HEADER as _UPD_HDR,
)
from d4pg_tpu.distributed.transport import (
    MAX_PAYLOAD,
    ConnRegistry,
    ProtocolError,
    ReconnectingClient,
    _recv_exact,
    server_handshake,
)
from d4pg_tpu.distributed.weight_plane import decode_flat, encode_flat
from d4pg_tpu.distributed.weight_server import _flatten, _unflatten
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.trace import RECORDER as TRACE, new_trace_id

STATUS_APPLIED = 0
STATUS_FENCED = 1
STATUS_TORN = 2
STATUS_TIMEOUT = 3
_STATUS_NAMES = {STATUS_APPLIED: "applied", STATUS_FENCED: "fenced",
                 STATUS_TORN: "torn", STATUS_TIMEOUT: "barrier_timeout"}
_STATUS_IDS = {v: k for k, v in _STATUS_NAMES.items()}


# ------------------------------------------------------------- codec ----

def encode_update(params, *, replica_id: int, epoch: int, generation: int,
                  basis_version: int, step: int = 0, codec: str = "f32",
                  trace_id: int = 0, birth_ts: float | None = None) -> bytes:
    """One wire frame for a replica submission (see module doc)."""
    flat = encode_flat(_flatten(params), codec)
    buf = io.BytesIO()
    np.savez(buf, **flat)
    payload = buf.getvalue()
    if len(payload) > MAX_PAYLOAD:
        raise ProtocolError(
            f"update payload {len(payload)}B exceeds MAX_PAYLOAD")
    header = _UPD_HDR.pack(
        _UPD_MAGIC, int(replica_id), int(epoch), int(generation),
        int(basis_version), int(step), int(trace_id),
        time.time() if birth_ts is None else float(birth_ts),
        ("f32", "bf16", "int8").index(codec),
        zlib.crc32(payload), len(payload))
    return header + payload


def update_frame_meta(frame: bytes) -> dict:
    """Header-only parse — the zero-decode fencing read. Validates magic
    and length bounds but deliberately NOT the crc (that would require
    touching the whole payload, defeating the point)."""
    if len(frame) < _UPD_HDR.size:
        raise ProtocolError(f"update frame truncated at {len(frame)}B")
    (magic, replica_id, epoch, generation, basis_version, step, trace_id,
     birth_ts, codec_id, crc, length) = _UPD_HDR.unpack_from(frame)
    if magic != _UPD_MAGIC:
        raise ProtocolError(f"bad update magic {magic:#x}")
    if length > MAX_PAYLOAD:
        raise ProtocolError(f"update payload {length}B exceeds MAX_PAYLOAD")
    if codec_id > 2:
        # must be a ProtocolError, not an IndexError out of the tuple
        # lookup below: _serve only contains wire-format exceptions
        raise ProtocolError(f"unknown update codec id {codec_id}")
    return {"replica_id": replica_id, "epoch": epoch,
            "generation": generation, "basis_version": basis_version,
            "step": step, "trace_id": trace_id, "birth_ts": birth_ts,
            "codec": ("f32", "bf16", "int8")[codec_id], "crc": crc,
            "len": length}


def decode_update(frame: bytes):
    """(meta, params) — crc-checked full decode; raises ``ProtocolError``
    on a torn or corrupt payload."""
    meta = update_frame_meta(frame)
    payload = frame[_UPD_HDR.size:]
    if len(payload) != meta["len"]:
        raise ProtocolError(
            f"update payload torn: {len(payload)}B of {meta['len']}B")
    if zlib.crc32(payload) != meta["crc"]:
        raise ProtocolError("update payload crc mismatch")
    with np.load(io.BytesIO(payload)) as z:
        flat = {k: z[k] for k in z.files}
    return meta, _unflatten(decode_flat(flat))


# ------------------------------------------------------------- server ----

class AggregatorServer(ConnRegistry):
    """Accepts replica connections and feeds their frames to an
    ``Aggregator``. One thread per connection (replica counts are small —
    single digits — so a thread per replica is the simple right thing);
    each submit is a strict request/ack round trip, which doubles as
    replica-side backpressure: a replica cannot run ahead of its own
    unmerged update."""

    def __init__(self, agg, host: str = "127.0.0.1", port: int = 0,
                 secret: str | None = None):
        super().__init__()
        self._agg = agg
        self._secret = secret
        self.frames = 0
        self.applied = 0
        self.fenced_header = 0   # zero-decode header fences
        self.fenced_submit = 0   # aggregator-level fences
        self.barrier_timeouts = 0
        self.torn = 0
        self.bytes_in = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen()
        self.port = self._server.getsockname()[1]
        self._stop = threading.Event()
        self._conn_threads: list[threading.Thread] = []
        self._thread = threading.Thread(target=self._accept, daemon=True)
        self._thread.start()

    def _accept(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    self._server.settimeout(0.2)
                    conn, _ = self._server.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                self._register_conn(conn)
                self._conn_threads = [t for t in self._conn_threads
                                      if t.is_alive()]
                t = threading.Thread(target=self._serve, args=(conn,),
                                     daemon=True)
                self._conn_threads.append(t)
                t.start()
        except Exception as e:
            contained_crash("updates.accept", e)

    def _handle_frame(self, frame: bytes) -> tuple[int, dict]:
        """(status_id, result) for one complete frame — shared by the
        socket path and tests that drive raw bytes."""
        self.frames += 1
        self.bytes_in += len(frame)
        tid = 0
        try:
            meta = update_frame_meta(frame)
            tid = meta["trace_id"]
            if tid:
                TRACE.begin(tid, meta["birth_ts"])
                TRACE.record_span(tid, "admission")
            live = self._agg.live_epoch(meta["replica_id"])
            if live != meta["epoch"]:
                # the chaos hot path: dead epoch bounced off the header,
                # payload never decoded
                self.fenced_header += 1
                if tid:
                    TRACE.terminal_shed(tid)
                record_event("update_header_fenced",
                             replica=meta["replica_id"],
                             epoch=meta["epoch"], live_epoch=live)
                return STATUS_FENCED, {"version": self._agg.version}
            try:
                meta, params = decode_update(frame)
            except (ProtocolError, ValueError, KeyError, TypeError, OSError,
                    zipfile.BadZipFile):
                # ProtocolError covers length/crc tears; the rest come out
                # of np.load/decode_flat on a crc-VALID but garbage body
                # (the sender checksummed corrupt bytes). Either way: torn,
                # counted, acked, connection stays alive.
                self.torn += 1
                if tid:
                    TRACE.terminal_shed(tid)
                record_event("update_torn", replica=meta["replica_id"])
                return STATUS_TORN, {"version": self._agg.version}
            if tid:
                TRACE.record_span(tid, "decode")
            result = self._agg.submit(
                meta["replica_id"], meta["epoch"], params,
                meta["basis_version"], step=meta["step"],
                generation=meta["generation"])
            status = _STATUS_IDS.get(result["status"], STATUS_FENCED)
            if status == STATUS_APPLIED:
                self.applied += 1
                if tid:
                    TRACE.mark_committed([tid])
            elif status == STATUS_TIMEOUT:
                self.barrier_timeouts += 1
                if tid:
                    TRACE.terminal_shed(tid)
            else:
                self.fenced_submit += 1
                if tid:
                    TRACE.terminal_shed(tid)
            return status, result
        except Exception as e:
            # an admitted frame must not vanish from the ledger, and the
            # span opened above must terminate before the raise escapes
            # (zero-orphan invariant, exception edge included)
            if tid:
                TRACE.terminal_shed(tid)
            record_event("update_frame_error", error=type(e).__name__)
            raise

    def _serve(self, conn: socket.socket) -> None:
        try:
            self._serve_conn(conn)
        except Exception as e:
            contained_crash("updates.serve", e)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            with conn:
                if not server_handshake(conn, self._secret):
                    return
                while not self._stop.is_set():
                    head = _recv_exact(conn, _UPD_HDR.size)
                    if head is None:
                        return
                    meta = update_frame_meta(head)
                    payload = _recv_exact(conn, meta["len"])
                    if payload is None:
                        return  # peer died mid-frame: TCP tears it for us
                    status, result = self._handle_frame(head + payload)
                    lag = result.get("lag")
                    conn.sendall(_UPD_ACK.pack(
                        _UPD_MAGIC, status, int(result.get("version", 0)),
                        -1 if lag is None else int(lag),
                        float(result.get("weight", 0.0)),
                        int(bool(result.get("clipped", False)))))
        except (OSError, ProtocolError):
            return  # conn-level fault: drop the connection, replica retries
        finally:
            self._unregister_conn(conn)

    def stats(self) -> dict:
        return {"frames": self.frames, "applied": self.applied,
                "fenced_header": self.fenced_header,
                "fenced_submit": self.fenced_submit,
                "barrier_timeouts": self.barrier_timeouts,
                "torn": self.torn, "bytes_in": self.bytes_in}

    def close(self) -> None:
        self._stop.set()
        try:
            self._server.close()
        except OSError:
            pass
        self._shutdown_conns()
        for t in self._conn_threads:
            t.join(timeout=2.0)
        self._conn_threads.clear()


# ------------------------------------------------------------- client ----

class UpdateClient(ReconnectingClient):
    """Replica-side submitter. ``submit`` matches the in-process
    ``Aggregator.submit`` verdict shape, so a ``LearnerReplica`` can use
    either interchangeably (``basis``/``register`` stay in-process —
    replicas and aggregator share the train process today; this client
    exists for the chaos harness and the eventual cross-host learner).

    The last encoded frame is retained (``last_frame``) so a supervisor
    can replay a killed replica's in-flight bytes verbatim — the chaos
    harness's fence-must-bounce probe."""

    def __init__(self, host: str, port: int, connect_timeout: float = 10.0,
                 secret: str | None = None, codec: str = "f32"):
        self.codec = codec
        self.last_frame: bytes | None = None
        self.acks = 0
        super().__init__(host, port, connect_timeout=connect_timeout,
                         secret=secret)

    def submit(self, replica_id: int, epoch: int, params, basis_version: int,
               step: int = 0, generation: int = 0,
               trace_id: int | None = None) -> dict:
        if trace_id is None:
            # birth_ts in the header carries the send instant; the span
            # itself opens server-side at admission (weight-plane idiom)
            trace_id = new_trace_id(replica_id) if TRACE.enabled else 0
        frame = encode_update(
            params, replica_id=replica_id, epoch=epoch,
            generation=generation, basis_version=basis_version, step=step,
            codec=self.codec, trace_id=trace_id)
        self.last_frame = frame
        return self.submit_frame(frame)

    def submit_frame(self, frame: bytes) -> dict:
        """Ship raw frame bytes (the chaos replay path) and await the
        ack. Transport faults raise ``ConnectionError`` — the caller
        (supervisor) owns the respawn policy."""
        self._check_open()
        with self._lock:
            if self._sock is None:
                self._connect()
            try:
                self._sock.sendall(frame)
                ack = _recv_exact(self._sock, _UPD_ACK.size)
            except OSError as e:
                self._drop_sock()
                raise ConnectionError(f"update submit failed: {e}") from e
            if ack is None:
                self._drop_sock()
                raise ConnectionError("aggregator closed during submit")
        magic, status, version, lag, weight, clipped = _UPD_ACK.unpack(ack)
        if magic != _UPD_MAGIC:
            raise ProtocolError(f"bad ack magic {magic:#x}")
        self.acks += 1
        return {"status": _STATUS_NAMES.get(status, "fenced"),
                "version": version, "lag": None if lag < 0 else lag,
                "weight": weight, "clipped": bool(clipped)}
