"""Weight-distribution plane: versioned delta broadcast, quantized
transport, relay fan-out, and generation fencing.

The ingest plane (actor -> learner) is sharded, chaos-tested, traced and
crash-fenced; this module gives its inverse — learner -> actor weight
sync — the same treatment. "Learn Atari in 21 minutes" (arXiv
1801.02852) shows parameter synchronization is THE bottleneck at large
actor fan-out, and IMPACT (arXiv 1912.00167) shows training tolerates
bounded weight staleness; the plane therefore optimizes bytes-per-pull
and measures staleness instead of pretending sync is free.

Wire protocol (v2; one port answers BOTH magics, so v1
``weight_server.WeightClient`` pullers never break):

  client sends  [u32 0xD4FC][i64 have_version][u32 have_generation]
                [u8 codec][u8 flags]                 (flags bit0: deltas ok)
  server replies[u32 0xD4FC][u8 kind][u32 crc32][u32 len][payload]
                (kind 0: not newer, len==0; kind 1: npz frame)

payload = npz of codec-encoded tensor entries plus metadata
(``__version__``/``__step__``/``__generation__``/``__codec__``/
``__kind__``/``__base_version__``/``__pub_ts__``/``__trace__``). The
crc32 covers the payload: a torn/truncated/corrupted frame is DETECTED
at the client, counted, and dropped — never accepted (the weight-chaos
acceptance bar: 0 torn versions accepted).

Delta encoding: the server keeps a bounded window of recent versions'
flattened params. A puller whose ``have_version`` is inside the window
(same generation) receives per-tensor deltas against its base: tensors
bitwise-identical to the base ship as a name in ``__same__`` (0 bytes);
changed tensors ship either a sparse XOR (u32 word indices + XOR words,
chosen when it is smaller) or the full tensor. XOR on raw bytes is
dtype-agnostic and EXACT: reconstruction is bitwise-identical to the
full snapshot, and ``verify=True`` (default) asserts exactly that on
every delta frame built (the delta oracle).

Quantized transport (opt-in PER CLIENT via the request's codec byte):
``bf16`` truncates f32 tensors to bfloat16 bits with round-to-nearest-
even (relative error <= ``BF16_REL_BOUND``); ``int8`` quantizes with a
per-tensor symmetric scale (absolute error <= scale/2). Metadata and
norm-stats keys (``__*``) and non-f32 tensors always travel raw —
acting statistics must be bitwise the learner's. ``verify=True`` checks
the declared bound on every tensor encoded (the quantization oracle).
Deltas compose with codecs: the window caches the ENCODED flat per
codec, and XOR deltas run over encoded bytes, so a quantized delta
reconstruction is bitwise-identical to the quantized full snapshot.

Generation fencing (the PR-7 machinery, carried by ``WeightStore``): a
restarted learner's store is constructed at ``generation+1``, versions
may rewind, and every frame is stamped ``(generation, version)``. The
server purges pre-crash window entries the moment it observes a newer
generation; clients reject any frame whose generation is below the
highest they have seen (and any non-newer version within a generation)
— so a relay can never serve a pre-crash version as current, and a
puller can never adopt one.

Relay fan-out: ``WeightRelay`` = a ``WeightPlaneClient`` pulling from an
upstream (learner or another relay), a local ``WeightStore`` republished
verbatim (version/generation/original publish timestamp pass through),
and a ``WeightPlaneServer`` serving peers the SAME wire protocol — so
trees of any depth compose from one building block and staleness
measured at a leaf is end-to-end.

Observability: every server publishes through the obs registry's
``weights`` provider (mirroring ``ingest_stats``): snapshots ingested,
frames served (full/delta/not-newer), bytes, delta hit-rate, oracle
tallies, and the pull->publish staleness histogram
(``weights.staleness_ms``). When the trace recorder is armed, each
honestly-served frame opens a span (birth = publish instant, admission
= serve instant) that the accepting client terminates (``commit``) or
the rejecting client sheds — the zero-orphan invariant the weight-chaos
artifact pins, with conn-teardown sweeping any frames in flight.
"""

from __future__ import annotations

import io
import json
import struct
import threading
import time
import weakref
import zipfile
import zlib
from collections import OrderedDict

import numpy as np

from d4pg_tpu.core.locking import TieredLock
from d4pg_tpu.distributed.transport import (
    MAX_PAYLOAD,
    ProtocolError,
    ReconnectingClient,
    _recv_exact,
    server_handshake,
)
from d4pg_tpu.distributed.weight_server import (
    _MAGIC as _V1_MAGIC,
    _REQ as _V1_REQ,
    _RESP as _V1_RESP,
    WeightServer,
    _flatten,
    _unflatten,
)
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.obs.trace import RECORDER as TRACE, TERMINALS, new_trace_id

# Frame shapes come from the declared wire registry (weights-v2 rows:
# _PLANE_REQ "!IqIBB" magic/have_version/have_gen/codec/flags,
# _PLANE_RESP "!IBII" magic/kind/crc32/len); see core/wire.py and
# ``python -m d4pg_tpu.lint --wire``.
from d4pg_tpu.core.wire import (
    MAGIC_WEIGHTS_V2 as _PLANE_MAGIC,
    WEIGHTS_V2_REQ as _PLANE_REQ,
    WEIGHTS_V2_RESP as _PLANE_RESP,
    WFLAG_DELTA as _FLAG_DELTA,
)

_KIND_NONE = 0
_KIND_FRAME = 1

CODECS = ("f32", "bf16", "int8")
_CODEC_ID = {name: i for i, name in enumerate(CODECS)}

# Declared quantization error bounds (the quantization oracle's and the
# tests' single source of truth). bf16 keeps 8 significand bits ->
# round-to-nearest relative error <= 2^-8 for normal values; the
# absolute fudge covers bf16's subnormal step (2^-133). int8 symmetric
# quantization rounds to the nearest multiple of the per-tensor scale.
BF16_REL_BOUND = 2.0 ** -8
BF16_ABS_FUDGE = 2.0 ** -133
INT8_HALF_STEPS = 0.5


# ------------------------------------------------------------ codecs ----

def f32_to_bf16(x: np.ndarray) -> np.ndarray:
    """f32 -> bfloat16 bit pattern (uint16), round-to-nearest-even."""
    bits = np.ascontiguousarray(x, dtype=np.float32).view(np.uint32)
    rounded = bits + 0x7FFF + ((bits >> 16) & 1)
    return (rounded >> 16).astype(np.uint16)


def bf16_to_f32(h: np.ndarray) -> np.ndarray:
    """bfloat16 bit pattern (uint16) -> f32 (exact: bf16 ⊂ f32)."""
    return (h.astype(np.uint32) << 16).view(np.float32)


def quantize_int8(x: np.ndarray) -> tuple[np.ndarray, float]:
    """Symmetric per-tensor int8: scale = max|x|/127 (1.0 for the
    all-zero tensor so dequant stays exact); |x - q*scale| <= scale/2."""
    amax = float(np.max(np.abs(x))) if x.size else 0.0
    scale = (amax / 127.0) or 1.0
    q = np.clip(np.rint(x / np.float32(scale)), -127, 127).astype(np.int8)
    return q, scale


def encode_flat(flat: dict[str, np.ndarray], codec: str
                ) -> dict[str, np.ndarray]:
    """Codec-encode a flattened param dict into wire tensors. Key
    prefixes mark the decode rule per tensor — explicit, so an original
    uint16/int8 tensor can never be mistaken for an encoded one:
    ``r:`` raw passthrough, ``h:`` bf16 bits, ``q:`` int8 + ``qs:``
    its f32 scale. Metadata/norm keys (``__*``) and non-f32 tensors are
    always raw."""
    if codec not in _CODEC_ID:
        raise ValueError(f"unknown weight codec {codec!r}")
    out: dict[str, np.ndarray] = {}
    for k, arr in flat.items():
        arr = np.asarray(arr)
        if codec == "f32" or k.startswith("__") or arr.dtype != np.float32:
            out[f"r:{k}"] = arr
        elif codec == "bf16":
            out[f"h:{k}"] = f32_to_bf16(arr)
        else:  # int8
            q, scale = quantize_int8(arr)
            out[f"q:{k}"] = q
            out[f"qs:{k}"] = np.float32(scale)
    return out


def decode_flat(enc: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Invert ``encode_flat`` (dequantizing to f32 where encoded)."""
    out: dict[str, np.ndarray] = {}
    for k, arr in enc.items():
        if k.startswith("r:"):
            out[k[2:]] = arr
        elif k.startswith("h:"):
            out[k[2:]] = bf16_to_f32(arr)
        elif k.startswith("q:"):
            out[k[2:]] = arr.astype(np.float32) * enc[f"qs:{k[2:]}"]
        elif k.startswith("qs:"):
            continue
        else:
            raise ProtocolError(f"unknown encoded-tensor prefix in {k!r}")
    return out


def quant_error_excess(flat: dict[str, np.ndarray],
                       enc: dict[str, np.ndarray]) -> float:
    """Max (error - declared bound) over all quantized tensors — the
    quantization oracle: <= 0 means every tensor honors its bound."""
    worst = -np.inf
    for k, arr in flat.items():
        x = np.asarray(arr, dtype=np.float32)
        if f"h:{k}" in enc:
            err = np.abs(bf16_to_f32(enc[f"h:{k}"]) - x)
            bound = BF16_REL_BOUND * np.abs(x) + BF16_ABS_FUDGE
        elif f"q:{k}" in enc:
            scale = float(enc[f"qs:{k}"])
            err = np.abs(enc[f"q:{k}"].astype(np.float32) * np.float32(scale)
                         - x)
            bound = np.full_like(x, INT8_HALF_STEPS * scale * (1 + 1e-6))
        else:
            continue
        if err.size:
            worst = max(worst, float(np.max(err - bound)))
    return worst if np.isfinite(worst) else 0.0


# ------------------------------------------------------------- delta ----

def _xor_words(a: bytes, b: bytes) -> np.ndarray:
    """XOR two equal-length byte strings as zero-padded u32 words."""
    pad = (-len(a)) % 4
    av = np.frombuffer(a + b"\0" * pad, dtype=np.uint32)
    bv = np.frombuffer(b + b"\0" * pad, dtype=np.uint32)
    return av ^ bv


def delta_encode(base: dict[str, np.ndarray], new: dict[str, np.ndarray]
                 ) -> dict[str, np.ndarray]:
    """Per-tensor delta of ``new`` against ``base``: bitwise-identical
    tensors ship by name only (``__same__``), changed tensors ship a
    sparse XOR (``xi:``/``xv:`` u32 word indices + XOR words) when that
    is smaller than the tensor, else the full tensor (``t:``). Tensors
    absent from the base (or with changed shape/dtype) ship full; base
    tensors absent from ``new`` are listed in ``__dropped__``.
    Reconstruction via ``delta_apply`` is EXACT — XOR over raw bytes is
    bitwise, whatever the dtype."""
    out: dict[str, np.ndarray] = {}
    same: list[str] = []
    for k, arr in new.items():
        b = base.get(k)
        if b is None or b.dtype != arr.dtype or b.shape != arr.shape:
            out[f"t:{k}"] = arr
            continue
        bb, nb = b.tobytes(), arr.tobytes()
        if bb == nb:
            same.append(k)
            continue
        w = _xor_words(bb, nb)
        idx = np.flatnonzero(w)
        if idx.size * 8 < len(nb):
            out[f"xi:{k}"] = idx.astype(np.uint32)
            out[f"xv:{k}"] = w[idx]
        else:
            out[f"t:{k}"] = arr
    dropped = [k for k in base if k not in new]
    out["__same__"] = np.frombuffer(json.dumps(same).encode(), np.uint8)
    out["__dropped__"] = np.frombuffer(json.dumps(dropped).encode(), np.uint8)
    return out


def delta_apply(base: dict[str, np.ndarray],
                entries: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Reconstruct the new encoded flat from ``base`` + a delta frame's
    entries (bitwise inverse of ``delta_encode``)."""
    same = set(json.loads(entries["__same__"].tobytes().decode()))
    dropped = set(json.loads(entries["__dropped__"].tobytes().decode()))
    out: dict[str, np.ndarray] = {
        k: v for k, v in base.items() if k in same and k not in dropped}
    for ek, v in entries.items():
        if ek.startswith("t:"):
            out[ek[2:]] = v
        elif ek.startswith("xi:"):
            k = ek[3:]
            b = base.get(k)
            if b is None:
                raise ProtocolError(f"delta references unknown base {k!r}")
            raw = b.tobytes()
            pad = (-len(raw)) % 4
            w = np.frombuffer(raw + b"\0" * pad, dtype=np.uint32).copy()
            w[v] ^= entries[f"xv:{k}"]
            out[k] = np.frombuffer(w.tobytes()[:len(raw)],
                                   dtype=b.dtype).reshape(b.shape)
    missing = same - set(base)
    if missing:
        raise ProtocolError(f"delta __same__ references unknown base "
                            f"tensors {sorted(missing)[:3]}")
    return out


# --------------------------------------------------------- wire chaos ----

class WeightWireChaos:
    """Seeded server-side fault injection for the weight wire (the
    weight-chaos harness's knobs): ``torn_prob`` corrupts a frame's
    payload bytes without fixing the crc32 (the client must detect and
    reject — a torn version accepted is an oracle failure);
    ``stale_prob`` serves a deliberately stale frame — a pre-crash
    generation from ``stash`` when one exists (fencing drill), else the
    oldest window version (version-monotonicity drill). Decisions draw
    from one seeded stream, so a seed replays the same fault script."""

    def __init__(self, torn_prob: float = 0.0, stale_prob: float = 0.0,
                 seed: int = 0):
        self.torn_prob = float(torn_prob)
        self.stale_prob = float(stale_prob)
        self._rng = np.random.default_rng(
            np.random.SeedSequence(seed, spawn_key=(0x77E1,)))
        self.stash: list[bytes] = []  # pre-crash full-frame payloads
        self.torn_injected = 0
        self.stale_injected = 0

    def decide(self) -> str:
        u_torn, u_stale, u_pick = self._rng.random(3)
        if u_torn < self.torn_prob:
            return "torn"
        if u_stale < self.stale_prob:
            return "stale"
        return "ok"

    def corrupt(self, payload: bytes) -> bytes:
        """Flip a seeded run of bytes mid-payload (crc left stale)."""
        buf = bytearray(payload)
        if buf:
            start = int(self._rng.integers(0, max(1, len(buf) - 8)))
            for i in range(start, min(len(buf), start + 8)):
                buf[i] ^= 0xA5
        return bytes(buf)

    def pick_stash(self) -> bytes | None:
        if not self.stash:
            return None
        return self.stash[int(self._rng.integers(0, len(self.stash)))]


# -------------------------------------------------------- the server ----

class WeightPlaneServer(WeightServer):
    """Versioned delta/quantized weight broadcast over one port.

    Answers BOTH wire protocols: v1 (``weight_server.WeightClient``,
    full npz snapshots, memoized by the base class) and the v2 plane
    protocol (codec + delta + generation fencing + crc). All plane state
    — the bounded version window, per-codec encoded flats, the frame
    memo — lives under the base class's ``wserve``-tier ``_frame_lock``
    with single-flight fill semantics: N pullers of (version, codec,
    base) cost one encode."""

    def __init__(self, store: WeightStore, host: str = "127.0.0.1",
                 port: int = 0, secret: str | None = None,
                 window: int = 8, verify: bool = True,
                 chaos: WeightWireChaos | None = None):
        # plane state first: the base ctor starts the accept thread, and
        # a connection arriving before these exist would race __init__
        self.window_size = max(1, int(window))
        self.verify = bool(verify)
        self.chaos = chaos
        self._window: OrderedDict[tuple[int, int], dict] = OrderedDict()
        self._enc: dict[tuple[int, int, str], dict] = {}
        self._frames: dict[tuple, tuple[bytes, int]] = {}
        self._latest: tuple[int, int] | None = None
        self.stats = {
            "snapshots_built": 0, "codec_encodes": 0, "frames_full": 0,
            "frames_delta": 0, "frames_not_newer": 0, "frames_v1": 0,
            "bytes_sent": 0, "bytes_delta": 0, "bytes_full": 0,
            "torn_injected": 0, "stale_injected": 0,
            "oracle_delta_checks": 0, "oracle_delta_failures": 0,
            "oracle_quant_checks": 0, "oracle_quant_failures": 0,
            "window_purged_generations": 0,
        }
        super().__init__(store, host=host, port=port, secret=secret)
        _SERVERS.add(self)

    # -- window + caches (all under _frame_lock) --------------------------

    def _refresh_locked(self) -> None:
        snap = self._store.snapshot_ex()
        if snap["params"] is None:
            return
        gen, version = snap["generation"], snap["version"]
        if self._latest == (gen, version):
            return
        if self._latest is not None:
            cur_gen, cur_ver = self._latest
            if (gen, version) <= (cur_gen, cur_ver) and gen <= cur_gen:
                return  # store rewound without a generation bump: ignore
            if gen > cur_gen:
                # generation fence: purge EVERY pre-crash entry the
                # moment the new generation is visible — a relay must
                # never serve a pre-crash version as current
                self._window.clear()
                self._enc.clear()
                self._frames.clear()
                self.stats["window_purged_generations"] += 1
                record_event("weight_gen_purge", old_gen=cur_gen, new_gen=gen)
        flat = _flatten(snap["params"])
        norm = snap["norm_stats"]
        if norm is not None:
            flat["__norm_mean__"] = np.asarray(norm[0])
            flat["__norm_std__"] = np.asarray(norm[1])
            if len(norm) > 2:
                flat["__norm_clip__"] = np.float64(norm[2])
        self._window[(gen, version)] = {
            "flat": flat, "step": snap["step"],
            "pub_ts": snap["published_ts"] or time.monotonic(),
        }
        self._latest = (gen, version)
        self.stats["snapshots_built"] += 1
        while len(self._window) > self.window_size:
            old_key, _ = self._window.popitem(last=False)
            self._enc = {k: v for k, v in self._enc.items()
                         if k[:2] != old_key}
            self._frames = {k: v for k, v in self._frames.items()
                            if k[:2] != old_key}

    def _encoded_locked(self, gen: int, version: int, codec: str) -> dict:
        key = (gen, version, codec)
        enc = self._enc.get(key)
        if enc is None:
            entry = self._window[(gen, version)]
            enc = self._enc[key] = encode_flat(entry["flat"], codec)
            self.stats["codec_encodes"] += 1
            if self.verify and codec != "f32":
                self.stats["oracle_quant_checks"] += 1
                if quant_error_excess(entry["flat"], enc) > 0:
                    self.stats["oracle_quant_failures"] += 1
                    record_event("weight_quant_oracle_fail",
                                 version=version, codec=codec)
        return enc

    def _frame_locked(self, gen: int, version: int, codec: str,
                      base_version: int) -> tuple[bytes, int, int]:
        """Build (or memo-hit) the serialized frame; returns
        (payload, kind, trace_id). ``base_version < 0`` means full."""
        key = (gen, version, codec, base_version)
        hit = self._frames.get(key)
        if hit is not None:
            payload, tid = hit
            kind = 1 if base_version >= 0 else 0
            return payload, kind, tid
        entry = self._window[(gen, version)]
        enc_new = self._encoded_locked(gen, version, codec)
        if base_version >= 0:
            enc_base = self._encoded_locked(gen, base_version, codec)
            entries = delta_encode(enc_base, enc_new)
            kind = 1
            if self.verify:
                # the delta oracle: reconstruction must be bitwise the
                # full snapshot, every frame, before it ever ships
                self.stats["oracle_delta_checks"] += 1
                rebuilt = delta_apply(enc_base, entries)
                ok = (rebuilt.keys() == enc_new.keys()
                      and all(rebuilt[k].tobytes() == enc_new[k].tobytes()
                              for k in enc_new))
                if not ok:
                    self.stats["oracle_delta_failures"] += 1
                    record_event("weight_delta_oracle_fail",
                                 version=version, base=base_version)
        else:
            entries = {f"t:{k}": v for k, v in enc_new.items()}
            kind = 0
        tid = new_trace_id()
        buf = io.BytesIO()
        np.savez(
            buf,
            __version__=np.int64(version),
            __step__=np.int64(entry["step"]),
            __generation__=np.int64(gen),
            __codec__=np.int64(_CODEC_ID[codec]),
            __kind__=np.int64(kind),
            __base_version__=np.int64(base_version),
            __pub_ts__=np.float64(entry["pub_ts"]),
            __trace__=np.uint64(tid),
            **entries,
        )
        payload = buf.getvalue()
        self._frames[key] = (payload, tid)
        return payload, kind, tid

    def reset_window(self) -> None:
        """Drop every cached version/frame (relay generation swap)."""
        with self._frame_lock:
            self._window.clear()
            self._enc.clear()
            self._frames.clear()
            self._latest = None

    def latest_full_payload(self, codec: str = "f32") -> bytes | None:
        """The latest full-frame payload — the weight-chaos harness
        stashes this before a learner kill so the restarted server can
        inject genuine pre-crash frames (the fencing drill)."""
        with self._frame_lock:
            self._refresh_locked()
            if self._latest is None:
                return None
            gen, version = self._latest
            payload, _, _ = self._frame_locked(gen, version, codec, -1)
            return payload

    # -- serving -----------------------------------------------------------

    def _respond(self, have_version: int, have_gen: int, codec: str,
                 want_delta: bool) -> tuple[bytes, int | None]:
        """One v2 response (header + payload) + the trace id to track as
        in-flight (None for not-newer / chaos-injected serves)."""
        with self._frame_lock:
            self._refresh_locked()
            if self._latest is None:
                return _PLANE_RESP.pack(_PLANE_MAGIC, _KIND_NONE, 0, 0), None
            gen, version = self._latest
            if gen == have_gen and version <= have_version:
                self.stats["frames_not_newer"] += 1
                return _PLANE_RESP.pack(_PLANE_MAGIC, _KIND_NONE, 0, 0), None
            injected = self.chaos.decide() if self.chaos is not None else "ok"
            if injected == "stale":
                payload = self._stale_payload_locked(codec)
                if payload is not None:
                    # valid crc, stale CONTENT: the client must fence it
                    # by generation/version, not by checksum
                    self.chaos.stale_injected += 1
                    self.stats["stale_injected"] += 1
                    head = _PLANE_RESP.pack(_PLANE_MAGIC, _KIND_FRAME,
                                            zlib.crc32(payload), len(payload))
                    return head + payload, None
            base = -1
            if (want_delta and gen == have_gen and 0 <= have_version < version
                    and (gen, have_version) in self._window):
                base = have_version
            payload, _, tid = self._frame_locked(gen, version, codec, base)
            if injected == "torn":
                self.chaos.torn_injected += 1
                self.stats["torn_injected"] += 1
                torn = self.chaos.corrupt(payload)
                # crc computed over the ORIGINAL bytes: detection is
                # guaranteed; no trace opens (the frame never validly
                # existed, so it must not be able to orphan)
                head = _PLANE_RESP.pack(_PLANE_MAGIC, _KIND_FRAME,
                                        zlib.crc32(payload), len(torn))
                return head + torn, None
            if base >= 0:
                self.stats["frames_delta"] += 1
                self.stats["bytes_delta"] += len(payload)
            else:
                self.stats["frames_full"] += 1
                self.stats["bytes_full"] += len(payload)
            self.stats["bytes_sent"] += len(payload)
            entry = self._window[(gen, version)]
            _STALENESS.observe(
                1e3 * max(0.0, time.monotonic() - entry["pub_ts"]))
            if TRACE.enabled:
                TRACE.begin(tid, entry["pub_ts"])
                TRACE.record_span(tid, "admission")
            head = _PLANE_RESP.pack(_PLANE_MAGIC, _KIND_FRAME,
                                    zlib.crc32(payload), len(payload))
            return head + payload, tid

    def _stale_payload_locked(self, codec: str) -> bytes | None:
        stashed = self.chaos.pick_stash()
        if stashed is not None:
            return stashed
        for key in self._window:
            if key != self._latest:
                gen, version = key
                payload, _, _ = self._frame_locked(gen, version, codec, -1)
                return payload
        return None

    def _serve(self, conn) -> None:
        """Dual-protocol serve loop: dispatch per-request on the magic.
        The per-conn ``outstanding`` list tracks honestly-served trace
        ids; the conn's NEXT request is the implicit ack (the protocol
        is strictly request/response per conn), and teardown sheds
        whatever is still in flight so no trace can orphan."""
        try:
            self._serve_plane_conn(conn)
        except Exception as e:
            contained_crash("weights.serve", e)

    def _serve_plane_conn(self, conn) -> None:
        outstanding: list[int] = []
        try:
            with conn:
                if not server_handshake(conn, self._secret):
                    return
                while not self._stop.is_set():
                    head = _recv_exact(conn, 4)
                    if head is None:
                        return
                    (magic,) = struct.unpack("!I", head)
                    if magic == _V1_MAGIC:
                        rest = _recv_exact(conn, _V1_REQ.size - 4)
                        if rest is None:
                            return
                        (have,) = struct.unpack("!q", rest)
                        payload = self._legacy_frame(have)
                        with self._frame_lock:
                            self.stats["frames_v1"] += 1
                        if payload is None:
                            conn.sendall(_V1_RESP.pack(_V1_MAGIC, 0))
                        else:
                            conn.sendall(_V1_RESP.pack(_V1_MAGIC, len(payload))
                                         + payload)
                        continue
                    if magic != _PLANE_MAGIC:
                        return
                    rest = _recv_exact(conn, _PLANE_REQ.size - 4)
                    if rest is None:
                        return
                    have_version, have_gen, codec_id, flags = struct.unpack(
                        "!qIBB", rest)
                    if codec_id >= len(CODECS):
                        return
                    outstanding.clear()  # implicit ack of prior frames
                    resp, tid = self._respond(have_version, have_gen,
                                              CODECS[codec_id],
                                              bool(flags & _FLAG_DELTA))
                    # Register the in-flight trace BEFORE the write: the
                    # admission span is already stamped, so a peer dying
                    # mid-sendall must still reach the teardown sweep.
                    if tid is not None:
                        outstanding.append(tid)
                    conn.sendall(resp)
        except OSError:
            return  # peer died mid-frame; teardown sweep handles traces
        finally:
            self._shed_outstanding(outstanding)
            self._unregister_conn(conn)

    @staticmethod
    def _shed_outstanding(tids: list[int]) -> None:
        if not tids or not TRACE.enabled:
            return
        table = TRACE.span_table()
        for tid in tids:
            spans = table.get(tid)
            if spans is None or not any(t in spans for t in TERMINALS):
                TRACE.terminal_shed(tid)

    def weight_stats(self) -> dict:
        """Consistent per-server snapshot (one lock round trip) — the
        ``weights`` provider sums these across live servers."""
        with self._frame_lock:
            out = dict(self.stats)
            out["window_len"] = len(self._window)
            out["frame_memo_len"] = len(self._frames)
            out["latest"] = self._latest
        out["frame_encodes_v1"] = self.frame_encodes
        served = out["frames_delta"] + out["frames_full"]
        out["delta_hit_rate"] = (round(out["frames_delta"] / served, 4)
                                 if served else None)
        return out


# The aggregate obs provider (mirrors the lock plane's module-level
# registration: the weight plane lives for the process). Per-instance
# snapshots are each taken under that instance's own lock; the sums are
# sums of per-server-consistent snapshots — the ingest_stats contract.
_SERVERS: "weakref.WeakSet[WeightPlaneServer]" = weakref.WeakSet()
_STALENESS = REGISTRY.histogram("weights.staleness_ms")


def _weights_snapshot() -> dict:
    totals: dict = {"servers": 0}
    for srv in list(_SERVERS):
        stats = srv.weight_stats()
        totals["servers"] += 1
        for k, v in stats.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                totals[k] = totals.get(k, 0) + v
    served = totals.get("frames_delta", 0) + totals.get("frames_full", 0)
    totals["delta_hit_rate"] = (round(totals.get("frames_delta", 0) / served,
                                      4) if served else None)
    totals["staleness_ms"] = _STALENESS.snapshot_dict()
    return totals


REGISTRY.register_provider("weights", _weights_snapshot)


# -------------------------------------------------------- the client ----

class WeightPlaneClient(ReconnectingClient):
    """v2 puller: codec + delta negotiation, crc verification, and
    generation fencing, with the same stale-degradation contract as the
    v1 ``WeightClient`` (a down server means acting on stale weights,
    not crashing; only ``down_timeout`` s of continuous unreachability
    raises). The client owns its sync state: ``version``/``generation``
    advance only on ACCEPTED frames, and every rejection (torn crc,
    fenced generation, non-newer version, missing delta base) is counted
    and sheds its trace — 0 torn versions accepted, by construction."""

    def __init__(self, host: str, port: int, codec: str = "f32",
                 delta: bool = True, connect_timeout: float = 10.0,
                 secret: str | None = None, down_timeout: float = 300.0,
                 reconnect_interval: float = 10.0):
        if codec not in _CODEC_ID:
            raise ValueError(f"unknown weight codec {codec!r}")
        self.codec = codec
        self._delta = bool(delta)
        self._down_timeout = down_timeout
        self._down_since: float | None = None
        self._ever_pulled = False
        self._reconnect_interval = reconnect_interval
        self._next_reconnect = 0.0
        self._enc: dict[str, np.ndarray] | None = None
        self.version = 0
        self.generation = 0
        self.step = 0
        self.norm_stats: tuple | None = None
        self.last_pub_ts = 0.0
        self.counters = {
            "pulls": 0, "accepts": 0, "not_newer": 0, "full_frames": 0,
            "delta_frames": 0, "bytes_received": 0, "torn_rejected": 0,
            "fenced_rejected": 0, "stale_rejected": 0, "delta_base_misses": 0,
        }
        super().__init__(host, port, connect_timeout, secret)

    def get_if_newer(self, have_version: int | None = None):
        """Pull if the server has anything newer than OUR state (the
        optional ``have_version`` is accepted for WeightClient interface
        compatibility but the fencing state is authoritative). Returns
        (version, params) or None."""
        with self._lock:
            self._check_open()
            if (self._sock is None and self._ever_pulled
                    and time.monotonic() < self._next_reconnect):
                return None
            try:
                if self._sock is None:
                    self._next_reconnect = (time.monotonic()
                                            + self._reconnect_interval)
                    self._connect()
                result = self._pull_frame()
                self._ever_pulled = True
                if self._down_since is not None:
                    record_event("weight_stale_exit",
                                 addr=f"{self._addr[0]}:{self._addr[1]}",
                                 down_s=round(
                                     time.monotonic() - self._down_since, 3))
                self._down_since = None
                return result
            except ProtocolError:
                self._drop_sock()
                raise
            except (OSError, ConnectionError):
                self._drop_sock()
                self._check_open()
                if not self._ever_pulled:
                    raise  # config/auth fault: no stale weights exist yet
                now = time.monotonic()
                if self._down_since is None:
                    self._down_since = now
                    record_event("weight_stale_enter",
                                 addr=f"{self._addr[0]}:{self._addr[1]}",
                                 have_version=self.version)
                if now - self._down_since > self._down_timeout:
                    raise ConnectionError(
                        f"weight server unreachable for "
                        f"{self._down_timeout:.0f}s at "
                        f"{self._addr[0]}:{self._addr[1]}")
                return None

    def _pull_frame(self):
        """One request/response + frame validation; caller holds _lock."""
        self.counters["pulls"] += 1
        delta_ok = self._delta and self._enc is not None
        self._sock.sendall(_PLANE_REQ.pack(
            _PLANE_MAGIC, self.version, self.generation,
            _CODEC_ID[self.codec], _FLAG_DELTA if delta_ok else 0))
        head = _recv_exact(self._sock, _PLANE_RESP.size)
        if head is None:
            raise ConnectionError("weight server closed the connection")
        magic, kind, crc, length = _PLANE_RESP.unpack(head)
        if magic != _PLANE_MAGIC or length > MAX_PAYLOAD:
            raise ProtocolError("corrupt weight stream")
        # a well-formed header proves handshake + protocol are good:
        # arm stale-degradation even if THIS frame turns out torn (a
        # first-ever torn pull is transient damage, not a config fault)
        self._ever_pulled = True
        if kind == _KIND_NONE:
            self.counters["not_newer"] += 1
            return None
        payload = _recv_exact(self._sock, length)
        if payload is None:
            raise ConnectionError("truncated weight payload")
        self.counters["bytes_received"] += len(payload)
        if zlib.crc32(payload) != crc:
            # torn/corrupted frame: DETECTED, counted, never accepted;
            # drop the socket (the stream may be desynced) and degrade
            # to stale weights like any transient failure
            self.counters["torn_rejected"] += 1
            record_event("weight_torn_rejected",
                         addr=f"{self._addr[0]}:{self._addr[1]}",
                         bytes=len(payload))
            raise ConnectionError("weight frame failed crc (torn payload)")
        return self._accept(payload)

    def _accept(self, payload: bytes):
        try:
            with np.load(io.BytesIO(payload)) as z:
                meta_gen = int(z["__generation__"])
                version = int(z["__version__"])
                kind = int(z["__kind__"])
                base_version = int(z["__base_version__"])
                tid = int(z["__trace__"])
                entries = {k: z[k] for k in z.files if not k.startswith("__")}
                entries["__same__"] = (z["__same__"] if "__same__" in z.files
                                       else np.frombuffer(b"[]", np.uint8))
                entries["__dropped__"] = (z["__dropped__"]
                                          if "__dropped__" in z.files
                                          else np.frombuffer(b"[]", np.uint8))
                step = int(z["__step__"])
                pub_ts = float(z["__pub_ts__"])
        except (ValueError, KeyError, OSError, zipfile.BadZipFile) as e:
            # crc-valid but unparseable body (the sender corrupted it
            # BEFORE checksumming, or a hostile peer checksummed
            # garbage): detected, counted, never adopted. Raise
            # ConnectionError so get_if_newer degrades to stale weights
            # exactly like a torn frame instead of crashing the actor.
            self.counters["torn_rejected"] += 1
            record_event("weight_torn_rejected",
                         addr=f"{self._addr[0]}:{self._addr[1]}",
                         bytes=len(payload), parse_error=type(e).__name__)
            raise ConnectionError(
                f"weight frame unparseable after crc pass: {e}") from e
        if meta_gen < self.generation:
            # generation fence: a pre-crash frame can NEVER be adopted,
            # whatever its version number claims
            self.counters["fenced_rejected"] += 1
            record_event("weight_fence_rejected", frame_gen=meta_gen,
                         have_gen=self.generation, version=version)
            self._shed(tid)
            return None
        if meta_gen == self.generation and version <= self.version:
            self.counters["stale_rejected"] += 1
            self._shed(tid)
            return None
        if kind == _KIND_FRAME and base_version >= 0:
            if (meta_gen != self.generation or self._enc is None
                    or base_version != self.version):
                # delta against a base we no longer hold (or from a
                # different generation): force a full pull next time
                self.counters["delta_base_misses"] += 1
                self._enc = None
                self.version = 0
                self._shed(tid)
                return None
            enc = delta_apply(self._enc, entries)
            self.counters["delta_frames"] += 1
        else:
            enc = {k[2:]: v for k, v in entries.items() if k[:2] == "t:"}
            self.counters["full_frames"] += 1
        if meta_gen > self.generation:
            record_event("weight_gen_adopted", old_gen=self.generation,
                         new_gen=meta_gen, version=version)
        if self._delta:
            self._enc = enc
        flat = decode_flat(enc)
        norm_mean = flat.pop("__norm_mean__", None)
        norm_std = flat.pop("__norm_std__", None)
        norm_clip = flat.pop("__norm_clip__", None)
        if norm_mean is not None:
            self.norm_stats = (norm_mean, norm_std)
            if norm_clip is not None:
                self.norm_stats += (float(norm_clip),)
        self.version = version
        self.generation = meta_gen
        self.step = step
        self.last_pub_ts = pub_ts
        self.counters["accepts"] += 1
        if TRACE.enabled:
            TRACE.record_span(tid, "commit")
        return version, _unflatten(flat)

    @staticmethod
    def _shed(tid: int) -> None:
        if TRACE.enabled:
            TRACE.terminal_shed(tid)


# --------------------------------------------------------- the relay ----

class WeightRelay:
    """One fan-out node: pull from an upstream (learner or relay), cache
    locally, serve downstream peers the same wire protocol. Trees of any
    depth compose from this one block — version, generation and the
    ORIGINAL publish timestamp pass through verbatim, so fencing and
    staleness are end-to-end properties of the tree.

    Generation swaps are fenced twice: the puller client refuses
    pre-crash frames outright, and on an adoption the relay purges its
    server's cached window BEFORE republishing (``wrelay`` ->
    ``wserve`` -> ``wstore`` tier descent), so there is no instant at
    which a downstream pull can observe a pre-crash version served as
    current."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1", port: int = 0,
                 secret: str | None = None, poll_interval: float = 0.02,
                 window: int = 8, down_timeout: float = 300.0,
                 chaos: WeightWireChaos | None = None):
        self._relay_lock = TieredLock("wrelay")
        self._gen = 0
        self.pulls_ok = 0
        self.gen_adoptions = 0
        # relays pull full-precision with deltas: quantization is a
        # leaf-client choice, re-quantizing per hop would compound error
        self._client = WeightPlaneClient(
            upstream_host, upstream_port, codec="f32", delta=True,
            secret=secret, down_timeout=down_timeout,
            reconnect_interval=min(1.0, poll_interval * 10))
        self._store = WeightStore()
        self._server = WeightPlaneServer(self._store, host=host, port=port,
                                         secret=secret, window=window,
                                         chaos=chaos)
        self.port = self._server.port
        self._poll_interval = float(poll_interval)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    @property
    def generation(self) -> int:
        return self._gen  # plain int read; written under _relay_lock

    def _poll(self) -> None:
        try:
            self._poll_loop()
        except Exception as e:
            contained_crash("weights.relay_poll", e)

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                res = self._client.get_if_newer()
            except (ConnectionError, OSError, ProtocolError):
                res = None  # degrade stale; the client rate-limits retries
            if res is not None:
                version, params = res
                with self._relay_lock:
                    if self._client.generation > self._gen:
                        self._gen = self._client.generation
                        self.gen_adoptions += 1
                        # purge BEFORE republish: no window in which the
                        # server could hand out a pre-crash frame next
                        # to a post-crash store state
                        self._server.reset_window()
                    self.pulls_ok += 1
                    self._store.publish_versioned(
                        params, version, self._client.step,
                        norm_stats=self._client.norm_stats,
                        generation=self._client.generation,
                        publish_ts=self._client.last_pub_ts)
            self._stop.wait(self._poll_interval)

    def weight_stats(self) -> dict:
        return self._server.weight_stats()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._server.close()
        self._client.close()
