"""Actor workers: env stepping + exploration + transition streaming.

Replaces the acting half of the reference's ``Worker``/``addExperienceToBuffer``
(``main.py:137-185, 188-368``): where the reference steps one env with
batch-1 inference and writes into a process-private buffer, the actor here
steps a vectorized pool with one batched jit'd policy call per tick, folds
n-step transitions, and streams them to the central replay service.

Since the serving plane landed, this module is the COMPOSITION layer:
the policy-query half (weight pulls, exploration noise, epsilon decay,
device pinning) lives in ``serving/client.py`` behind the
``PolicyClient`` interface, and the env-stepping half lives in
``serving/lane.py`` (``VectorActorLane``). ``ActorWorker`` wires a
local client to a lane — bitwise the pre-split behavior, pinned by the
serving parity oracle — and ``GoalActorWorker`` drives the same client
through whole-episode HER rollouts. ``ActorConfig`` and the acting
device helpers are re-exported from their new home for compatibility.

Actors are stateless-restartable: everything an actor owns (envs, noise,
n-step window) is rebuilt on restart; replay and weights live with the
learner (SURVEY.md §5 failure-detection note).
"""

from __future__ import annotations

import threading

import numpy as np

from d4pg_tpu.envs.her import her_relabel
from d4pg_tpu.envs.vector import EnvPool
from d4pg_tpu.envs.wrappers import flatten_goal_obs, rescale_action
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.replay.uniform import TransitionBatch
from d4pg_tpu.serving.client import (  # noqa: F401 — compatibility re-exports
    ActorConfig,
    LocalPolicyClient,
    act_device_scope,
    put_params_on,
    resolve_act_device,
)
from d4pg_tpu.serving.lane import VectorActorLane


class _BaseActor:
    """Transition-sink bookkeeping around one ``PolicyClient``.

    The policy machinery (weight pulls, noise, epsilon) lives in the
    client; the underscored delegate methods and properties below keep
    the pre-split surface (``_epsilon``, ``_ou``, ``_maybe_pull_weights``,
    ``_explore_actions``) working for subclasses and tests."""

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        service: ReplayService,
        weights: WeightStore,
        seed: int = 0,
        obs_norm=None,
        policy=None,
    ):
        self.actor_id = actor_id
        self.config = config
        self.cfg = actor_cfg
        self.service = service
        self.weights = weights
        self.policy = policy if policy is not None else LocalPolicyClient(
            config, actor_cfg, weights, seed=seed, obs_norm=obs_norm)
        self._stop = threading.Event()
        self.env_steps = 0
        # Degradation accounting: ``service.add`` returning False (ingest
        # backpressure past its timeout) or a drop_on_timeout transport
        # shedding a frame means replay rows were LOST — benign for
        # ingest, but it must be a counted, surfaced event (the fleet
        # plane's no-silent-loss rule), never a crash or a silent pass.
        self.dropped_batches = 0

    # -- policy delegates (pre-split surface) -------------------------------
    @property
    def obs_norm(self):
        return self.policy.obs_norm

    @obs_norm.setter
    def obs_norm(self, value) -> None:
        self.policy.obs_norm = value

    @property
    def _epsilon(self) -> float:
        return self.policy.epsilon

    @property
    def _version(self) -> int:
        return self.policy.version

    @property
    def _params(self):
        return getattr(self.policy, "params", None)

    @property
    def _ou(self):
        return getattr(self.policy, "_ou", None)

    def _maybe_pull_weights(self) -> bool:
        return self.policy.pull()

    def _explore_actions(self, obs: np.ndarray) -> np.ndarray:
        """Noisy policy actions for a [B, obs_dim] batch; uniform random
        before the first weight publish (warmup, ``main.py:200-207``)."""
        return self.policy.actions(obs)

    def _reset_noise(self, done_mask: np.ndarray) -> None:
        self.policy.reset_noise(done_mask)

    def _decay_epsilon(self) -> None:
        self.policy.decay_epsilon()

    def stop(self) -> None:
        self._stop.set()


class ActorWorker(_BaseActor):
    """Acting loop over a vectorized EnvPool with n-step folding.

    A thin composition since the serving split: the loop itself is
    ``serving.lane.VectorActorLane`` (shared stop event, shared policy
    client), so the legacy per-process actor and the serving plane's
    lanes run LITERALLY the same code. ``run`` stays resumable: the pool
    is reset once, and both the episode state and the n-step window
    persist across calls.
    """

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        pool: EnvPool,
        service: ReplayService,
        weights: WeightStore,
        seed: int = 0,
        obs_dtype=None,
        obs_norm=None,
        policy=None,
    ):
        self._lane = None
        super().__init__(actor_id, config, actor_cfg, service, weights, seed,
                         obs_norm=obs_norm, policy=policy)
        self.pool = pool
        self._lane = VectorActorLane(
            actor_id, config, actor_cfg, pool, service,
            obs_dtype=obs_dtype, policy=self.policy, stop=self._stop)

    def run(self, max_steps: int) -> int:
        """Collect ``max_steps`` pool ticks (E transitions per tick)."""
        return self._lane.run(max_steps)

    # counters live with the lane; these views keep the legacy surface
    @property
    def env_steps(self) -> int:
        return self._lane.env_steps if self._lane is not None else 0

    @env_steps.setter
    def env_steps(self, value: int) -> None:
        if self._lane is not None:
            self._lane.env_steps = int(value)

    @property
    def dropped_batches(self) -> int:
        return self._lane.dropped_batches if self._lane is not None else 0

    @dropped_batches.setter
    def dropped_batches(self, value: int) -> None:
        if self._lane is not None:
            self._lane.dropped_batches = int(value)

    @property
    def _obs(self):
        return self._lane._obs

    @property
    def _folder(self):
        return self._lane._folder


class GoalActorWorker(_BaseActor):
    """Actor for goal-conditioned dict-obs envs with HER relabeling.

    Rolls whole episodes on a single env, streams the original 1-step
    transitions plus future-strategy relabels — the fixed version of
    ``addExperienceToBuffer`` (``main.py:137-185``).
    """

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        env,
        service: ReplayService,
        weights: WeightStore,
        her_ratio: float = 0.8,
        rng_seed: int = 0,
        seed: int = 0,
        obs_norm=None,
    ):
        super().__init__(actor_id, config, actor_cfg, service, weights, seed,
                         obs_norm=obs_norm)
        self.env = env
        self.her_ratio = her_ratio
        self._np_rng = np.random.default_rng(rng_seed)
        # The policy lives in tanh range (-1, 1); the env may not. The
        # reference wraps EVERY worker env — HER included — in
        # NormalizeAction (``main.py:190``, ``normalize_env.py:5-8``); round 1
        # stepped the raw tanh action here while the Evaluator rescaled,
        # giving training and eval different dynamics on any goal env whose
        # action box isn't (-1, 1). Stored transitions keep the tanh-space
        # action, matching EnvPool/Evaluator.
        self._act_low = np.asarray(env.action_space.low, np.float32)
        self._act_high = np.asarray(env.action_space.high, np.float32)
        # gymnasium 1.x wrappers (TimeLimit, OrderEnforcing) do NOT forward
        # arbitrary attributes, so the GoalEnv's compute_reward (the
        # ``main.py:177`` relabeling contract) must be taken from the
        # unwrapped env when the handle is wrapped.
        self._compute_reward = (
            env.compute_reward if hasattr(env, "compute_reward")
            else env.unwrapped.compute_reward
        )

    def run_episode(self, max_steps: int) -> int:
        env = self.env
        self._maybe_pull_weights()
        obs_dict, _ = env.reset()
        raw_obs, achieved, actions, next_raw, rewards, dones = [], [], [], [], [], []
        achieved.append(np.asarray(obs_dict["achieved_goal"], np.float32).copy())
        for _ in range(max_steps):
            flat = flatten_goal_obs(obs_dict)
            if self.obs_norm is not None:
                flat = self.obs_norm.normalize(flat)
            a = self._explore_actions(flat[None])[0]
            nobs_dict, r, term, trunc, info = env.step(
                rescale_action(a, self._act_low, self._act_high)
            )
            raw_obs.append(np.asarray(obs_dict["observation"], np.float32).copy())
            actions.append(a)
            next_raw.append(np.asarray(nobs_dict["observation"], np.float32).copy())
            rewards.append(r)
            done = bool(info.get("is_success", term))
            dones.append(float(done))
            achieved.append(np.asarray(nobs_dict["achieved_goal"], np.float32).copy())
            obs_dict = nobs_dict
            self.env_steps += 1
            if done or term or trunc:
                break
        T = len(actions)
        goal = np.asarray(obs_dict["desired_goal"], np.float32)
        raw_obs_a = np.stack(raw_obs)
        next_raw_a = np.stack(next_raw)
        actions_a = np.stack(actions).astype(np.float32)
        dones_a = np.asarray(dones, np.float32)
        goal_tiled = np.tile(goal, (T, 1))
        originals = TransitionBatch(
            obs=np.concatenate([raw_obs_a, goal_tiled], -1).astype(np.float32),
            action=actions_a,
            reward=np.asarray(rewards, np.float32) * self.cfg.reward_scale,
            next_obs=np.concatenate([next_raw_a, goal_tiled], -1).astype(np.float32),
            done=dones_a,
            discount=(self.cfg.gamma * (1.0 - dones_a)).astype(np.float32),
        )
        relabeled = her_relabel(
            raw_obs_a, np.stack(achieved), actions_a, next_raw_a,
            self._compute_reward, self._np_rng, self.her_ratio, self.cfg.gamma,
        )
        relabeled = relabeled._replace(
            reward=relabeled.reward * self.cfg.reward_scale)
        # both batches stream RAW: the ReplayService drain normalizes at
        # insert (and folds them into the statistics — original AND
        # relabeled rows are what the networks train on, so goal dims get
        # stats from desired and achieved goals alike)
        if not self.service.add(originals, actor_id=self.actor_id):
            self.dropped_batches += 1
        # relabels are synthetic rows, not fresh env interaction: keep them
        # out of the env_steps counter (it is logged and checkpointed)
        if not self.service.add(relabeled, actor_id=self.actor_id,
                                count_env_steps=False):
            self.dropped_batches += 1
        self._reset_noise(np.array([True]))  # episode boundary: zero OU state
        self._decay_epsilon()
        return T
