"""Actor workers: env stepping + exploration + transition streaming.

Replaces the acting half of the reference's ``Worker``/``addExperienceToBuffer``
(``main.py:137-185, 188-368``): where the reference steps one env with
batch-1 inference and writes into a process-private buffer, the actor here
steps a vectorized pool with one batched jit'd policy call per tick, folds
n-step transitions, and streams them to the central replay service. Weights
are pulled from the ``WeightStore`` when a new version appears (the
reference pulls from shared memory every train call, ``ddpg.py:247``).

Actors are stateless-restartable: everything an actor owns (envs, noise,
n-step window) is rebuilt on restart; replay and weights live with the
learner (SURVEY.md §5 failure-detection note).
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.envs.her import her_relabel
from d4pg_tpu.envs.normalizer import FrozenNormalizer, RunningMeanStd
from d4pg_tpu.envs.vector import EnvPool
from d4pg_tpu.envs.wrappers import flatten_goal_obs, rescale_action
from d4pg_tpu.core.noise import ou
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.learner.update import act, act_ou
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.replay.nstep import NStepFolder
from d4pg_tpu.replay.uniform import TransitionBatch


@dataclasses.dataclass
class ActorConfig:
    epsilon_0: float = 0.3  # the reference's live, never-decayed eps (C5)
    min_epsilon: float = 0.01
    epsilon_horizon: int = 5000  # episodes to decay over (random_process.py:13)
    n_step: int = 3
    gamma: float = 0.99
    reward_scale: float = 1.0
    weight_poll_every: int = 1  # pool ticks between version checks
    # Exploration process. The reference exposes --ou_theta/--ou_sigma/--ou_mu
    # but never wires OU in (SURVEY.md C6 — constructed nowhere live); here
    # noise='ou' actually runs the temporally-correlated process.
    noise: str = "gaussian"  # 'gaussian' | 'ou'
    # Probability of replacing the policy action with a uniform random one,
    # per env per tick (the HER recipe's epsilon-greedy component — sparse
    # goal tasks need undirected exploration that additive Gaussian noise
    # around a confident wrong policy cannot provide). 0 = reference
    # behavior (additive noise only, random_process.py:16-18).
    random_eps: float = 0.0
    ou_theta: float = 0.25
    ou_sigma: float = 0.05
    ou_mu: float = 0.0
    ou_dt: float = 0.01
    # Where actor inference runs. Acting is latency-bound batch-E inference
    # dispatched every pool tick; on a TPU host every tick would round-trip
    # PCIe (or a remote tunnel) for microseconds of MLP compute, serializing
    # the env loop on transfer latency and contending with the learner's
    # dispatch queue. 'cpu' (default) pins the policy forward to the host
    # CPU backend — the D4PG production shape: the accelerator belongs to
    # the learner, actors run on TPU-VM host cores. 'default' uses the
    # default backend (worth it only for big conv encoders + wide pools).
    device: str = "cpu"  # 'cpu' | 'default'

    def __post_init__(self):
        if self.noise not in ("gaussian", "ou"):
            raise ValueError(f"unknown noise process {self.noise!r}")
        if self.device not in ("cpu", "default"):
            raise ValueError(f"unknown actor device {self.device!r}")


def resolve_act_device(kind: str):
    """Pinned inference device for an acting/eval component: the host CPU
    backend for ``'cpu'`` (see ``ActorConfig.device``), None (follow the
    default backend) for ``'default'``. Shared by actors and the Evaluator
    so the placement policy lives in one place."""
    if kind not in ("cpu", "default"):
        raise ValueError(f"unknown actor device {kind!r}")
    if kind != "cpu":
        return None
    # local_devices, not devices: under jax.distributed the global device
    # list starts with process 0's devices, so devices("cpu")[0] on any
    # other process is NON-addressable and acting there either errors or
    # produces arrays this process cannot read.
    return jax.local_devices(backend="cpu")[0]


def act_device_scope(device):
    """Thread-local default-device scope for a pinned device (no-op scope
    when following the default backend)."""
    if device is None:
        return contextlib.nullcontext()
    return jax.default_device(device)


def put_params_on(device, params):
    """Move published params onto the pinned device. Publishes may carry
    accelerator arrays (the fused learner publishes device params);
    committed arrays would drag the acting computation back onto the
    learner's chip."""
    if device is None:
        return params
    return jax.device_put(params, device)


class _BaseActor:
    """Weight-pulling + epsilon-decay machinery shared by actor kinds."""

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        service: ReplayService,
        weights: WeightStore,
        seed: int = 0,
        obs_norm=None,
    ):
        self.actor_id = actor_id
        self.config = config
        self.cfg = actor_cfg
        self.service = service
        self.weights = weights
        # READ-ONLY normalizer view for the policy input (the networks are
        # trained on standardized rows — the ReplayService's drain thread
        # owns the statistics and normalizes at insert). In-process actors
        # share the service's RunningMeanStd; remote/spawned actors receive
        # a FrozenNormalizer refreshed from the weight channel (below).
        # Transitions are ALWAYS streamed raw.
        self.obs_norm = obs_norm
        self._act_device = resolve_act_device(actor_cfg.device)
        with self._device_scope():
            self._key = jax.random.key(seed)
        self._version = 0
        self._params = None
        self._epsilon = actor_cfg.epsilon_0
        self._explore_rng = np.random.default_rng(seed + 17)
        self._episodes = 0
        self._ou = None  # lazily-sized OU state when cfg.noise == 'ou'
        self._stop = threading.Event()
        self.env_steps = 0
        # Degradation accounting: ``service.add`` returning False (ingest
        # backpressure past its timeout) or a drop_on_timeout transport
        # shedding a frame means replay rows were LOST — benign for
        # ingest, but it must be a counted, surfaced event (the fleet
        # plane's no-silent-loss rule), never a crash or a silent pass.
        self.dropped_batches = 0

    def _device_scope(self):
        """Context placing this actor's jax dispatches on its pinned device
        (thread-local, so actor threads don't disturb the learner's default
        placement). No-op scope when following the default backend."""
        return act_device_scope(self._act_device)

    def _maybe_pull_weights(self) -> bool:
        got = self.weights.get_if_newer(self._version)
        if got is not None:
            self._version, params = got
            self._params = put_params_on(self._act_device, params)
            # Remote/spawned actors: the weight payload piggybacks the
            # learner's normalization statistics (WeightClient.norm_stats).
            # An in-process RunningMeanStd handle stays authoritative.
            ns = getattr(self.weights, "norm_stats", None)
            if ns is not None and not isinstance(self.obs_norm, RunningMeanStd):
                if self.obs_norm is None:
                    self.obs_norm = FrozenNormalizer(*ns)
                else:
                    self.obs_norm.set(*ns)
            return True
        return False

    def _explore_actions(self, obs: np.ndarray) -> np.ndarray:
        """Noisy policy actions for a [B, obs_dim] batch; uniform random
        before the first weight publish (warmup, ``main.py:200-207``)."""
        with self._device_scope():
            return self._explore_actions_inner(obs)

    def _explore_actions_inner(self, obs: np.ndarray) -> np.ndarray:
        self._key, ka = jax.random.split(self._key)
        if self._params is None:
            return np.asarray(
                jax.random.uniform(ka, (obs.shape[0], self.config.act_dim),
                                   minval=-1.0, maxval=1.0)
            )
        if self.cfg.noise == "ou":
            if self._ou is None or self._ou.x.shape[0] != obs.shape[0]:
                self._ou = ou.init(self.config.act_dim, (obs.shape[0],))
            actions, self._ou = act_ou(
                self.config, self._params, jnp.asarray(obs), self._ou, ka,
                epsilon=self._epsilon, theta=self.cfg.ou_theta,
                mu=self.cfg.ou_mu, sigma=self.cfg.ou_sigma, dt=self.cfg.ou_dt,
            )
            actions = np.asarray(actions)
        else:
            actions = np.asarray(
                act(self.config, self._params, jnp.asarray(obs), ka,
                    self._epsilon)
            )
        if self.cfg.random_eps > 0.0:
            rng = self._explore_rng
            mask = rng.random(actions.shape[0]) < self.cfg.random_eps
            if mask.any():
                actions = np.array(actions)  # jax->np output is read-only
                actions[mask] = rng.uniform(
                    -1.0, 1.0, (int(mask.sum()), actions.shape[1])
                ).astype(actions.dtype)
        return actions

    def _reset_noise(self, done_mask: np.ndarray) -> None:
        """Zero the OU state of envs whose episode ended
        (``random_process.py:41-45`` resets x on episode reset)."""
        if self._ou is not None and done_mask.any():
            with self._device_scope():  # keep the OU state on the pinned device
                keep = jnp.asarray(~done_mask, jnp.float32)[:, None]
                self._ou = self._ou._replace(x=self._ou.x * keep)

    def _decay_epsilon(self) -> None:
        """eps = min + (eps0-min) * exp(-5k/horizon) on episode end — the
        decay the reference defines but never runs (``random_process.py:
        19-21``, call commented at ``main.py:366``)."""
        self._episodes += 1
        c = self.cfg
        self._epsilon = c.min_epsilon + (c.epsilon_0 - c.min_epsilon) * float(
            np.exp(-5.0 * self._episodes / c.epsilon_horizon)
        )

    def stop(self) -> None:
        self._stop.set()


class ActorWorker(_BaseActor):
    """Acting loop over a vectorized EnvPool with n-step folding.

    ``run`` is resumable: the pool is reset once, and both the episode state
    and the n-step window persist across calls — a cycle boundary in the
    training loop must NOT restart episodes or drop pending window entries
    (stale entries stitched across a reset would corrupt transitions).
    """

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        pool: EnvPool,
        service: ReplayService,
        weights: WeightStore,
        seed: int = 0,
        obs_dtype=None,
        obs_norm=None,
    ):
        super().__init__(actor_id, config, actor_cfg, service, weights, seed,
                         obs_norm=obs_norm)
        self.pool = pool
        self._folder = NStepFolder(
            actor_cfg.n_step, actor_cfg.gamma, pool.num_envs,
            config.obs_spec, config.act_dim, obs_dtype=obs_dtype,
        )
        self._obs: np.ndarray | None = None

    def run(self, max_steps: int) -> int:
        """Collect ``max_steps`` pool ticks (E transitions per tick)."""
        if self._obs is None:
            self._obs = self.pool.reset()
            self._folder.reset()
        obs = self._obs
        self._maybe_pull_weights()
        for tick in range(max_steps):
            if self._stop.is_set():
                break
            if tick % self.cfg.weight_poll_every == 0:
                self._maybe_pull_weights()
            if self.obs_norm is not None:
                actions = self._explore_actions(self.obs_norm.normalize(obs))
            else:
                actions = self._explore_actions(obs)
            out = self.pool.step(actions)
            folded = self._folder.step(
                obs, actions, out.reward * self.cfg.reward_scale,
                out.final_obs, out.terminated, out.truncated,
            )
            if not self.service.add(folded, actor_id=self.actor_id):
                self.dropped_batches += 1
            done_any = out.terminated | out.truncated
            self._reset_noise(done_any)
            for _ in range(int(done_any.sum())):
                self._decay_epsilon()
            obs = out.obs
            self.env_steps += self.pool.num_envs
        self._obs = obs
        return self.env_steps


class GoalActorWorker(_BaseActor):
    """Actor for goal-conditioned dict-obs envs with HER relabeling.

    Rolls whole episodes on a single env, streams the original 1-step
    transitions plus future-strategy relabels — the fixed version of
    ``addExperienceToBuffer`` (``main.py:137-185``).
    """

    def __init__(
        self,
        actor_id: str,
        config: D4PGConfig,
        actor_cfg: ActorConfig,
        env,
        service: ReplayService,
        weights: WeightStore,
        her_ratio: float = 0.8,
        rng_seed: int = 0,
        seed: int = 0,
        obs_norm=None,
    ):
        super().__init__(actor_id, config, actor_cfg, service, weights, seed,
                         obs_norm=obs_norm)
        self.env = env
        self.her_ratio = her_ratio
        self._np_rng = np.random.default_rng(rng_seed)
        # The policy lives in tanh range (-1, 1); the env may not. The
        # reference wraps EVERY worker env — HER included — in
        # NormalizeAction (``main.py:190``, ``normalize_env.py:5-8``); round 1
        # stepped the raw tanh action here while the Evaluator rescaled,
        # giving training and eval different dynamics on any goal env whose
        # action box isn't (-1, 1). Stored transitions keep the tanh-space
        # action, matching EnvPool/Evaluator.
        self._act_low = np.asarray(env.action_space.low, np.float32)
        self._act_high = np.asarray(env.action_space.high, np.float32)
        # gymnasium 1.x wrappers (TimeLimit, OrderEnforcing) do NOT forward
        # arbitrary attributes, so the GoalEnv's compute_reward (the
        # ``main.py:177`` relabeling contract) must be taken from the
        # unwrapped env when the handle is wrapped.
        self._compute_reward = (
            env.compute_reward if hasattr(env, "compute_reward")
            else env.unwrapped.compute_reward
        )

    def run_episode(self, max_steps: int) -> int:
        env = self.env
        self._maybe_pull_weights()
        obs_dict, _ = env.reset()
        raw_obs, achieved, actions, next_raw, rewards, dones = [], [], [], [], [], []
        achieved.append(np.asarray(obs_dict["achieved_goal"], np.float32).copy())
        for _ in range(max_steps):
            flat = flatten_goal_obs(obs_dict)
            if self.obs_norm is not None:
                flat = self.obs_norm.normalize(flat)
            a = self._explore_actions(flat[None])[0]
            nobs_dict, r, term, trunc, info = env.step(
                rescale_action(a, self._act_low, self._act_high)
            )
            raw_obs.append(np.asarray(obs_dict["observation"], np.float32).copy())
            actions.append(a)
            next_raw.append(np.asarray(nobs_dict["observation"], np.float32).copy())
            rewards.append(r)
            done = bool(info.get("is_success", term))
            dones.append(float(done))
            achieved.append(np.asarray(nobs_dict["achieved_goal"], np.float32).copy())
            obs_dict = nobs_dict
            self.env_steps += 1
            if done or term or trunc:
                break
        T = len(actions)
        goal = np.asarray(obs_dict["desired_goal"], np.float32)
        raw_obs_a = np.stack(raw_obs)
        next_raw_a = np.stack(next_raw)
        actions_a = np.stack(actions).astype(np.float32)
        dones_a = np.asarray(dones, np.float32)
        goal_tiled = np.tile(goal, (T, 1))
        originals = TransitionBatch(
            obs=np.concatenate([raw_obs_a, goal_tiled], -1).astype(np.float32),
            action=actions_a,
            reward=np.asarray(rewards, np.float32) * self.cfg.reward_scale,
            next_obs=np.concatenate([next_raw_a, goal_tiled], -1).astype(np.float32),
            done=dones_a,
            discount=(self.cfg.gamma * (1.0 - dones_a)).astype(np.float32),
        )
        relabeled = her_relabel(
            raw_obs_a, np.stack(achieved), actions_a, next_raw_a,
            self._compute_reward, self._np_rng, self.her_ratio, self.cfg.gamma,
        )
        relabeled = relabeled._replace(
            reward=relabeled.reward * self.cfg.reward_scale)
        # both batches stream RAW: the ReplayService drain normalizes at
        # insert (and folds them into the statistics — original AND
        # relabeled rows are what the networks train on, so goal dims get
        # stats from desired and achieved goals alike)
        if not self.service.add(originals, actor_id=self.actor_id):
            self.dropped_batches += 1
        # relabels are synthetic rows, not fresh env interaction: keep them
        # out of the env_steps counter (it is logged and checkpointed)
        if not self.service.add(relabeled, actor_id=self.actor_id,
                                count_env_steps=False):
            self.dropped_batches += 1
        self._reset_noise(np.array([True]))  # episode boundary: zero OU state
        self._decay_epsilon()
        return T
