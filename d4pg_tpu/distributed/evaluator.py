"""Evaluator: periodic greedy rollouts against the latest published weights.

Parity: the reference's evaluator process (``global_model_eval``,
``main.py:103-134``): copy global weights, run a greedy episode, track the
0.95/0.05 EWMA of returns, repeat — plus the per-cycle 10-trial eval with
success-rate (``main.py:309-347``). Here the evaluator pulls from the
``WeightStore`` (no shared memory) and reports through a metrics callback
instead of appending to a process-local list the parent never sees
(the reference's ``global_returns`` bug, SURVEY.md C17).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from d4pg_tpu.envs.wrappers import flatten_goal_obs, rescale_action
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.learner.update import act_deterministic
from d4pg_tpu.distributed.weights import WeightStore

EWMA_OLD, EWMA_NEW = 0.95, 0.05  # main.py:131


class Evaluator:
    def __init__(
        self,
        config: D4PGConfig,
        env_fn: Callable[[], object],
        weights: WeightStore,
        max_steps: int = 1000,
        goal_conditioned: bool = False,
    ):
        self.config = config
        self.env = env_fn()
        self.weights = weights
        self.max_steps = max_steps
        self.goal_conditioned = goal_conditioned
        self.ewma_return: Optional[float] = None
        low = np.asarray(self.env.action_space.low, np.float32)
        high = np.asarray(self.env.action_space.high, np.float32)
        self._low, self._high = low, high

    def _greedy_episode(self, params, seed: int | None = None) -> tuple[float, bool]:
        reset_kw = {"seed": seed} if seed is not None else {}
        obs, _ = self.env.reset(**reset_kw)
        total, success = 0.0, False
        for _ in range(self.max_steps):
            flat = flatten_goal_obs(obs)
            a = np.asarray(
                act_deterministic(self.config, params, jnp.asarray(flat[None]))
            )[0]
            obs, r, term, trunc, info = self.env.step(
                rescale_action(a, self._low, self._high)
            )
            total += float(r)
            success = success or bool(info.get("is_success", False))
            if term or trunc:
                break
        return total, success

    def evaluate(self, n_trials: int = 10, seed: int | None = None) -> dict:
        """Run n greedy trials; returns metrics incl. EWMA'd return and
        success rate (``main.py:309-353``)."""
        _, params = self.weights.get()
        if params is None:
            raise RuntimeError("no weights published yet")
        returns, successes = [], []
        for i in range(n_trials):
            ep_seed = None if seed is None else seed + i
            ret, suc = self._greedy_episode(params, ep_seed)
            returns.append(ret)
            successes.append(suc)
        avg = float(np.mean(returns))
        if self.ewma_return is None:
            self.ewma_return = avg
        else:
            self.ewma_return = EWMA_OLD * self.ewma_return + EWMA_NEW * avg
        return {
            "avg_test_reward": avg,
            "ewma_test_reward": self.ewma_return,
            "success_rate": float(np.mean(successes)),
            "learner_step": self.weights.step,
        }
