"""Evaluator: periodic greedy rollouts against the latest published weights.

Parity: the reference's evaluator process (``global_model_eval``,
``main.py:103-134``): copy global weights, run a greedy episode, track the
0.95/0.05 EWMA of returns, repeat — plus the per-cycle 10-trial eval with
success-rate (``main.py:309-347``). Here the evaluator pulls from the
``WeightStore`` (no shared memory) and reports through a metrics callback
instead of appending to a process-local list the parent never sees
(the reference's ``global_returns`` bug, SURVEY.md C17).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

import numpy as np

from d4pg_tpu.envs.wrappers import flatten_goal_obs, rescale_action
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.learner.state import D4PGConfig
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.serving.client import ActorConfig, LocalPolicyClient

EWMA_OLD, EWMA_NEW = 0.95, 0.05  # main.py:131


class Evaluator:
    def __init__(
        self,
        config: D4PGConfig,
        env_fn: Callable[[], object],
        weights: WeightStore,
        max_steps: int = 1000,
        goal_conditioned: bool = False,
        device: str = "cpu",
        obs_norm=None,
    ):
        self.config = config
        self.env = env_fn()
        self.weights = weights
        self.max_steps = max_steps
        self.goal_conditioned = goal_conditioned
        # shared RunningMeanStd: the policy was trained on normalized obs,
        # so greedy eval must apply the same (current) statistics — read
        # only, never updated from eval rollouts
        self.obs_norm = obs_norm
        self.ewma_return: Optional[float] = None
        low = np.asarray(self.env.action_space.low, np.float32)
        high = np.asarray(self.env.action_space.high, np.float32)
        self._low, self._high = low, high
        # Greedy rollouts are batch-1 inference per env step — pinned to the
        # host CPU backend by default for the same reason as ActorConfig
        # .device: a per-step accelerator round trip costs more than the MLP
        # forward, and eval must not contend with the learner's chip. Since
        # the serving split, the query path is the same PolicyClient the
        # actors use (greedy mode) instead of a duplicated inline dispatch.
        self.policy = LocalPolicyClient(
            config, ActorConfig(device=device), weights)

    def _device_scope(self):
        return self.policy._device_scope()

    def _greedy_episode(self, seed: int | None = None) -> tuple[float, bool]:
        reset_kw = {"seed": seed} if seed is not None else {}
        obs, _ = self.env.reset(**reset_kw)
        total, success = 0.0, False
        for _ in range(self.max_steps):
            flat = flatten_goal_obs(obs)
            if self.obs_norm is not None:
                flat = self.obs_norm.normalize(flat)
            a = self.policy.greedy_actions(flat[None])[0]
            obs, r, term, trunc, info = self.env.step(
                rescale_action(a, self._low, self._high)
            )
            total += float(r)
            success = success or bool(info.get("is_success", False))
            if term or trunc:
                break
        return total, success

    def evaluate(self, n_trials: int = 10, seed: int | None = None) -> dict:
        """Run n greedy trials; returns metrics incl. EWMA'd return and
        success rate (``main.py:309-353``)."""
        # Snapshot step WITH the params: the learner may publish again while
        # the rollouts run, and ``learner_step`` must describe the weights
        # actually evaluated (it feeds the eval_lag_steps metric).
        # snapshot_pull adopts the store's CURRENT params regardless of
        # version — eval must not skip a re-publish of the same version.
        _, published_step = self.policy.snapshot_pull()
        returns, successes = [], []
        for i in range(n_trials):
            ep_seed = None if seed is None else seed + i
            ret, suc = self._greedy_episode(ep_seed)
            returns.append(ret)
            successes.append(suc)
        avg = float(np.mean(returns))
        if self.ewma_return is None:
            self.ewma_return = avg
        else:
            self.ewma_return = EWMA_OLD * self.ewma_return + EWMA_NEW * avg
        return {
            "avg_test_reward": avg,
            "ewma_test_reward": self.ewma_return,
            "success_rate": float(np.mean(successes)),
            "learner_step": published_step,
        }


class AsyncEvaluator:
    """Concurrent evaluation off the learner thread.

    The reference evaluates in a SEPARATE process while training continues
    (``main.py:395-397``); round 1 ran ``Evaluator.evaluate`` inline on the
    learner thread, stalling every cycle for the rollouts. This wrapper owns
    a background thread: the learner ``request()``s an eval (non-blocking;
    coalesced if one is already running) and reads the most recent completed
    result via ``latest()``. Results carry the ``learner_step`` the weights
    were published at, so the logged ``eval_lag_steps`` is observable.
    """

    def __init__(self, evaluator: Evaluator):
        self._ev = evaluator
        self._requests: queue.Queue = queue.Queue(maxsize=1)
        self._latest: Optional[dict] = None
        self._lock = threading.Lock()
        # Accepted-but-not-finished request count. Incremented in request()
        # BEFORE the queue put and decremented only after the eval (or its
        # failure) completes, so wait() cannot slip through the window
        # between the worker's dequeue and the start of the rollouts.
        self._outstanding = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def request(self, n_trials: int, seed: int | None = None) -> bool:
        """Enqueue an eval against the CURRENT WeightStore contents. Returns
        False (dropped) if an eval is already queued — the learner never
        waits."""
        with self._lock:
            self._outstanding += 1
        try:
            self._requests.put_nowait((n_trials, seed))
            return True
        except queue.Full:
            with self._lock:
                self._outstanding -= 1
            return False

    def latest(self) -> Optional[dict]:
        """Most recent completed eval metrics (None until the first one)."""
        with self._lock:
            return None if self._latest is None else dict(self._latest)

    def wait(self, timeout: float = 300.0) -> Optional[dict]:
        """Drain pending requests and return the final metrics (shutdown /
        end-of-training path)."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if self._outstanding == 0:
                    break
            time.sleep(0.01)
        return self.latest()

    def _loop(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    n_trials, seed = self._requests.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    result = self._ev.evaluate(n_trials, seed=seed)
                    with self._lock:
                        self._latest = result
                except Exception as e:  # noqa: BLE001 — eval crash must not kill training
                    print(f"evaluator failed: {e!r}", flush=True)
                finally:
                    with self._lock:
                        self._outstanding -= 1
        except Exception as e:
            contained_crash("evaluator.loop", e)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)
