"""Weight-chaos fleet harness: the broadcast plane under fire.

The ingest harness (``fleet/harness.py``) proves the actor->learner
plane survives drops, duplication, stalls and learner kills; this module
is the mirror drill for the learner->actor weight plane
(``distributed/weight_plane.py``). One run stands up a learner publisher
behind a ``WeightPlaneServer``, a relay chain of configurable depth, and
N puller clients spread across every tier with a mix of codecs, then
injects the weight plane's fault set:

  - **stale pulls** — the server serves deliberately old frames (from
    the pre-crash stash after a kill, else the oldest window version);
    clients must fence them by (generation, version), never adopt them.
  - **torn payloads** — served frames are corrupted without fixing the
    crc; clients must detect, count, and drop every one.
  - **relay crash mid-fan-out** — a relay dies and is rebuilt on the
    same port; downstream pullers degrade stale and reconverge.
  - **learner kill during broadcast** — the learner store+server die and
    restart at ``generation+1`` on the same port with a REWOUND version
    counter; the restarted server's chaos stash carries genuine
    pre-crash frames so fencing is exercised against real bytes.

Three oracles gate the run (the acceptance bar the bench artifact pins):

  1. **ledger**: every accepted (generation, version) pair must have
     actually been published — an accepted pair outside the publish
     ledger means corrupt or fabricated weights got through (0 torn
     versions accepted). Per puller the accepted sequence must be
     monotone: generation never decreases, version strictly increases
     within a generation (no pre-crash frame adopted as current).
  2. **trace**: with the wire-to-grad recorder at sample 1.0, every
     honestly-served frame must terminate (client commit or shed, conn
     teardown sweeping in-flight frames) — 0 orphans.
  3. **locks**: the run executes under lock-hierarchy record mode —
     0 new violations across the wrelay/wserve/wstore tiers.

The delta/quantization oracles run inside the servers themselves
(``verify=True``) and their tallies surface in the report.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from d4pg_tpu.core import locking
from d4pg_tpu.distributed.weight_plane import (
    CODECS,
    WeightPlaneClient,
    WeightPlaneServer,
    WeightRelay,
    WeightWireChaos,
)
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import percentile_summary
from d4pg_tpu.obs.trace import RECORDER as TRACE


@dataclasses.dataclass(frozen=True)
class WeightChaosConfig:
    """One weight-chaos run. Probabilities are per served frame; the
    kill counts are scheduled at seeded-jittered instants across the
    run, so a (config, seed) pair replays the same fault script."""

    n_pullers: int = 64
    relay_depth: int = 2
    duration_s: float = 8.0
    publish_hz: float = 20.0
    pull_hz: float = 25.0
    torn_prob: float = 0.04
    stale_prob: float = 0.04
    learner_kills: int = 1
    relay_kills: int = 1
    window: int = 8
    param_dim: int = 64
    seed: int = 0

    def kill_schedule(self, kills: int, lane: int) -> list[float]:
        """Seeded kill offsets (s): nominally even across the middle
        80% of the run, each jittered +-25% of its slot."""
        if kills <= 0:
            return []
        rng = np.random.default_rng(
            np.random.SeedSequence(self.seed, spawn_key=(0xD4C4, lane)))
        span = 0.8 * self.duration_s
        slot = span / kills
        return sorted(0.1 * self.duration_s + (i + 0.5) * slot
                      + float(rng.uniform(-0.25, 0.25)) * slot
                      for i in range(kills))


class _Publisher:
    """The synthetic learner: publishes seeded param mutations at
    ``publish_hz`` into whatever store currently backs the learner port,
    and keeps the ledger of every (generation, version) ever published
    — the harness's accepted-frames oracle checks against it."""

    def __init__(self, cfg: WeightChaosConfig):
        self._cfg = cfg
        rng = np.random.default_rng(
            np.random.SeedSequence(cfg.seed, spawn_key=(0xD4C5,)))
        d = cfg.param_dim
        self._rng = rng
        self._params = {
            "actor": {"w0": rng.normal(size=(d, d)).astype(np.float32),
                      "b0": rng.normal(size=(d,)).astype(np.float32),
                      "w1": rng.normal(size=(d, d)).astype(np.float32)},
        }
        self.store = WeightStore()
        self.published: set[tuple[int, int]] = set()
        self.publishes = 0
        self._pub_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _mutate(self) -> None:
        # sparse mutation most publishes (exercises the sparse-XOR delta
        # arm), occasional full refresh (the full-tensor arm)
        w = self._params["actor"]["w0"]
        if self._rng.random() < 0.15:
            self._params["actor"]["w0"] = self._rng.normal(
                size=w.shape).astype(np.float32)
        else:
            i = int(self._rng.integers(0, w.shape[0]))
            w[i] += self._rng.normal(size=w.shape[1]).astype(np.float32)
        self._params["actor"]["b0"] += np.float32(0.001)

    def publish_once(self) -> None:
        with self._pub_lock:
            self._mutate()
            store = self.store
            version = store.publish(self._params, step=self.publishes,
                                    to_host=False)
            self.published.add((store.generation, version))
            self.publishes += 1

    def swap_store(self, store: WeightStore) -> None:
        with self._pub_lock:
            self.store = store

    def _run(self) -> None:
        try:
            interval = 1.0 / self._cfg.publish_hz
            while not self._stop.is_set():
                self.publish_once()
                self._stop.wait(interval)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.weight_publisher", e)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class _Puller:
    """One actor-side puller lane: pulls at ``pull_hz``, records every
    accepted (generation, version) + its end-to-end adopt lag."""

    def __init__(self, index: int, port: int, codec: str,
                 cfg: WeightChaosConfig):
        self.index = index
        self.client = WeightPlaneClient(
            "127.0.0.1", port, codec=codec, delta=True,
            down_timeout=10 * cfg.duration_s, reconnect_interval=0.05)
        self.accepted: list[tuple[int, int]] = []
        self.lag_ms: list[float] = []
        self.errors = 0
        self._cfg = cfg
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            interval = 1.0 / self._cfg.pull_hz
            while not self._stop.is_set():
                self.pull_once()
                self._stop.wait(interval)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.weight_puller", e)

    def pull_once(self) -> bool:
        try:
            res = self.client.get_if_newer()
        except (ConnectionError, OSError) as exc:
            self.errors += 1
            record_event("weight_puller_error", puller=self.index,
                         error=type(exc).__name__)
            return False
        if res is None:
            return False
        self.accepted.append((self.client.generation, self.client.version))
        self.lag_ms.append(
            1e3 * max(0.0, time.monotonic() - self.client.last_pub_ts))
        return True

    def monotone(self) -> bool:
        prev = (0, 0)
        for gen, version in self.accepted:
            if gen < prev[0] or (gen == prev[0] and version <= prev[1]):
                return False
            prev = (gen, version)
        return True

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.client.close()


def _sum_stats(total: dict, part: dict) -> None:
    for k, v in part.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            total[k] = total.get(k, 0) + v


def run_weight_chaos(cfg: WeightChaosConfig | None = None, **overrides
                     ) -> dict:
    """Execute one weight-chaos run and return the artifact block."""
    cfg = dataclasses.replace(cfg or WeightChaosConfig(), **overrides)
    violations_before = locking.violation_count()
    locking.enable_debug(raise_on_violation=False)
    TRACE.reset()
    TRACE.enable(sample_rate=1.0)

    pub = _Publisher(cfg)
    chaos_objs: list[WeightWireChaos] = []

    def mk_chaos(lane: int) -> WeightWireChaos:
        c = WeightWireChaos(torn_prob=cfg.torn_prob,
                            stale_prob=cfg.stale_prob,
                            seed=cfg.seed * 1000 + lane)
        chaos_objs.append(c)
        return c

    def bind_server(store: WeightStore, port: int, lane: int
                    ) -> WeightPlaneServer:
        deadline = time.monotonic() + 10.0
        while True:  # the restarted incarnation re-binds the SAME port
            try:
                return WeightPlaneServer(store, port=port, window=cfg.window,
                                         chaos=mk_chaos(lane))
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    learner = {"server": bind_server(pub.store, 0, 0)}
    learner_port = learner["server"].port
    pub.publish_once()  # at least one version before anyone pulls
    pub.start()

    relays: list[dict] = []
    upstream_port = learner_port
    for depth in range(cfg.relay_depth):
        relay = WeightRelay("127.0.0.1", upstream_port,
                            poll_interval=0.01, window=cfg.window,
                            down_timeout=10 * cfg.duration_s,
                            chaos=mk_chaos(10 + depth))
        relays.append({"relay": relay, "upstream": upstream_port,
                       "port": relay.port, "depth": depth})
        upstream_port = relay.port

    # pullers round-robin across every tier (learner + each relay) and
    # across codecs, so fencing/deltas/quantization all see every hop
    tier_ports = [learner_port] + [r["port"] for r in relays]
    pullers = [
        _Puller(i, tier_ports[i % len(tier_ports)],
                CODECS[i % len(CODECS)], cfg)
        for i in range(cfg.n_pullers)
    ]

    retired_server_stats: dict = {}
    retired_client_counters: dict = {}
    learner_kill_times = cfg.kill_schedule(cfg.learner_kills, lane=1)
    relay_kill_times = cfg.kill_schedule(
        cfg.relay_kills if relays else 0, lane=2)
    learner_kills = relay_kills = 0
    rng = np.random.default_rng(
        np.random.SeedSequence(cfg.seed, spawn_key=(0xD4C6,)))

    start = time.monotonic()
    while True:
        now = time.monotonic() - start
        if now >= cfg.duration_s:
            break
        if learner_kill_times and now >= learner_kill_times[0]:
            learner_kill_times.pop(0)
            old = learner["server"]
            stash = old.latest_full_payload()  # genuine pre-crash bytes
            old_gen = pub.store.generation
            old.close()
            store = WeightStore(generation=old_gen + 1)
            pub.swap_store(store)
            server = bind_server(store, learner_port, lane=20 + learner_kills)
            if stash is not None:
                server.chaos.stash.append(stash)
            _sum_stats(retired_server_stats, old.weight_stats())
            learner["server"] = server
            learner_kills += 1
            record_event("weight_chaos_learner_kill", new_gen=old_gen + 1)
        if relay_kill_times and now >= relay_kill_times[0]:
            relay_kill_times.pop(0)
            slot = relays[int(rng.integers(0, len(relays)))]
            old_relay = slot["relay"]
            _sum_stats(retired_server_stats, old_relay.weight_stats())
            _sum_stats(retired_client_counters, old_relay._client.counters)
            old_relay.close()
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    slot["relay"] = WeightRelay(
                        "127.0.0.1", slot["upstream"], port=slot["port"],
                        poll_interval=0.01, window=cfg.window,
                        down_timeout=10 * cfg.duration_s,
                        chaos=mk_chaos(30 + relay_kills))
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.05)
            relay_kills += 1
            record_event("weight_chaos_relay_kill", depth=slot["depth"])
        time.sleep(0.01)
    duration = time.monotonic() - start

    # drain: stop publishing and injecting, give every puller a last
    # window to converge on the final (generation, version)
    pub.close()
    for c in chaos_objs:
        c.torn_prob = c.stale_prob = 0.0
    final = (pub.store.generation, pub.store.version)
    drain_deadline = time.monotonic() + max(2.0, 0.5 * cfg.duration_s)
    while time.monotonic() < drain_deadline:
        if all(p.accepted and p.accepted[-1] == final for p in pullers):
            break
        time.sleep(0.05)
    converged = sum(1 for p in pullers
                    if p.accepted and p.accepted[-1] == final)

    for p in pullers:
        p.stop()
    servers = [learner["server"]] + [r["relay"]._server for r in relays]
    server_stats = dict(retired_server_stats)
    for srv in servers:
        _sum_stats(server_stats, srv.weight_stats())
    client_counters = dict(retired_client_counters)
    for r in relays:
        _sum_stats(client_counters, r["relay"]._client.counters)
    for p in pullers:
        _sum_stats(client_counters, p.client.counters)
    for r in relays:
        r["relay"].close()
    learner["server"].close()
    time.sleep(0.3)  # serve threads notice teardown, shed in-flight traces

    accepted_pairs = [pair for p in pullers for pair in p.accepted]
    unpublished = [pair for pair in accepted_pairs
                   if pair not in pub.published]
    lag = [v for p in pullers for v in p.lag_ms]
    served = server_stats.get("frames_full", 0) + server_stats.get(
        "frames_delta", 0)
    trace_block = TRACE.latency_block()
    TRACE.disable()
    report = {
        "metric": "weight_chaos",
        "schema": 1,
        "n_pullers": cfg.n_pullers,
        "relay_depth": cfg.relay_depth,
        "duration_s": round(duration, 3),
        "window": cfg.window,
        "publishes": pub.publishes,
        "final_generation": final[0],
        "learner_kills": learner_kills,
        "relay_kills": relay_kills,
        "snapshots_per_sec": round(
            client_counters.get("accepts", 0) / duration, 1),
        "frames_served": served,
        "frames_full": server_stats.get("frames_full", 0),
        "frames_delta": server_stats.get("frames_delta", 0),
        "delta_hit_rate": round(server_stats.get("frames_delta", 0)
                                / served, 4) if served else None,
        "bytes_per_sec": round(server_stats.get("bytes_sent", 0) / duration),
        "staleness_ms": percentile_summary(lag),
        "torn": {
            "injected": server_stats.get("torn_injected", 0),
            "rejected": client_counters.get("torn_rejected", 0),
            "accepted": len(unpublished),
        },
        "stale_injected": server_stats.get("stale_injected", 0),
        "fenced_rejected": client_counters.get("fenced_rejected", 0),
        "stale_rejected": client_counters.get("stale_rejected", 0),
        "delta_base_misses": client_counters.get("delta_base_misses", 0),
        "oracle": {
            "delta_checks": server_stats.get("oracle_delta_checks", 0),
            "delta_failures": server_stats.get("oracle_delta_failures", 0),
            "quant_checks": server_stats.get("oracle_quant_checks", 0),
            "quant_failures": server_stats.get("oracle_quant_failures", 0),
        },
        "ledger": {
            "published": len(pub.published),
            "accepted": len(accepted_pairs),
            "unpublished_accepted": len(unpublished),
            "monotone": all(p.monotone() for p in pullers),
        },
        "pullers_converged": converged,
        "puller_errors": sum(p.errors for p in pullers),
        "hierarchy_violations": locking.violation_count() - violations_before,
        "trace": {
            "orphans": trace_block["orphans"],
            "n_traces": trace_block["n_traces"],
            "completed": trace_block["completed"],
            "shed": trace_block["shed"],
            "overflow": trace_block["overflow"],
        },
        "chaos": {"torn_prob": cfg.torn_prob, "stale_prob": cfg.stale_prob},
        "seed": cfg.seed,
    }
    TRACE.reset()
    return report
