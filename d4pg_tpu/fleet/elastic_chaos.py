"""Elastic-chaos fleet drill: a flash crowd vs the autoscaler, A/B.

The elastic plane's claim is causal, so the drill is a controlled
experiment: run the SAME seeded offered load (``elastic/traffic.py`` —
the flash crowd is scripted into the model, every lane's schedule a
pure recurrence over its own model clock) through two arms,

  - **static** — serving batcher and ingest deques pinned at
    deliberately modest capacity, the overload story the fleet shipped
    with (flat knobs, per-class admission doing the shedding);
  - **elastic** — identical everything, plus an ``Autoscaler`` sensing
    the obs registry and live-actuating the serving batch limits and
    the ingest deque depth through the knobs this PR added,

and gate on the arms' SLO ledgers: the elastic arm must show STRICTLY
fewer serving SLO breaches (staleness + queueing latency) AND strictly
fewer ingest shed rows than the static arm at equal offered load.

Load is offered by light protocol pumps, not full actor lanes: a
request pump speaks the raw serving wire (lane-tagged req_ids, a
bounded pipeline window so a flash genuinely queues at the server) and
an ingest pump drives ``ReplayService.add`` in-process at the model's
row rates, while a consumer thread hammers the sample path so the
commit drain sees learner-side buffer-lock contention — the realistic
reason an ingest queue backs up at all.

Alongside the A/B gate, the run carries the standing chaos oracles:
lock-hierarchy violations delta 0, zero trace orphans at sample 1.0,
contained-crash delta 0, and the scaling ledger's decision stream must
replay bit-identically from its recorded signals
(``autoscaler.replay_matches``).
"""

from __future__ import annotations

import dataclasses
import socket
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from d4pg_tpu.core import locking
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.transport import _recv_exact
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.elastic import (
    AdmissionPolicy,
    Autoscaler,
    AutoscalerConfig,
    ScalingLedger,
    TrafficConfig,
    TrafficModel,
)
from d4pg_tpu.elastic.autoscaler import replay_matches
from d4pg_tpu.learner.state import D4PGConfig, init_state
from d4pg_tpu.learner.update import act_deterministic
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.draw_ledger import LEDGER
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import REGISTRY, percentile_summary
from d4pg_tpu.obs.trace import RECORDER as TRACE, new_trace_id
from d4pg_tpu.replay.uniform import ReplayBuffer, TransitionBatch
from d4pg_tpu.serving import PolicyInferenceServer, protocol


@dataclasses.dataclass(frozen=True)
class ElasticChaosConfig:
    """One A/B drill. Offered load is pinned by MODEL time: every pump
    runs until its model clock crosses ``model_horizon_s``, so both
    arms offer the exact same request/row schedule regardless of how
    fast each arm actually serves it."""

    # serving-side request pumps
    n_lanes: int = 16
    rows_per_req: int = 8
    base_req_per_s: float = 60.0   # per lane, at multiplier 1.0
    pipeline_window: int = 4       # in-flight requests per lane
    # ingest-side row pumps
    n_ingest_lanes: int = 8
    block_rows: int = 64
    base_ingest_rows_per_s: float = 2500.0  # per lane
    # the scripted flash crowd (model seconds)
    model_horizon_s: float = 3.0
    flash_start_s: float = 1.0
    flash_duration_s: float = 0.8
    flash_amp: float = 8.0
    # static-arm capacity knobs (deliberately modest: the flash must
    # exceed them, or there is nothing for the autoscaler to beat)
    static_max_batch_rows: int = 8
    static_batch_window_s: float = 0.002
    static_ingest_capacity: int = 24   # batches per shard deque
    shed_watermark: float = 0.75
    # SLOs + admission
    sla_latency_ms: float = 25.0
    admission_depth: int = 96
    # elastic-arm ceilings
    serving_rows_max: int = 256
    ingest_capacity_max: int = 512
    autoscaler_interval_s: float = 0.05
    # learner-contention consumer (same in both arms)
    consume_chunk_k: int = 8
    consume_batch: int = 64
    env_horizon: int = 50
    hidden: tuple = (32, 32)
    n_atoms: int = 11
    seed: int = 0

    def agent_config(self) -> D4PGConfig:
        """Tiny real network (PointMass dims) — the server dispatches
        genuine ``act_deterministic``, not a stub."""
        return D4PGConfig(obs_dim=4, act_dim=2, v_min=-50.0, v_max=0.0,
                          n_atoms=self.n_atoms, hidden=tuple(self.hidden))

    def serving_traffic(self) -> TrafficConfig:
        return TrafficConfig(
            seed=self.seed, n_actors=self.n_lanes,
            base_rows_per_sec=self.base_req_per_s * self.rows_per_req,
            diurnal_amp=0.1, diurnal_period_s=self.model_horizon_s * 4,
            flash_schedule=((self.flash_start_s, self.flash_duration_s,
                             self.flash_amp),),
            horizon_s=self.model_horizon_s)

    def ingest_traffic(self) -> TrafficConfig:
        return TrafficConfig(
            seed=self.seed + 1, n_actors=self.n_ingest_lanes,
            base_rows_per_sec=self.base_ingest_rows_per_s,
            diurnal_amp=0.1, diurnal_period_s=self.model_horizon_s * 4,
            flash_schedule=((self.flash_start_s, self.flash_duration_s,
                             self.flash_amp),),
            horizon_s=self.model_horizon_s)

    def autoscaler_config(self) -> AutoscalerConfig:
        return AutoscalerConfig(
            interval_s=self.autoscaler_interval_s,
            serving_rows_init=self.static_max_batch_rows,
            serving_rows_min=self.static_max_batch_rows,
            serving_rows_max=self.serving_rows_max,
            serving_window_hot_s=0.0005,
            serving_window_cold_s=self.static_batch_window_s,
            queue_high=4, queue_low=1,
            latency_high_ms=0.5 * self.sla_latency_ms,
            latency_low_ms=0.1 * self.sla_latency_ms,
            ingest_capacity_init=self.static_ingest_capacity,
            ingest_capacity_min=self.static_ingest_capacity,
            ingest_capacity_max=self.ingest_capacity_max,
            ingest_high=0.5, ingest_low=0.1,
            cooldown_ticks=2)


class _RequestPump:
    """One serving lane: raw protocol over one socket, req_ids tagged
    with the lane id (the server's admission class derives from exactly
    those bits), a bounded pipeline window, model-clock pacing."""

    def __init__(self, lane: int, cfg: ElasticChaosConfig, port: int,
                 rate_fn, stop: threading.Event):
        self.lane = lane
        self.cfg = cfg
        self.port = port
        self.rate_fn = rate_fn
        self.stop = stop
        self.counters = {"sent": 0, "served": 0, "overload": 0,
                         "no_params": 0, "errors": 0}
        # (model_t, latency_ms, status) per completed request
        self.records: list[tuple[float, float, int]] = []
        self.model_t = 0.0
        self._inflight: list[tuple[int, float, float]] = []
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"elastic-pump-{lane}")

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: float) -> None:
        self._thread.join(timeout)

    def run(self) -> None:
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("elastic.request_pump", e)

    def _read_one(self, sock: socket.socket) -> bool:
        body = protocol.read_frame(sock, protocol.MAGIC_RESPONSE,
                                   _recv_exact)
        if body is None:
            return False
        rsp = protocol.decode_response(body)
        now = time.monotonic()
        for i, (rid, t0, mt) in enumerate(self._inflight):
            if rid == rsp["req_id"]:
                del self._inflight[i]
                self.records.append((mt, 1e3 * (now - t0), rsp["status"]))
                break
        if rsp["status"] == protocol.STATUS_OK:
            self.counters["served"] += 1
        elif rsp["status"] == protocol.STATUS_OVERLOAD:
            self.counters["overload"] += 1
        elif rsp["status"] == protocol.STATUS_NO_PARAMS:
            self.counters["no_params"] += 1
        else:
            self.counters["errors"] += 1
        return True

    def _run(self) -> None:
        cfg = self.cfg
        sock = socket.create_connection(("127.0.0.1", self.port),
                                        timeout=30.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        obs = np.zeros((cfg.rows_per_req, 4), np.float32)
        counter = 0
        next_t = time.monotonic()
        try:
            while self.model_t < cfg.model_horizon_s \
                    and not self.stop.is_set():
                rate = max(1e-6, float(self.rate_fn(self.model_t)))
                period = cfg.rows_per_req / rate
                req_id = ((self.lane & 0xFFF) << 20) | (counter & 0xFFFFF)
                counter += 1
                tid = new_trace_id(self.lane)
                t0 = time.monotonic()
                sock.sendall(protocol.encode_request(
                    req_id, obs, trace=(tid, t0)))
                self.counters["sent"] += 1
                self._inflight.append((req_id, t0, self.model_t))
                self.model_t += period
                while len(self._inflight) > cfg.pipeline_window:
                    if not self._read_one(sock):
                        return
                next_t += period
                wait = next_t - time.monotonic()
                if wait > 0:
                    self.stop.wait(wait)
                else:
                    next_t = time.monotonic()  # behind: no catch-up burst
            while self._inflight:
                if not self._read_one(sock):
                    return
        except (OSError, protocol.ProtocolError):
            self.counters["errors"] += 1
        finally:
            try:
                sock.close()
            except OSError:
                pass


class _IngestPump:
    """One ingest lane: paced in-process ``service.add`` at the model's
    row rates (the transport slice is the ingest harness's business —
    here the service's admission/shed path is the subject)."""

    def __init__(self, lane: int, cfg: ElasticChaosConfig,
                 service: ReplayService, template: TransitionBatch,
                 rate_fn, stop: threading.Event):
        self.lane = lane
        self.cfg = cfg
        self.service = service
        self.template = template
        self.rate_fn = rate_fn
        self.stop = stop
        self.blocks_offered = 0
        self.blocks_rejected = 0
        self.model_t = 0.0
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"elastic-ingest-{lane}")

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: float) -> None:
        self._thread.join(timeout)

    def run(self) -> None:
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("elastic.ingest_pump", e)

    def _run(self) -> None:
        cfg = self.cfg
        next_t = time.monotonic()
        while self.model_t < cfg.model_horizon_s and not self.stop.is_set():
            rate = max(1e-6, float(self.rate_fn(self.model_t)))
            period = cfg.block_rows / rate
            self.model_t += period
            self.blocks_offered += 1
            if not self.service.add(self.template,
                                    actor_id=f"elastic-{self.lane}",
                                    block=False):
                self.blocks_rejected += 1
            next_t += period
            wait = next_t - time.monotonic()
            if wait > 0:
                self.stop.wait(wait)
            else:
                next_t = time.monotonic()


def _consumer(service: ReplayService, cfg: ElasticChaosConfig,
              stop: threading.Event) -> None:
    """Learner-contention lane: hammer the sample path so the commit
    drain contends for the buffer lock exactly as it does under a real
    training loop. Identical in both arms — contention is part of the
    environment, not the treatment."""
    try:
        while not stop.is_set():
            if len(service) >= cfg.consume_batch:
                service.sample_chunk(cfg.consume_chunk_k, cfg.consume_batch)
            else:
                stop.wait(0.002)
    except Exception as e:  # noqa: BLE001 — top frame of the lane
        contained_crash("elastic.consumer", e)


def _synth_block(cfg: ElasticChaosConfig) -> TransitionBatch:
    n = cfg.block_rows
    rng = np.random.default_rng(cfg.seed)
    return TransitionBatch(
        obs=rng.standard_normal((n, 4)).astype(np.float32),
        action=rng.uniform(-1, 1, (n, 2)).astype(np.float32),
        reward=rng.standard_normal(n).astype(np.float32),
        next_obs=rng.standard_normal((n, 4)).astype(np.float32),
        done=np.zeros(n, np.float32),
        discount=np.full(n, 0.99, np.float32),
    )


def _curves(pumps: list[_RequestPump], cfg: ElasticChaosConfig,
            bins: int = 12) -> list[dict]:
    """Offered-vs-served + SLO-compliance curve over model time: per
    bin, requests offered, served OK, overload-rejected, and the
    fraction of served requests inside the latency SLO."""
    edges = np.linspace(0.0, cfg.model_horizon_s, bins + 1)
    out = []
    for b in range(bins):
        lo, hi = float(edges[b]), float(edges[b + 1])
        offered = served = rejected = within = 0
        for p in pumps:
            for mt, lat, status in p.records:
                if lo <= mt < hi:
                    offered += 1
                    if status == protocol.STATUS_OK:
                        served += 1
                        if lat <= cfg.sla_latency_ms:
                            within += 1
                    elif status == protocol.STATUS_OVERLOAD:
                        rejected += 1
        out.append({
            "t": round(0.5 * (lo + hi), 4),
            "offered": offered,
            "served": served,
            "rejected": rejected,
            "slo_compliance": round(within / served, 4) if served else None,
        })
    return out


def _run_arm(cfg: ElasticChaosConfig, elastic: bool) -> dict:
    """One arm: identical offered load and environment; the autoscaler
    runs only when ``elastic``."""
    agent_cfg = cfg.agent_config()
    # fresh draw-count window per arm: every counted draw in this arm
    # is a construction-time (config-deterministic) TrafficModel draw,
    # so the gate can pin full-digest equality across arms
    LEDGER.reset(armed=True)
    policy = AdmissionPolicy()
    store = WeightStore()
    store.publish(init_state(agent_cfg,
                             jax.random.key(cfg.seed)).actor_params,
                  step=0, to_host=False)
    server = PolicyInferenceServer(
        agent_cfg, store, port=0,
        batch_window_s=cfg.static_batch_window_s,
        max_batch_rows=cfg.static_max_batch_rows,
        sla_staleness_s=1e9,  # latency is the SLO under test, not age
        refresh_interval_s=0.02,
        admission=policy, admission_depth=cfg.admission_depth,
        sla_latency_ms=cfg.sla_latency_ms)
    service = ReplayService(
        ReplayBuffer(8192, 4, 2, seed=cfg.seed),
        ingest_capacity=cfg.static_ingest_capacity,
        shed_watermark=cfg.shed_watermark,
        admission=policy)

    autoscaler = None
    if elastic:
        autoscaler = Autoscaler(
            cfg.autoscaler_config(),
            actuators={
                "serving_rows":
                    lambda v: server.set_batch_limits(max_rows=v),
                "serving_window_s":
                    lambda v: server.set_batch_limits(window_s=v),
                "ingest_capacity": service.set_ingest_depth,
            },
            ledger=ScalingLedger(),
            register_provider=False,
        ).start()

    stop = threading.Event()
    consumer = threading.Thread(target=_consumer,
                                args=(service, cfg, stop), daemon=True,
                                name="elastic-consumer")
    consumer.start()

    serving_model = TrafficModel(cfg.serving_traffic())
    ingest_model = TrafficModel(cfg.ingest_traffic())
    template = _synth_block(cfg)
    ingest_pumps = [
        _IngestPump(i, cfg, service, template, ingest_model.rate_fn(i),
                    stop)
        for i in range(cfg.n_ingest_lanes)
    ]
    pumps = [
        _RequestPump(i, cfg, server.port, serving_model.rate_fn(i), stop)
        for i in range(cfg.n_lanes)
    ]
    t0 = time.monotonic()
    for p in ingest_pumps:
        p.start()
    for p in pumps:
        p.start()
    budget = max(30.0, 20.0 * cfg.model_horizon_s)
    for p in pumps:
        p.join(budget)
    for p in ingest_pumps:
        p.join(budget)
    wall_s = time.monotonic() - t0
    stop.set()
    consumer.join(timeout=5.0)
    if autoscaler is not None:
        autoscaler.close()
    service.flush(timeout=10.0)

    sstats = server.serving_stats()
    istats = service.ingest_stats()
    counters: dict = {}
    latencies: list[float] = []
    for p in pumps:
        for k, v in p.counters.items():
            counters[k] = counters.get(k, 0) + v
        latencies.extend(lat for _, lat, st in p.records
                         if st == protocol.STATUS_OK)
    arm = {
        "wall_s": round(wall_s, 3),
        "requests": counters,
        "request_latency_ms": percentile_summary(latencies),
        "curves": _curves(pumps, cfg),
        "serving": {
            "sla_breaches": sstats["sla_breaches"],
            "latency_breaches": sstats["latency_breaches"],
            "admission_rejects": sstats["admission_rejects"],
            "admission_rejects_by_class":
                sstats["admission_rejects_by_class"],
            "responses_ok": sstats["responses_ok"],
            "batches": sstats["batches"],
            "max_batch_rows": sstats["max_batch_rows"],
            "batch_window_s": sstats["batch_window_s"],
            "latency_ms": sstats["latency_ms"],
        },
        "ingest": {
            "rows_committed": istats["rows_committed"],
            "sheds": istats["sheds"],
            "shed_rows": istats["shed_rows"],
            "sheds_by_class": istats["sheds_by_class"],
            "admit_fails": istats["admit_fails"],
            "ingest_capacity": istats["ingest_capacity"],
            "blocks_offered": sum(p.blocks_offered for p in ingest_pumps),
            "blocks_rejected": sum(p.blocks_rejected for p in ingest_pumps),
        },
    }
    if autoscaler is not None:
        astats = autoscaler.autoscaler_stats()
        arm["autoscaler"] = {
            "ticks": astats["ticks"],
            "decisions": astats["decisions"],
            "actuations": astats["actuations"],
            "actuator_errors": astats["actuator_errors"],
            "final_targets": astats["targets"],
            "ledger_digest": astats["ledger_digest"],
            "ledger_records": astats["ledger_records"],
            "ledger_replay_ok": replay_matches(cfg.autoscaler_config(),
                                               autoscaler.ledger),
            "ledger_tail": autoscaler.ledger.to_jsonable(tail=8),
        }
    arm["draw_ledger"] = LEDGER.export()
    server.close()
    service.close()
    return arm


def run_elastic_chaos(cfg: ElasticChaosConfig | None = None, **overrides
                      ) -> dict:
    """Execute the A/B drill and return the artifact block."""
    cfg = dataclasses.replace(cfg or ElasticChaosConfig(), **overrides)
    agent_cfg = cfg.agent_config()
    violations_before = locking.violation_count()
    crashes_before = REGISTRY.counter("threads.contained_crashes").value
    locking.enable_debug(raise_on_violation=False)
    TRACE.reset()
    TRACE.enable(sample_rate=1.0)
    record_event("elastic_chaos_start", n_lanes=cfg.n_lanes,
                 flash_amp=cfg.flash_amp, seed=cfg.seed)

    # pre-warm every pow2 dispatch bucket both arms can reach: jit
    # compilation must not masquerade as a queueing-latency breach in
    # whichever arm first visits a bucket
    params = init_state(agent_cfg, jax.random.key(cfg.seed)).actor_params
    b = 1
    while b <= cfg.serving_rows_max:
        np.asarray(act_deterministic(agent_cfg, params,
                                     jnp.zeros((b, 4), jnp.float32)))
        b *= 2

    arms = {"static": _run_arm(cfg, elastic=False),
            "elastic": _run_arm(cfg, elastic=True)}

    def slo(arm: dict) -> int:
        return (arm["serving"]["sla_breaches"]
                + arm["serving"]["latency_breaches"])

    gate = {
        "slo_breaches_static": slo(arms["static"]),
        "slo_breaches_elastic": slo(arms["elastic"]),
        "shed_rows_static": arms["static"]["ingest"]["shed_rows"],
        "shed_rows_elastic": arms["elastic"]["ingest"]["shed_rows"],
        # equal-seeded-load oracle: both arms constructed their traffic
        # models from the same config, so their counted RNG draw
        # histories must hash identically — a mismatch means the arms
        # were not compared under the same offered load
        "draw_digest_equal": (arms["static"]["draw_ledger"]["digest"]
                              == arms["elastic"]["draw_ledger"]["digest"]),
    }
    gate["pass"] = bool(
        gate["slo_breaches_elastic"] < gate["slo_breaches_static"]
        and gate["shed_rows_elastic"] < gate["shed_rows_static"]
        and gate["draw_digest_equal"])

    trace_block = TRACE.latency_block()
    TRACE.disable()
    report = {
        "metric": "elastic_chaos",
        "schema": 1,
        "n_lanes": cfg.n_lanes,
        "n_ingest_lanes": cfg.n_ingest_lanes,
        "model_horizon_s": cfg.model_horizon_s,
        "flash": {"start_s": cfg.flash_start_s,
                  "duration_s": cfg.flash_duration_s,
                  "amp": cfg.flash_amp},
        "sla_latency_ms": cfg.sla_latency_ms,
        "arms": arms,
        "ab_gate": gate,
        "hierarchy_violations":
            locking.violation_count() - violations_before,
        "contained_crashes":
            REGISTRY.counter("threads.contained_crashes").value
            - crashes_before,
        "trace": {
            "orphans": trace_block["orphans"],
            "n_traces": trace_block["n_traces"],
            "completed": trace_block["completed"],
            "shed": trace_block["shed"],
            "overflow": trace_block["overflow"],
        },
        "seed": cfg.seed,
    }
    TRACE.reset()
    return report
