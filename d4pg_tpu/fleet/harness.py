"""The fleet harness: N chaos-wrapped sender lanes vs ONE replay service.

This is the measurement rig that closes the ROADMAP fan-out item: BASELINE
mandates 256 actors, PR 2 priced the ingest plane at ~5,200 Humanoid rows/s
per receiver core, and this harness actually RUNS the fan-out — real TCP,
real frames, seeded faults — and reports what the plane does under it:

  - rows/s actually inserted (the number the priced ceiling predicted),
  - p50/p99 send latency across every lane,
  - every loss, named: chaos drops, backpressure drops (sender-side
    timeout sheds), receiver sheds (oldest-batch watermark evictions),
  - recovery: crash→first-delivered-block per lane, and the service's own
    eviction→re-admission intervals,
  - a deadlock verdict (all lanes joined, drain alive, queue drained).

Lanes are threads by default (a 256-lane fleet on one host); ``mode=
'process'`` spawns real subprocesses for small-N cross-checks. Chaos is
seeded and index-deterministic (``fleet/chaos.py``), so a run's fault
script — which lane dropped/delayed/crashed at which tick — replays
bit-for-bit; use ``max_ticks`` (instead of ``duration_s``) to make two
runs' scripts comparable end-to-end.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

import numpy as np

from d4pg_tpu.core import locking
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.transport import TransitionReceiver
from d4pg_tpu.elastic.traffic import TrafficConfig, TrafficModel
from d4pg_tpu.fleet.chaos import ChaosConfig, ChaosPolicy, StallGate
from d4pg_tpu.fleet.sender import ThrottledSender, synthetic_block
from d4pg_tpu.obs import draw_ledger as obs_draw
from d4pg_tpu.obs import flight as obs_flight
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs import trace as obs_trace
from d4pg_tpu.obs.registry import REGISTRY
from d4pg_tpu.replay.uniform import ReplayBuffer

# Default postmortem directory for flight-recorder dumps (deadlock /
# crash / assertion / recorded hierarchy violation): the same evidence
# tree the fleet artifacts live in.
_EVIDENCE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "docs", "evidence", "fleet")


@dataclasses.dataclass
class FleetConfig:
    n_actors: int = 8
    duration_s: float = 8.0
    # when set, every lane runs EXACTLY this many ticks and duration_s is
    # ignored — the deterministic mode (chaos scripts align 1:1 across runs)
    max_ticks: int | None = None
    rows_per_sec: float = 20.0  # per-lane offered load
    block_rows: int = 16
    obs_dim: int = 376  # Humanoid-sized rows: comparable to the priced plane
    act_dim: int = 17
    capacity: int = 100_000
    ingest_capacity: int = 64
    shed_watermark: float = 0.75
    heartbeat_timeout: float = 3.0
    evict_every_s: float = 0.5
    send_timeout: float = 1.0
    max_retries: int | None = 4
    # 'thread' | 'process' | 'actor' — 'actor' lanes spawn REAL
    # ``actor_main`` subprocesses (env + policy + n-step folding) against
    # the harness's receiver + a live weight server, closing the
    # "harness drives only the transport slice" gap; chaos injection does
    # not apply there (real actors own their own fault story).
    mode: str = "thread"
    # Sharded ingest plane: K accept/decode/commit shards on the receiver
    # (``ReplayService(num_ingest_shards=K)`` behind a
    # ``TransitionReceiver(num_shards=K)``).
    ingest_shards: int = 1
    # 'auto' | 'npz' | 'raw'. auto resolves to the sharded plane's native
    # v2 raw-column frames when ingest_shards > 1 (their fixed header is
    # what zero-decode admission/routing needs) and to the legacy npz
    # frames at K=1 — so a K=1 sweep row measures the plane exactly as
    # PR 3 shipped it.
    codec: str = "auto"
    # Run the receiver's tiered locks (core/locking.py) with hierarchy
    # assertions in RECORD mode + contention counting: the report gains a
    # ``locks`` block (per-tier acquisitions/contended/wait_ns/max_hold_ns
    # and the hierarchy-violation count, which every committed artifact
    # must show as 0). Record mode, not raise: a raise inside a shard
    # worker would read as a deadlock instead of a named violation.
    lock_debug: bool = True
    # Wire-to-grad tracing (d4pg_tpu/obs/trace): fraction of frames each
    # lane samples with a trace id + birth timestamp in the v2 header
    # extension. 0 (default) keeps the plane exactly as shipped; > 0
    # requires the raw codec to carry spans (npz frames are never
    # traced) and arms the receiver-side recorder + a consumer lane that
    # concurrently samples the service (so committed rows get a real
    # grad-consumption mark, and the chaos run exercises the sample path
    # under ingest load — previously untested concurrency).
    trace_sample: float = 0.0
    # Consumer-lane sampling cadence (Hz) when tracing is armed.
    consume_hz: float = 50.0
    # Flight-recorder dump directory (None = docs/evidence/fleet). Dumps
    # fire on deadlock, run exception, or a recorded lock-hierarchy
    # violation — the chaos postmortem.
    flight_dir: str | None = None
    chaos: ChaosConfig = dataclasses.field(default_factory=ChaosConfig)
    template_seed: int = 0
    connect_stagger_s: float = 0.002  # per-lane offset on the connect storm
    # Reconnect-storm guard (service_chaos runs): seeded per-lane upward
    # jitter, uniform in [0, reconnect_jitter_s), on the FIRST retry
    # after a lane loses its connection — a restarted service meets a
    # spread of reconnects instead of n_actors simultaneous handshakes.
    reconnect_jitter_s: float = 0.25
    # 'actor' mode knobs: the env each real actor runs and its pool width
    actor_env: str = "point"
    actor_num_envs: int = 2
    # Elastic traffic plane (elastic/traffic.py): when set, thread-mode
    # lanes pace themselves off the seeded TrafficModel (diurnal curve +
    # flash crowds + heavy-tailed per-actor rates) instead of the flat
    # ``rows_per_sec`` — the offered-load trace replays bit-for-bit from
    # ``traffic.seed``. ``rows_per_sec`` still feeds the demand estimate
    # shown in reports (the traffic model's base rate should match it).
    traffic: TrafficConfig | None = None

    def __post_init__(self):
        if self.mode not in ("thread", "process", "actor"):
            raise ValueError(f"unknown fleet mode {self.mode!r}")
        if self.codec not in ("auto", "npz", "raw"):
            raise ValueError(f"unknown codec {self.codec!r}")
        if self.ingest_shards < 1:
            raise ValueError("ingest_shards must be >= 1")
        if self.chaos.service_chaos_enabled():
            # generation fencing rides the v2 raw header: npz frames
            # carry no generation, so a restarted service could not tell
            # a pre-crash retry from a fresh row — a silent duplicate
            # instead of a declared fence. Refuse the configuration.
            if self.resolved_codec() != "raw":
                raise ValueError(
                    "service_chaos needs codec='raw' (generation fencing "
                    "is a v2 raw-header extension)")
            if self.mode != "thread":
                raise ValueError(
                    "service_chaos supervisor runs in thread mode only")

    def resolved_codec(self) -> str:
        if self.codec != "auto":
            return self.codec
        return "raw" if self.ingest_shards > 1 else "npz"

    def demand_rows_per_sec(self) -> float:
        return self.n_actors * self.rows_per_sec


def _quiesce(service: ReplayService, settle_s: float = 0.25,
             timeout: float = 5.0) -> None:
    """Wait for the in-flight tail: lanes have closed their sockets, but
    their final frames can still be in kernel buffers / receiver threads.
    Returns once the insert counter stops moving for ``settle_s`` (so the
    accounting the report does is over a drained plane), bounded by
    ``timeout``."""
    deadline = time.monotonic() + timeout
    last = service.env_steps
    last_change = time.monotonic()
    while time.monotonic() < deadline:
        time.sleep(0.05)
        now_steps = service.env_steps
        if now_steps != last:
            last, last_change = now_steps, time.monotonic()
        elif time.monotonic() - last_change >= settle_s:
            return


def _percentiles(values: list[float]) -> dict:
    if not values:
        return {"p50": None, "p99": None, "mean": None, "n": 0}
    arr = np.asarray(values, np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "mean": round(float(arr.mean()), 3),
        "n": int(arr.size),
    }


def _recovery_stats(samples: list[float]) -> dict:
    if not samples:
        return {"mean_s": None, "max_s": None, "n": 0}
    arr = np.asarray(samples, np.float64)
    return {
        "mean_s": round(float(arr.mean()), 3),
        "max_s": round(float(arr.max()), 3),
        "n": int(arr.size),
    }


class FleetHarness:
    def __init__(self, config: FleetConfig):
        self.config = config
        self.policy = ChaosPolicy(config.chaos)

    # -- lock sentinels ----------------------------------------------------
    def _arm_lock_sentinels(self) -> None:
        if self.config.lock_debug:
            locking.reset_stats()
            locking.enable_debug(raise_on_violation=False)

    # -- observability plane -----------------------------------------------
    def _arm_obs(self) -> None:
        """Reset + arm the flight recorder (always), the draw ledger
        (always — every chaos run reports per-stream RNG draw counts),
        and the trace recorder (when ``trace_sample`` > 0)."""
        cfg = self.config
        obs_draw.LEDGER.reset(armed=True)
        obs_flight.RECORDER.reset()
        obs_flight.record_event(
            "fleet_run_start", n_actors=cfg.n_actors, mode=cfg.mode,
            ingest_shards=cfg.ingest_shards, codec=cfg.resolved_codec(),
            seed=cfg.chaos.seed)
        obs_trace.RECORDER.reset()
        if cfg.trace_sample > 0:
            obs_trace.RECORDER.enable(cfg.trace_sample)

    def _latency_report(self) -> dict | None:
        """Latency block + disarm; None when tracing was off."""
        if self.config.trace_sample <= 0:
            return None
        obs_trace.RECORDER.mark_grad()  # stamp the committed tail
        block = obs_trace.RECORDER.latency_block()
        obs_trace.RECORDER.disable()
        return block

    def _maybe_dump_flight(self, reason: str, extra: dict | None = None
                           ) -> str | None:
        directory = self.config.flight_dir or _EVIDENCE_DIR
        try:
            return obs_flight.RECORDER.dump(directory, reason, extra=extra)
        except OSError as e:  # a failing dump must not mask the failure
            print(f"flight-recorder dump failed: {e}", flush=True)
            return None

    def _start_consumer(self, service_ref,
                        stop: threading.Event) -> threading.Thread | None:
        """The consumer lane: concurrently samples the service like a
        learner would and marks grad consumption for committed traces.
        Only runs when tracing is armed — it changes the plane's
        concurrency profile (sample() under the buffer lock vs the
        commit thread), which untraced runs must not silently gain.
        ``service_ref`` is a zero-arg callable: under service_chaos the
        live service is swapped out by the supervisor mid-run."""
        cfg = self.config
        if cfg.trace_sample <= 0:
            return None
        period = 1.0 / max(1.0, cfg.consume_hz)
        batch = min(64, cfg.block_rows * 4)

        def consume():
            try:
                while not stop.is_set():
                    service = service_ref()
                    if len(service) >= batch:
                        try:
                            service.sample(batch)
                        except (ValueError, RuntimeError):
                            pass  # raced an empty buffer or a dying service
                        obs_trace.RECORDER.mark_grad()
                    stop.wait(period)
            except Exception as e:  # noqa: BLE001 — top frame of the lane
                contained_crash("fleet.consumer", e)

        t = threading.Thread(target=consume, daemon=True,
                             name="fleet-consumer")
        t.start()
        return t

    def _lock_report(self) -> dict | None:
        """Snapshot + disarm. ``per_lock`` keys are tier names (all shard
        conditions fold into ``shard``, etc.); ``wait_ns`` is contended
        acquisition time — the number that attributes fleet time to lock
        waits in the K-sweep artifact."""
        if not self.config.lock_debug:
            return None
        report = {
            "hierarchy_violations": locking.violation_count(),
            "violation_samples": locking.hierarchy_violations()[:4],
            "per_lock": locking.lock_stats(),
        }
        locking.disable_debug()
        return report

    # -- shared receiver construction --------------------------------------
    def _make_service(self, obs_dim: int | None = None,
                      act_dim: int | None = None,
                      generation: int = 0) -> ReplayService:
        cfg = self.config
        return ReplayService(
            ReplayBuffer(cfg.capacity,
                         cfg.obs_dim if obs_dim is None else obs_dim,
                         cfg.act_dim if act_dim is None else act_dim),
            ingest_capacity=cfg.ingest_capacity,
            heartbeat_timeout=cfg.heartbeat_timeout,
            shed_watermark=cfg.shed_watermark,
            num_ingest_shards=cfg.ingest_shards,
            generation=generation,
        )

    def _make_receiver(self, service: ReplayService,
                       gate: StallGate | None = None,
                       port: int = 0,
                       generation=None) -> TransitionReceiver:
        """K>1 (or K=1 on the raw codec): shard-aware receiver forwarding
        UNDECODED payloads so decode runs on the owning ingest shard's
        worker — the path that reads the v2 header's trace extension at
        admission. K=1 on npz: the legacy decode-in-connection-thread
        path, bit-compatible with PR 3. ``port``/``generation``: the
        service_chaos supervisor rebinds a restarted receiver on the SAME
        port (SO_REUSEADDR — the fleet's retry path reconnects to the
        address it already has) and arms the generation greeting so
        pre-crash frames fence at admission."""
        cfg = self.config
        if cfg.ingest_shards > 1 or cfg.resolved_codec() == "raw":
            def on_payload(payload, shard, codec):
                if gate is not None:
                    gate.wait()
                service.add_payload(payload, shard=shard, codec=codec)

            return TransitionReceiver(
                lambda b, aid, count: service.add(
                    b, actor_id=aid, block=False, count_env_steps=count),
                host="127.0.0.1", port=port, num_shards=cfg.ingest_shards,
                on_payload=on_payload, generation=generation)

        def on_batch(batch, actor_id, count):
            if gate is not None:
                gate.wait()
            service.add(batch, actor_id=actor_id, block=False,
                        count_env_steps=count)

        return TransitionReceiver(on_batch, host="127.0.0.1", port=port,
                                  generation=generation)

    # -- thread mode -------------------------------------------------------
    def run(self) -> dict:
        cfg = self.config
        if cfg.mode == "process":
            return self._run_processes()
        if cfg.mode == "actor":
            return self._run_actors()
        try:
            return self._run_threads()
        except BaseException:
            # crash/assertion postmortem: whatever the ring saw last
            self._maybe_dump_flight("run_exception")
            raise

    def _run_threads(self) -> dict:
        cfg = self.config
        svc_chaos = cfg.chaos.service_chaos_enabled()
        self._arm_lock_sentinels()
        self._arm_obs()
        # Mutable holder: under service_chaos the supervisor SIGKILLs the
        # service and swaps a restored replacement in mid-run; every
        # long-lived thread (monitor, consumer, teardown) reads the live
        # instance through the holder instead of a stale binding.
        holder: dict = {"svc": self._make_service()}
        gate = StallGate()
        gen_ref = (lambda: holder["svc"].generation) if svc_chaos else None
        holder["recv"] = self._make_receiver(holder["svc"], gate,
                                             generation=gen_ref)
        port = holder["recv"].port
        template = synthetic_block(cfg.block_rows, cfg.obs_dim, cfg.act_dim,
                                   seed=cfg.template_seed)
        stop = threading.Event()
        traffic_model = (TrafficModel(cfg.traffic)
                         if cfg.traffic is not None else None)
        lanes = [
            ThrottledSender(
                i, f"fleet-{i}", "127.0.0.1", port, template,
                self.policy.actor_stream(i, f"fleet-{i}"),
                rows_per_sec=cfg.rows_per_sec,
                send_timeout=cfg.send_timeout, max_retries=cfg.max_retries,
                max_ticks=cfg.max_ticks, stop=stop,
                connect_stagger_s=i * cfg.connect_stagger_s,
                codec=cfg.resolved_codec(),
                trace_sample=cfg.trace_sample,
                expect_generation=svc_chaos,
                reconnect_jitter_s=(cfg.reconnect_jitter_s if svc_chaos
                                    else 0.0),
                rate_fn=(traffic_model.rate_fn(i)
                         if traffic_model is not None else None),
            )
            for i in range(cfg.n_actors)
        ]
        threads = [
            # lane.run is an instance-attribute target the static graph
            # can't resolve; ThrottledSender.run owns the lane's top-frame
            # broad handler and counts the crash.
            threading.Thread(target=lane.run, daemon=True,  # jaxlint: contained-by=ThrottledSender.run
                             name=f"fleet-lane-{i}")
            for i, lane in enumerate(lanes)
        ]

        monitor_stop = threading.Event()

        def monitor():
            # periodic heartbeat eviction + the seeded receiver-stall script
            try:
                horizon = cfg.duration_s if cfg.max_ticks is None else 3600.0
                stalls = list(self.policy.stall_schedule(horizon))
                t0 = time.monotonic()
                while not monitor_stop.is_set():
                    holder["svc"].evict_dead()
                    now = time.monotonic() - t0
                    if stalls and now >= stalls[0][0]:
                        _, dur = stalls.pop(0)
                        obs_flight.record_event("receiver_stall", dur_s=dur)
                        gate.stall()
                        monitor_stop.wait(dur)
                        gate.resume()
                    monitor_stop.wait(cfg.evict_every_s)
            except Exception as e:  # noqa: BLE001 — top frame of the lane
                contained_crash("fleet.monitor", e)

        monitor_thread = threading.Thread(target=monitor, daemon=True)

        recovery = None
        supervisor_thread = None
        if svc_chaos:
            recovery = {"kills": 0, "restarts": 0, "failed_restarts": 0,
                        "mttr_s": [], "rows_lost_to_crash": 0,
                        "snapshots": 0, "frames_fenced": 0, "rows_fenced": 0}
            supervisor_thread = threading.Thread(
                target=self._supervise, daemon=True,
                name="fleet-supervisor",
                args=(holder, gate, gen_ref, monitor_stop, recovery))

        t_start = time.monotonic()
        steps0 = holder["svc"].env_steps
        for t in threads:
            t.start()
        monitor_thread.start()
        if supervisor_thread is not None:
            supervisor_thread.start()
        consumer_stop = threading.Event()
        consumer_thread = self._start_consumer(lambda: holder["svc"],
                                               consumer_stop)

        deadlocks = 0
        if cfg.max_ticks is not None:
            # deterministic mode: lanes exit on their own tick budget
            budget = (cfg.max_ticks
                      * (cfg.block_rows / cfg.rows_per_sec + cfg.send_timeout)
                      + 10 * (cfg.chaos.restart_delay_s + 1.0) + 30.0)
            for t in threads:
                t.join(timeout=max(0.0, budget - (time.monotonic() - t_start)))
        else:
            stop.wait(cfg.duration_s)
            stop.set()
            for t in threads:
                t.join(timeout=cfg.send_timeout + 10.0)
        stop.set()
        deadlocks += sum(t.is_alive() for t in threads)
        dt = time.monotonic() - t_start

        gate.resume()  # never leave the drain gated during teardown
        monitor_stop.set()
        monitor_thread.join(timeout=5.0)
        if supervisor_thread is not None:
            supervisor_thread.join(timeout=15.0)
        service, receiver = holder["svc"], holder["recv"]
        _quiesce(service)
        receiver.close()
        service.flush(timeout=10.0)
        consumer_stop.set()
        if consumer_thread is not None:
            consumer_thread.join(timeout=5.0)
        rows_inserted = service.env_steps - steps0
        stats = service.ingest_stats()
        if stats["pending"] > 0 or not service._drain_thread.is_alive():
            deadlocks += 1  # drain wedged with accepted batches in flight
        if recovery is not None:
            # the final incarnation's fence counters (killed incarnations
            # were absorbed at their kill instants)
            recovery["frames_fenced"] += stats.get("fenced_frames", 0)
            recovery["rows_fenced"] += stats.get("fenced_rows", 0)
            recovery["final_generation"] = service.generation
        service.close()

        return self._report(lanes=[lane.summary() for lane in lanes],
                            rows_inserted=rows_inserted, dt=dt,
                            service_stats=stats, deadlocks=deadlocks,
                            stalls=gate.stalls, locks=self._lock_report(),
                            recovery=recovery)

    # -- the learner-kill supervisor ---------------------------------------
    def _supervise(self, holder: dict, gate: StallGate, gen_ref,
                   stop_ev: threading.Event, recovery: dict) -> None:
        """Periodic durable snapshots + the seeded kill script. Between
        kills the supervisor snapshots the live service every
        ``service_snapshot_every_s`` (the checkpoint cadence); at each
        kill instant it tears the service down ABRUPTLY and restarts it
        from the latest snapshot — rows committed after that cut are the
        declared crash loss, frames from the dead generation fence at
        admission, and MTTR is kill → first row committed by the
        restored incarnation."""
        try:
            self._supervise_run(holder, gate, gen_ref, stop_ev, recovery)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("fleet.supervisor", e)

    def _supervise_run(self, holder: dict, gate: StallGate, gen_ref,
                       stop_ev: threading.Event, recovery: dict) -> None:
        cfg = self.config
        ch = cfg.chaos
        horizon = cfg.duration_s if cfg.max_ticks is None else 3600.0
        kills = list(self.policy.service_kill_schedule(horizon))
        t0 = time.monotonic()
        snap = holder["svc"].snapshot(quiesce_timeout=0.25)
        recovery["snapshots"] += 1
        next_snap = time.monotonic() + ch.service_snapshot_every_s
        while not stop_ev.is_set():
            now = time.monotonic() - t0
            if kills and now >= kills[0]:
                kills.pop(0)
                self._kill_and_restart(holder, gate, gen_ref, stop_ev,
                                       recovery, snap)
                next_snap = time.monotonic() + ch.service_snapshot_every_s
                continue
            if time.monotonic() >= next_snap:
                try:
                    snap = holder["svc"].snapshot(quiesce_timeout=0.25)
                    recovery["snapshots"] += 1
                except (RuntimeError, ValueError) as e:
                    obs_flight.record_event("snapshot_failed", err=str(e))
                next_snap = time.monotonic() + ch.service_snapshot_every_s
            stop_ev.wait(0.02)

    def _kill_and_restart(self, holder: dict, gate: StallGate, gen_ref,
                          stop_ev: threading.Event, recovery: dict,
                          snap: dict) -> None:
        cfg = self.config
        ch = cfg.chaos
        svc, recv = holder["svc"], holder["recv"]
        port = recv.port
        # the replacement's FLOOR generation: constructor-seeded above the
        # dead incarnation so fencing stays correct even when two kills
        # land between periodic snapshots (restore alone would rewind the
        # id to snapshot-time + 1, un-fencing the first incarnation)
        next_gen = svc.generation + 1
        t_kill = time.monotonic()
        stats = svc.ingest_stats()
        rows_at_kill = svc.env_steps
        obs_flight.record_event("service_kill", generation=svc.generation,
                                env_steps=rows_at_kill)
        recv.close()
        svc.kill()  # abrupt: accepted-but-uncommitted batches die here
        recovery["kills"] += 1
        recovery["frames_fenced"] += stats.get("fenced_frames", 0)
        recovery["rows_fenced"] += stats.get("fenced_rows", 0)
        recovery["rows_lost_to_crash"] += max(
            0, rows_at_kill - int(snap.get("env_steps", 0)))
        backoff = ch.service_restart_backoff_s
        for attempt in range(max(1, ch.service_restart_max)):
            stop_ev.wait(backoff)
            backoff = min(backoff * 2.0, 5.0)
            new = None
            try:
                new = self._make_service(generation=next_gen)
                new.restore(snap)
                # service first, THEN the receiver: a sender racing the
                # swap must never be greeted with the dead generation
                holder["svc"] = new
                holder["recv"] = self._make_receiver(new, gate, port=port,
                                                     generation=gen_ref)
            except OSError as e:
                obs_flight.record_event("service_restart_failed",
                                        attempt=attempt, err=str(e))
                if new is not None:
                    new.kill()
                continue
            recovery["restarts"] += 1
            obs_flight.record_event("service_restart",
                                    generation=new.generation,
                                    attempt=attempt)
            # MTTR: kill instant -> first row COMMITTED by the restored
            # incarnation (not first reconnect — committed rows are what
            # the learner can train on again)
            restored_steps = new.env_steps
            deadline = time.monotonic() + 30.0
            while not stop_ev.is_set() and time.monotonic() < deadline:
                if new.env_steps > restored_steps:
                    recovery["mttr_s"].append(
                        round(time.monotonic() - t_kill, 4))
                    break
                stop_ev.wait(0.005)
            return
        recovery["failed_restarts"] += 1
        obs_flight.record_event("service_restart_exhausted",
                                attempts=ch.service_restart_max)

    # -- process mode ------------------------------------------------------
    def _run_processes(self) -> dict:
        import multiprocessing as mp

        from d4pg_tpu.fleet.sender import _process_lane_main

        cfg = self.config
        self._arm_lock_sentinels()
        self._arm_obs()
        service = self._make_service()
        receiver = self._make_receiver(service)
        ctx = mp.get_context("spawn")
        out_q = ctx.Queue()
        duration = (cfg.duration_s if cfg.max_ticks is None
                    else cfg.max_ticks * cfg.block_rows / cfg.rows_per_sec
                    + 30.0)
        procs = []
        for i in range(cfg.n_actors):
            kwargs = {
                "actor_index": i, "actor_id": f"fleet-{i}",
                "host": "127.0.0.1", "port": receiver.port,
                "chaos_config": dataclasses.asdict(cfg.chaos),
                "block_rows": cfg.block_rows, "obs_dim": cfg.obs_dim,
                "act_dim": cfg.act_dim, "template_seed": cfg.template_seed,
                "rows_per_sec": cfg.rows_per_sec,
                "send_timeout": cfg.send_timeout,
                "max_retries": cfg.max_retries, "max_ticks": cfg.max_ticks,
                "connect_stagger_s": i * cfg.connect_stagger_s,
                "codec": cfg.resolved_codec(),
                # birth stamps use CLOCK_MONOTONIC — one timeline across
                # processes on a host, so subprocess lanes trace fine
                "trace_sample": cfg.trace_sample,
            }
            p = ctx.Process(target=_process_lane_main,
                            args=(kwargs, duration, out_q), daemon=True)
            p.start()
            procs.append(p)
        t_start = time.monotonic()
        steps0 = service.env_steps
        consumer_stop = threading.Event()
        consumer_thread = self._start_consumer(lambda: service, consumer_stop)
        summaries, deadlocks = [], 0
        for _ in procs:
            try:
                summaries.append(out_q.get(timeout=duration + 60.0))
            except Exception:
                deadlocks += 1
        for p in procs:
            p.join(timeout=10.0)
            if p.is_alive():
                p.terminate()
        dt = time.monotonic() - t_start
        _quiesce(service)
        receiver.close()
        service.flush(timeout=10.0)
        consumer_stop.set()
        if consumer_thread is not None:
            consumer_thread.join(timeout=5.0)
        rows_inserted = service.env_steps - steps0
        stats = service.ingest_stats()
        service.close()
        return self._report(lanes=summaries, rows_inserted=rows_inserted,
                            dt=dt, service_stats=stats, deadlocks=deadlocks,
                            stalls=0, locks=self._lock_report())

    # -- real-actor mode ---------------------------------------------------
    def _run_actors(self) -> dict:
        """Lanes are REAL ``actor_main`` subprocesses: env pool + policy
        inference + n-step folding + ``CoalescingSender`` over real TCP,
        pulling live weights from a ``WeightServer`` — the full actor
        path, not the transport slice (ROADMAP: "fleet lanes driving REAL
        actor processes"). Each lane runs ``max_ticks`` pool steps (so
        offered rows are exact: ticks x num_envs), then the report closes
        the same accounting as the synthetic lanes."""
        import multiprocessing as mp

        import jax

        from d4pg_tpu.config import ExperimentConfig
        from d4pg_tpu.distributed.weight_server import WeightServer
        from d4pg_tpu.distributed.weights import WeightStore
        from d4pg_tpu.fleet.sender import _actor_lane_main
        from d4pg_tpu.learner import init_state
        from d4pg_tpu.train import infer_dims

        cfg = self.config
        self._arm_lock_sentinels()
        self._arm_obs()
        ticks = cfg.max_ticks if cfg.max_ticks is not None else 30
        acfg = ExperimentConfig(
            env=cfg.actor_env, num_envs=cfg.actor_num_envs, n_steps=2,
            max_steps=20, v_min=-5.0, v_max=0.0, hidden=(16, 16), n_atoms=11)
        obs_dim, act_dim, _ = infer_dims(acfg)
        service = self._make_service(obs_dim=obs_dim, act_dim=act_dim)
        receiver = self._make_receiver(service)
        store = WeightStore()
        store.publish(init_state(acfg.learner_config(obs_dim, act_dim),
                                 jax.random.key(cfg.template_seed)
                                 ).actor_params, step=0)
        weight_server = WeightServer(store, host="127.0.0.1")
        ctx = mp.get_context("spawn")
        out_q = ctx.Queue()
        procs = []
        for i in range(cfg.n_actors):
            p = ctx.Process(
                target=_actor_lane_main,
                args=(dataclasses.asdict(acfg), "127.0.0.1", receiver.port,
                      weight_server.port, f"actor-{i}", ticks,
                      cfg.send_timeout, cfg.max_retries, out_q,
                      cfg.resolved_codec(), cfg.trace_sample),
                daemon=True)
            p.start()
            procs.append(p)
        t_start = time.monotonic()
        steps0 = service.env_steps
        consumer_stop = threading.Event()
        consumer_thread = self._start_consumer(lambda: service, consumer_stop)
        summaries, deadlocks = [], 0
        # real actors pay a jax+env import per process: generous budget
        budget = 120.0 + ticks * cfg.actor_num_envs * 0.05
        for _ in procs:
            try:
                summaries.append(out_q.get(timeout=budget))
            except Exception:
                deadlocks += 1
        for p in procs:
            p.join(timeout=10.0)
            if p.is_alive():
                p.terminate()
        dt = time.monotonic() - t_start
        _quiesce(service)
        receiver.close()
        weight_server.close()
        service.flush(timeout=10.0)
        consumer_stop.set()
        if consumer_thread is not None:
            consumer_thread.join(timeout=5.0)
        rows_inserted = service.env_steps - steps0
        stats = service.ingest_stats()
        if stats["pending"] > 0 or not service._drain_thread.is_alive():
            deadlocks += 1
        service.close()
        return {
            "n_actors": cfg.n_actors,
            "mode": "actor",
            "locks": self._lock_report(),
            "latency": self._latency_report(),
            "trace_sample": cfg.trace_sample,
            "flight_events": len(obs_flight.RECORDER),
            "actor_env": cfg.actor_env,
            "num_envs": cfg.actor_num_envs,
            "ticks_per_lane": ticks,
            "duration_s": round(dt, 3),
            "rows_inserted": int(rows_inserted),
            "rows_per_sec": round(rows_inserted / dt, 1) if dt else 0.0,
            "lane_env_steps": [s.get("env_steps", 0) for s in summaries],
            "deadlocks": deadlocks,
            "ingest_shards": cfg.ingest_shards,
            "codec": cfg.resolved_codec(),
            "ingest": {k: stats[k] for k in
                       ("sheds", "shed_rows", "decode_errors",
                        "order_breaks", "evictions", "readmissions")},
        }

    # -- artifact ----------------------------------------------------------
    def _report(self, lanes: list[dict], rows_inserted: int, dt: float,
                service_stats: dict, deadlocks: int, stalls: int,
                locks: dict | None = None,
                recovery: dict | None = None) -> dict:
        cfg = self.config
        latencies = [v for lane in lanes for v in lane["latencies_ms"]]
        lane_recovery = [v for lane in lanes for v in lane["recovery_s"]]
        attempted = sum(lane["rows_attempted"] for lane in lanes)
        rows_per_sec = round(rows_inserted / dt, 1) if dt else 0.0
        # publish the headline into the unified registry (gauges survive
        # the run; export() is the one place that sees the whole process)
        REGISTRY.gauge("fleet.rows_per_sec").set(rows_per_sec)
        REGISTRY.gauge("fleet.deadlocks").set(deadlocks)
        latency = self._latency_report()
        flight_dump = None
        violations = locks["hierarchy_violations"] if locks else 0
        if deadlocks > 0 or violations > 0:
            # the chaos postmortem: dump the event ring next to the
            # artifacts so the failure ships its own context
            reason = ("deadlock" if deadlocks > 0
                      else "hierarchy_violation")
            flight_dump = self._maybe_dump_flight(reason, extra={
                "n_actors": cfg.n_actors, "deadlocks": deadlocks,
                "hierarchy_violations": violations,
                "seed": cfg.chaos.seed})
        return {
            "n_actors": cfg.n_actors,
            "mode": cfg.mode,
            "ingest_shards": cfg.ingest_shards,
            "codec": cfg.resolved_codec(),
            "duration_s": round(dt, 3),
            "rows_per_sec": rows_per_sec,
            "rows_per_sec_per_shard": round(
                rows_per_sec / cfg.ingest_shards, 1),
            "demand_rows_per_sec": round(cfg.demand_rows_per_sec(), 1),
            "rows_inserted": int(rows_inserted),
            "rows_attempted": int(attempted),
            "delivery_ratio": (round(rows_inserted / attempted, 4)
                               if attempted else None),
            "send_latency_ms": _percentiles(latencies),
            "drops": {
                "chaos_rows": sum(lane["rows_dropped_chaos"]
                                  for lane in lanes),
                "backpressure_rows": sum(
                    lane["rows_dropped_backpressure"] for lane in lanes),
                "shed_batches": service_stats["sheds"],
                "shed_rows": service_stats["shed_rows"],
            },
            "retries": sum(lane["retries"] for lane in lanes),
            "crashes": sum(lane["crashes"] for lane in lanes),
            "failed_restarts": sum(lane["failed_restarts"] for lane in lanes),
            "recovery": _recovery_stats(lane_recovery),
            "evictions": service_stats["evictions"],
            "readmissions": service_stats["readmissions"],
            "service_recovery": _recovery_stats(service_stats["recovery_s"]),
            "decode_errors": service_stats.get("decode_errors", 0),
            "order_breaks": service_stats.get("order_breaks", 0),
            "per_shard": service_stats.get("per_shard", []),
            "receiver_stalls": stalls,
            "deadlocks": deadlocks,
            "locks": locks,
            # wire-to-grad stage latency block (None when tracing off)
            "latency": latency,
            "trace_sample": cfg.trace_sample,
            "frames_traced": sum(lane.get("frames_traced", 0)
                                 for lane in lanes),
            "flight_dump": flight_dump,
            "flight_events": len(obs_flight.RECORDER),
            # per-stream RNG draw counts + canonical digests: the A/B
            # drivers pin schedule_digest equality across arms
            "draw_ledger": obs_draw.LEDGER.export(),
            "ticks": sum(lane["ticks"] for lane in lanes),
            "chaos": dataclasses.asdict(cfg.chaos),
            "seed": cfg.chaos.seed,
            # crash-recovery plane (None unless service_chaos ran): the
            # supervisor's ledger + the reconnect-storm spread proof
            "service_chaos": self._recovery_block(lanes, recovery),
            "chaos_log": sorted(
                ev for lane in lanes for ev in lane["chaos_log"]),
        }

    @staticmethod
    def _recovery_block(lanes: list[dict],
                        recovery: dict | None) -> dict | None:
        if recovery is None:
            return None
        jitters = [v for lane in lanes
                   for v in lane.get("storm_jitter_s", [])]
        return {
            "kills": recovery["kills"],
            "restarts": recovery["restarts"],
            "failed_restarts": recovery["failed_restarts"],
            "mttr_s": _recovery_stats(recovery["mttr_s"]),
            "snapshots": recovery["snapshots"],
            "rows_lost_to_crash": recovery["rows_lost_to_crash"],
            "frames_fenced": recovery["frames_fenced"],
            "rows_fenced": recovery["rows_fenced"],
            "final_generation": recovery.get("final_generation"),
            # the satellite's spread proof: distinct seeded jitters drawn
            # by distinct lanes on their first post-break retry — a storm
            # that arrived as one thundering herd would show distinct <= 1
            "reconnect_storm": {
                "jitters": len(jitters),
                "distinct": len({round(v, 6) for v in jitters}),
                "spread_ms": _percentiles([1e3 * v for v in jitters]),
            },
        }
