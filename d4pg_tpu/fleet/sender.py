"""Throttled sender lanes: the per-actor harness the fleet plane stresses
with.

A ``ThrottledSender`` is NOT a full actor — no env, no policy, no n-step
folder. It is the transport-facing slice of one: a paced stream of
transition blocks pushed through a real ``CoalescingSender`` over real
TCP, with a seeded ``ActorChaos`` stream deciding per block whether to
deliver, drop, delay, or crash. That slice is exactly what saturates at
256-actor fan-out (the plane, not the physics — README "Local
actor-process scaling"), so it is what the harness scales to 256 of on a
single host: a lane costs one mostly-sleeping thread and one preallocated
block, where a full actor would cost an env pool + jax inference per
lane and measure the host core instead.

Lanes run as in-proc threads by default; ``FleetHarness(mode='process')``
runs the same loop (``_process_lane_main``) in spawned subprocesses —
real process isolation, GIL-free encode — for fleets small enough to
afford a process each.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from d4pg_tpu.distributed.transport import (
    CoalescingSender,
    ReconnectingClient,
)
from d4pg_tpu.fleet.chaos import ActorChaos
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.replay.uniform import TransitionBatch


def synthetic_block(rows: int, obs_dim: int, act_dim: int,
                    seed: int = 0) -> TransitionBatch:
    """One reusable block of random transitions (shared read-only by every
    lane — the senders copy rows into their own preallocated columns, so
    one template serves a 256-lane fleet without 256 payload copies)."""
    rng = np.random.default_rng(seed)
    return TransitionBatch(
        obs=rng.standard_normal((rows, obs_dim)).astype(np.float32),
        action=rng.uniform(-1, 1, (rows, act_dim)).astype(np.float32),
        reward=rng.standard_normal(rows).astype(np.float32),
        next_obs=rng.standard_normal((rows, obs_dim)).astype(np.float32),
        done=np.zeros(rows, np.float32),
        discount=np.full(rows, 0.99, np.float32),
    )


class ThrottledSender:
    """One fleet lane: throttled blocks through a chaos-wrapped transport.

    The loop per tick: draw the next chaos event, then deliver / drop /
    delay / crash accordingly, then sleep out the remainder of the tick
    period (``block_rows / rows_per_sec``). A lane that falls behind does
    NOT burst to catch up — the throttle bounds offered load so the sweep
    measures the plane at a known demand, not a thundering herd.

    Crash semantics: the socket is torn down abruptly — no flush, no
    shutdown handshake — exactly what a SIGKILL'd actor process looks
    like to the learner. After ``restart_delay_s`` the lane reconnects
    (bounded attempts, counted) and the first DELIVERED block closes the
    crash→recovery interval recorded in ``recovery_s``.
    """

    def __init__(
        self,
        actor_index: int,
        actor_id: str,
        host: str,
        port: int,
        template: TransitionBatch,
        chaos: ActorChaos,
        rows_per_sec: float = 20.0,
        send_timeout: float = 1.0,
        max_retries: Optional[int] = 4,
        secret: Optional[str] = None,
        max_ticks: Optional[int] = None,
        stop: Optional[threading.Event] = None,
        connect_stagger_s: float = 0.0,
        codec: str = "npz",
        trace_sample: float = 0.0,
        expect_generation: bool = False,
        reconnect_jitter_s: float = 0.0,
        rate_fn=None,
    ):
        self.actor_index = actor_index
        self.actor_id = actor_id
        self._addr = (host, port)
        self._template = template
        self.chaos = chaos
        self._block_rows = int(np.asarray(template.obs).shape[0])
        self._period = self._block_rows / float(rows_per_sec)
        # Elastic traffic model (elastic/traffic.py): rate_fn maps MODEL
        # time (seconds of offered load already emitted, a pure
        # recurrence over the lane's own tick periods) to rows/sec. Model
        # time — not the wall clock — keeps the offered-load trace a
        # deterministic function of the seed: scheduler jitter changes
        # when blocks go out, never how many.
        self._rate_fn = rate_fn
        self._model_t = 0.0
        self._send_timeout = send_timeout
        self._max_retries = max_retries
        self._secret = secret
        self._max_ticks = max_ticks
        self._stop = stop if stop is not None else threading.Event()
        self._connect_stagger_s = connect_stagger_s
        self._codec = codec
        self._trace_sample = float(trace_sample)
        # crash-recovery plane: read the receiver's generation greeting
        # (service_chaos runs — the receiver must be greeting-armed) and
        # spread the post-service-restart reconnect storm with a seeded
        # per-actor upward jitter on the first retry of an episode
        self._expect_generation = bool(expect_generation)
        self._reconnect_jitter_s = float(reconnect_jitter_s)
        # counters (absorbed across crash-replaced sender instances)
        self.storm_jitters = 0
        self.storm_jitter_s: list[float] = []
        self.frames_traced = 0
        self.ticks = 0
        self.rows_attempted = 0
        self.rows_delivered = 0
        self.rows_dropped_chaos = 0
        self.rows_dropped_backpressure = 0
        self.retries = 0
        self.crashes = 0
        self.failed_restarts = 0
        self.recovery_s: list[float] = []
        self.latencies_ms: list[float] = []
        self._crashed_at: float | None = None

    # -- lifecycle ---------------------------------------------------------
    def _make_sender(self) -> CoalescingSender:
        # One frame per tick: min_block == max_block == the template size,
        # and the interval flush is disabled — the lane, not the coalescer,
        # paces the stream. backoff keeps retries inside the send budget.
        return CoalescingSender(
            self._addr[0], self._addr[1], actor_id=self.actor_id,
            secret=self._secret, retry_timeout=self._send_timeout,
            max_retries=self._max_retries, drop_on_timeout=True,
            min_block=self._block_rows, max_block=self._block_rows,
            flush_interval=1e9, backoff_base=0.05, backoff_max=1.0,
            backoff_seed=self.chaos.config.seed * 100_003 + self.actor_index,
            codec=self._codec,
            trace_sample=self._trace_sample,
            expect_generation=self._expect_generation,
            reconnect_jitter_s=self._reconnect_jitter_s,
        )

    def _absorb(self, sender: CoalescingSender) -> None:
        self.rows_delivered += sender.delivered_rows
        self.rows_dropped_backpressure += sender.dropped_rows
        self.retries += sender.retries
        self.frames_traced += sender.frames_traced
        self.storm_jitters += sender.storm_jitters
        self.storm_jitter_s.extend(sender.storm_jitter_s)

    def _sleep(self, seconds: float) -> None:
        if seconds > 0:
            self._stop.wait(seconds)

    # -- the lane loop -----------------------------------------------------
    def run(self) -> None:
        try:
            self._run_lane()
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("fleet.sender", e)

    def _run_lane(self) -> None:
        self._sleep(self._connect_stagger_s)  # de-synchronize the storm
        sender = self._reconnect()
        next_t = time.monotonic()
        try:
            while not self._stop.is_set() and (
                    self._max_ticks is None or self.ticks < self._max_ticks):
                ev = self.chaos.next()
                self.ticks += 1
                if ev.kind == "crash":
                    self.crashes += 1
                    self._crashed_at = time.monotonic()
                    if sender is not None:
                        self._absorb(sender)
                        # abrupt death: skip CoalescingSender.close's flush
                        ReconnectingClient.close(sender)
                    sender = None
                    self._sleep(ev.arg)
                    sender = self._reconnect()
                elif ev.kind == "drop":
                    self.rows_dropped_chaos += self._block_rows
                else:
                    if ev.kind == "delay":
                        self._sleep(ev.arg)
                    if sender is None:
                        sender = self._reconnect()
                    if sender is not None:
                        self._send_block(sender)
                if self._rate_fn is not None:
                    # traffic-model pacing: recompute the tick period from
                    # the modeled rate at the lane's model clock, then
                    # advance the clock by that period
                    rate = max(1e-6, float(self._rate_fn(self._model_t)))
                    self._period = self._block_rows / rate
                    self._model_t += self._period
                next_t += self._period
                wait = next_t - time.monotonic()
                if wait > 0:
                    self._sleep(wait)
                else:
                    next_t = time.monotonic()  # behind: no catch-up burst
        finally:
            if sender is not None:
                self._absorb(sender)
                try:
                    ReconnectingClient.close(sender)
                except OSError:
                    pass

    def _reconnect(self) -> CoalescingSender | None:
        """Bounded reconnect loop (a restarting actor retries its learner
        address, it does not die on the first refused connect)."""
        for _ in range(20):
            if self._stop.is_set():
                return None
            try:
                return self._make_sender()
            except (OSError, ConnectionError):
                self._sleep(0.1)
        self.failed_restarts += 1
        return None

    def _send_block(self, sender: CoalescingSender) -> None:
        self.rows_attempted += self._block_rows
        t0 = time.perf_counter()
        ok = sender.send(self._template)
        self.latencies_ms.append(1e3 * (time.perf_counter() - t0))
        if ok and self._crashed_at is not None:
            self.recovery_s.append(time.monotonic() - self._crashed_at)
            self._crashed_at = None

    def stop(self) -> None:
        self._stop.set()

    # -- results -----------------------------------------------------------
    def summary(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "ticks": self.ticks,
            "rows_attempted": self.rows_attempted,
            "rows_delivered": self.rows_delivered,
            "rows_dropped_chaos": self.rows_dropped_chaos,
            "rows_dropped_backpressure": self.rows_dropped_backpressure,
            "retries": self.retries,
            "crashes": self.crashes,
            "failed_restarts": self.failed_restarts,
            "frames_traced": self.frames_traced,
            "storm_jitters": self.storm_jitters,
            "storm_jitter_s": list(self.storm_jitter_s),
            "recovery_s": list(self.recovery_s),
            "latencies_ms": list(self.latencies_ms),
            "model_t": self._model_t,
            "chaos_log": [tuple(ev) for ev in self.chaos.log],
        }


def _process_lane_main(kwargs: dict, duration_s: float, out_queue) -> None:
    """Entry point for a subprocess lane (``mp.get_context('spawn')``):
    rebuilds the chaos stream and template from seeds, runs the same lane
    loop for ``duration_s``, ships the summary back over the queue."""
    from d4pg_tpu.fleet.chaos import ChaosConfig

    chaos = ActorChaos(ChaosConfig(**kwargs.pop("chaos_config")),
                       kwargs["actor_index"], kwargs["actor_id"])
    template = synthetic_block(
        kwargs.pop("block_rows"), kwargs.pop("obs_dim"),
        kwargs.pop("act_dim"), seed=kwargs.pop("template_seed"))
    lane = ThrottledSender(template=template, chaos=chaos, **kwargs)
    timer = threading.Timer(duration_s, lane.stop)
    timer.daemon = True
    timer.start()
    try:
        lane.run()
    finally:
        timer.cancel()
        out_queue.put(lane.summary())


def _actor_lane_main(cfg_kwargs: dict, host: str, transitions_port: int,
                     weights_port: int, actor_id: str, max_ticks: int,
                     send_timeout: float, max_retries, out_queue,
                     codec: str = "npz", trace_sample: float = 0.0) -> None:
    """Entry point for a REAL actor lane (``FleetHarness(mode='actor')``):
    a spawned subprocess running the full ``actor_main.run_actor`` path —
    env pool, policy inference, n-step folding, coalescing transport,
    live weight pulls — against the harness's learner-side servers. CPU
    backend forced before any jax import touches an accelerator; the
    fleet-member degradation policy (shed-and-count) is on so a slow
    receiver costs rows, not a wedged lane."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from d4pg_tpu.actor_main import run_actor
    from d4pg_tpu.config import ExperimentConfig

    steps = 0
    try:
        steps = run_actor(ExperimentConfig(**cfg_kwargs), host,
                          transitions_port, weights_port, actor_id=actor_id,
                          max_ticks=max_ticks, send_timeout=send_timeout,
                          send_retries=max_retries, drop_on_timeout=True,
                          codec=codec, trace_sample=trace_sample)
    finally:
        out_queue.put({"actor_id": actor_id, "env_steps": int(steps)})
