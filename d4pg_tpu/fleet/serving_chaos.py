"""Serving-chaos fleet harness: the action-inference plane under fire.

The ingest harness proves the actor->learner plane survives faults and
the weight harness proves the learner->actor broadcast does; this module
drills the third wire — the lane->server action path
(``serving/server.py``). One run stands up a publisher feeding a
``WeightStore``, a ``PolicyInferenceServer`` on a fixed port, and N
``VectorActorLane`` threads acting through ``RemotePolicyClient`` while
their transitions flow over the real ingest wire (``CoalescingSender``
-> ``TransitionReceiver`` -> ``ReplayService``), then injects the
serving plane's fault set:

  - **torn responses** — the server corrupts a seeded fraction of
    response payloads after the CRC is computed; every one must be a
    COUNTED client rejection, never an acted-on action batch.
  - **server kill + same-port rebind** — the serving process dies
    mid-flight and a new incarnation rebinds the same port; lanes
    degrade to cached-params fallback (counted, never a stall) and
    MTTR is measured kill -> first response served by the successor.

Three oracles gate the run:

  1. **ledger**: the server's torn-injection ledger intersected with
     the clients' acceptance ledgers must be EMPTY — 0 torn responses
     acted on (the req_id space is partitioned per lane, so the
     intersection is exact, not probabilistic).
  2. **trace**: with the recorder at sample 1.0, every admitted request
     must terminate (commit, write-failure shed, or teardown shed) —
     0 orphans across kills.
  3. **locks**: the run executes under lock-hierarchy record mode —
     0 new violations across the pserve tier and everything it meets.

Liveness is the implicit fourth: the run finishing its drain phase with
every lane still producing served actions means no deadlock and no
unbounded stall — the degradation ladder, not the wire, absorbed every
fault.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import jax
import numpy as np

from d4pg_tpu.core import locking
from d4pg_tpu.distributed.replay_service import ReplayService
from d4pg_tpu.distributed.transport import CoalescingSender, TransitionReceiver
from d4pg_tpu.distributed.weights import WeightStore
from d4pg_tpu.envs.fake import PointMassEnv
from d4pg_tpu.envs.vector import EnvPool
from d4pg_tpu.learner.state import D4PGConfig, init_state
from d4pg_tpu.obs.containment import contained_crash
from d4pg_tpu.obs.flight import record_event
from d4pg_tpu.obs.registry import percentile_summary
from d4pg_tpu.obs.trace import RECORDER as TRACE
from d4pg_tpu.replay.uniform import ReplayBuffer
from d4pg_tpu.serving import (
    ActorConfig,
    PolicyInferenceServer,
    RemotePolicyClient,
    ServingChaos,
    VectorActorLane,
)


@dataclasses.dataclass(frozen=True)
class ServingChaosConfig:
    """One serving-chaos run. ``torn_prob`` is per served response; the
    kill count is scheduled at seeded-jittered instants across the run,
    so a (config, seed) pair replays the same fault script."""

    n_lanes: int = 4
    envs_per_lane: int = 4
    duration_s: float = 4.0
    server_kills: int = 1
    torn_prob: float = 0.05
    request_timeout_s: float = 0.25
    batch_window_s: float = 0.002
    max_batch_rows: int = 256
    publish_hz: float = 20.0
    sla_staleness_s: float = 1.0
    env_horizon: int = 50
    hidden: tuple = (32, 32)
    n_atoms: int = 11
    seed: int = 0

    def kill_schedule(self, kills: int, lane: int) -> list[float]:
        """Seeded kill offsets (s): nominally even across the middle
        80% of the run, each jittered +-25% of its slot."""
        if kills <= 0:
            return []
        rng = np.random.default_rng(
            np.random.SeedSequence(self.seed, spawn_key=(0xD4E4, lane)))
        span = 0.8 * self.duration_s
        slot = span / kills
        return sorted(0.1 * self.duration_s + (i + 0.5) * slot
                      + float(rng.uniform(-0.25, 0.25)) * slot
                      for i in range(kills))

    def agent_config(self) -> D4PGConfig:
        """Tiny real network (PointMass dims) — the server dispatches
        genuine ``act_deterministic``, not a stub."""
        return D4PGConfig(obs_dim=4, act_dim=2, v_min=-50.0, v_max=0.0,
                          n_atoms=self.n_atoms, hidden=tuple(self.hidden))


class _ParamPublisher:
    """The synthetic learner: publishes seeded mutations of REAL
    ``init_state`` actor params at ``publish_hz``. Unlike the weight
    drill, a serving-server kill does NOT kill the store — the learner
    survives; only the inference tier dies — so one store lives for the
    whole run and doubles as every client's fallback-params handle."""

    def __init__(self, cfg: ServingChaosConfig, agent_cfg: D4PGConfig):
        self._rng = np.random.default_rng(
            np.random.SeedSequence(cfg.seed, spawn_key=(0xD4E5,)))
        self._hz = cfg.publish_hz
        self._params = init_state(agent_cfg,
                                  jax.random.key(cfg.seed)).actor_params
        self.store = WeightStore()
        self.publishes = 0
        self._pub_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def publish_once(self) -> None:
        with self._pub_lock:
            rng = self._rng
            self._params = jax.tree_util.tree_map(
                lambda x: x + np.asarray(
                    0.01 * rng.standard_normal(x.shape), x.dtype),
                self._params)
            self.store.publish(self._params, step=self.publishes,
                               to_host=False)
            self.publishes += 1

    def _run(self) -> None:
        try:
            interval = 1.0 / self._hz
            while not self._stop.is_set():
                self.publish_once()
                self._stop.wait(interval)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.param_publisher", e)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


class _SenderSink:
    """``VectorActorLane.service`` adapter over a ``CoalescingSender``:
    the lane's folded batches ride the real ingest wire. ``send``
    already returns False on a counted drop, which is exactly the
    lane's dropped-batch contract."""

    def __init__(self, sender: CoalescingSender):
        self.sender = sender

    def add(self, batch, actor_id: str = "lane", block: bool = True,
            timeout: float | None = None,
            count_env_steps: bool = True) -> bool:
        return bool(self.sender.send(batch,
                                     count_env_steps=count_env_steps))

    def close(self) -> None:
        self.sender.close()


class _Lane:
    """One serving lane: EnvPool + RemotePolicyClient + ingest sender,
    stepping on its own thread until told to stop."""

    def __init__(self, index: int, cfg: ServingChaosConfig,
                 agent_cfg: D4PGConfig, serve_port: int, ingest_port: int,
                 store: WeightStore):
        self.index = index
        pool = EnvPool(
            [lambda: PointMassEnv(horizon=cfg.env_horizon)
             for _ in range(cfg.envs_per_lane)],
            seed=cfg.seed * 10_000 + 100 * index)
        self.client = RemotePolicyClient(
            agent_cfg,
            ActorConfig(noise="gaussian", weight_poll_every=16),
            "127.0.0.1", serve_port,
            lane_id=index, seed=cfg.seed * 1_000 + index,
            timeout=cfg.request_timeout_s, connect_timeout=0.5,
            reconnect_backoff=0.05, weights=store,
            trace_sample=1.0, record_ledger=True)
        self.sink = _SenderSink(CoalescingSender(
            "127.0.0.1", ingest_port, actor_id=f"lane{index}",
            retry_timeout=0.2, max_retries=1, drop_on_timeout=True,
            min_block=32, max_block=128, flush_interval=0.05,
            backoff_seed=cfg.seed * 100_003 + index, codec="raw"))
        self.lane = VectorActorLane(
            f"lane{index}", agent_cfg, self.client.cfg, pool, self.sink,
            policy=self.client)
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        # one huge budget; the lane's own stop event breaks the loop
        try:
            self.lane.run(1 << 30)
        except Exception as e:  # noqa: BLE001 — top frame of the lane
            contained_crash("chaos.serving_lane", e)

    def stop(self) -> None:
        self.lane.stop()
        self._thread.join(timeout=10.0)

    def close(self) -> None:
        self.lane.close()   # policy + pool
        self.sink.close()


def _sum_stats(total: dict, part: dict) -> None:
    for k, v in part.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            total[k] = total.get(k, 0) + v


def run_serving_chaos(cfg: ServingChaosConfig | None = None, **overrides
                      ) -> dict:
    """Execute one serving-chaos run and return the artifact block."""
    cfg = dataclasses.replace(cfg or ServingChaosConfig(), **overrides)
    agent_cfg = cfg.agent_config()
    violations_before = locking.violation_count()
    locking.enable_debug(raise_on_violation=False)
    TRACE.reset()
    TRACE.enable(sample_rate=1.0)
    record_event("serving_chaos_start", n_lanes=cfg.n_lanes,
                 kills=cfg.server_kills, seed=cfg.seed)

    pub = _ParamPublisher(cfg, agent_cfg)
    pub.publish_once()  # params exist before the first request
    pub.start()

    # one chaos ledger across every server incarnation: the oracle
    # wants the union of injections, whoever served them
    chaos = ServingChaos(torn_response_rate=cfg.torn_prob, seed=cfg.seed)

    def bind_server(port: int) -> PolicyInferenceServer:
        deadline = time.monotonic() + 10.0
        while True:  # the restarted incarnation re-binds the SAME port
            try:
                return PolicyInferenceServer(
                    agent_cfg, pub.store, port=port,
                    batch_window_s=cfg.batch_window_s,
                    max_batch_rows=cfg.max_batch_rows,
                    sla_staleness_s=cfg.sla_staleness_s,
                    refresh_interval_s=0.02, chaos=chaos)
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    server = bind_server(0)
    serve_port = server.port

    # real ingest plane behind the lanes (v2 raw frames)
    service = ReplayService(ReplayBuffer(4096, 4, 2), ingest_capacity=256)
    receiver = TransitionReceiver(
        lambda b, aid, count: service.add(b, actor_id=aid, block=False,
                                          count_env_steps=count),
        host="127.0.0.1", port=0,
        on_payload=lambda payload, shard, codec: service.add_payload(
            payload, shard=shard, codec=codec))

    lanes = [_Lane(i, cfg, agent_cfg, serve_port, receiver.port, pub.store)
             for i in range(cfg.n_lanes)]

    retired_server_stats: dict = {}
    all_latency: list[float] = []
    all_occupancy: list[float] = []

    def retire(srv: PolicyInferenceServer) -> None:
        srv.close()
        _sum_stats(retired_server_stats, dict(srv.stats))
        # raw deques outlive close(); percentiles must span every
        # incarnation, not just the survivor
        all_latency.extend(srv._latency_ms)
        all_occupancy.extend(srv._occupancy)

    kill_times = cfg.kill_schedule(cfg.server_kills, lane=1)
    kills_done = 0
    mttr_s: list[float | None] = []

    start = time.monotonic()
    while True:
        now = time.monotonic() - start
        if now >= cfg.duration_s:
            break
        if kill_times and now >= kill_times[0]:
            kill_times.pop(0)
            t_kill = time.monotonic()
            served_before = sum(
                lane.client.stats()["served"] for lane in lanes)
            retire(server)
            server = bind_server(serve_port)
            kills_done += 1
            record_event("serving_chaos_server_kill", port=serve_port,
                         kill=kills_done)
            # MTTR: kill -> first response served by the successor
            mttr_deadline = time.monotonic() + max(5.0, cfg.duration_s)
            recovered = None
            while time.monotonic() < mttr_deadline:
                if sum(lane.client.stats()["served"]
                       for lane in lanes) > served_before:
                    recovered = time.monotonic() - t_kill
                    break
                time.sleep(0.005)
            mttr_s.append(round(recovered, 4)
                          if recovered is not None else None)
        time.sleep(0.01)
    duration = time.monotonic() - start

    # drain: stop tearing responses, require every lane to get at least
    # one more cleanly-served action batch (the ladder climbed back up)
    chaos.torn_response_rate = 0.0
    served_at_drain = [lane.client.stats()["served"] for lane in lanes]
    drain_deadline = time.monotonic() + max(2.0, 0.5 * cfg.duration_s)
    while time.monotonic() < drain_deadline:
        if all(lane.client.stats()["served"] > served_at_drain[i]
               for i, lane in enumerate(lanes)):
            break
        time.sleep(0.02)
    converged = sum(1 for i, lane in enumerate(lanes)
                    if lane.client.stats()["served"] > served_at_drain[i])

    for lane in lanes:
        lane.stop()

    client_stats: dict = {}
    accepted_ids: set[int] = set()
    env_steps = 0
    dropped = 0
    for lane in lanes:
        _sum_stats(client_stats, lane.client.stats())
        accepted_ids |= lane.client.accepted_req_ids or set()
        env_steps += lane.lane.env_steps
        dropped += lane.lane.dropped_batches
        lane.close()

    retire(server)
    receiver.close()
    service.close()
    pub.close()
    time.sleep(0.3)  # conn teardown sheds settle before the trace audit

    torn_acted_on = accepted_ids & chaos.torn_req_ids
    trace_block = TRACE.latency_block()
    TRACE.disable()
    report = {
        "metric": "serving_chaos",
        "schema": 1,
        "n_lanes": cfg.n_lanes,
        "envs_per_lane": cfg.envs_per_lane,
        "duration_s": round(duration, 3),
        "server_kills": kills_done,
        "mttr_s": mttr_s,
        "env_steps": env_steps,
        "actions_per_sec": round(env_steps / duration, 1),
        "publishes": pub.publishes,
        "requests": client_stats.get("requests", 0),
        "served": client_stats.get("served", 0),
        "timeouts": client_stats.get("timeouts", 0),
        "wire_errors": client_stats.get("wire_errors", 0),
        "fallbacks": client_stats.get("fallbacks", 0),
        "warmup_fallbacks": client_stats.get("warmup_fallbacks", 0),
        "no_params": client_stats.get("no_params", 0),
        "reconnects": client_stats.get("reconnects", 0),
        "torn": {
            "injected": chaos.torn_injected,
            "rejected": client_stats.get("torn_rejected", 0),
            "accepted": len(torn_acted_on),
        },
        "server": retired_server_stats,
        "batch_occupancy": percentile_summary(all_occupancy),
        "latency_ms": percentile_summary(all_latency),
        "ingest": {
            "env_steps": service.env_steps,
            "dropped_batches": dropped,
        },
        "lanes_converged": converged,
        "hierarchy_violations": locking.violation_count() - violations_before,
        "trace": {
            "orphans": trace_block["orphans"],
            "n_traces": trace_block["n_traces"],
            "completed": trace_block["completed"],
            "shed": trace_block["shed"],
            "overflow": trace_block["overflow"],
        },
        "chaos": {"torn_prob": cfg.torn_prob},
        "seed": cfg.seed,
    }
    TRACE.reset()
    return report
