"""Fleet plane: fan-out stress harness with seeded fault injection.

The distributed layer (``d4pg_tpu/distributed``) gives one actor a
correct transport; this package answers what happens when there are 256
of them and the network is having a bad day. ``FleetHarness`` runs N
throttled sender lanes against one ``ReplayService`` receiver over real
TCP, a seeded ``ChaosPolicy`` injects drops/delays/crashes/receiver
stalls at the transport boundary, and the harness reports what survived:
rows/s, latency percentiles, every counted loss, and recovery times.
``sweep.run_sweep`` walks N ∈ {8..256} and emits the ``bench_fleet``
artifact (``python bench.py --fleet``). See docs/architecture.md
"Fleet plane".
"""

from d4pg_tpu.fleet.chaos import (
    ActorChaos,
    ChaosConfig,
    ChaosEvent,
    ChaosPolicy,
    StallGate,
)
from d4pg_tpu.fleet.harness import FleetConfig, FleetHarness
from d4pg_tpu.fleet.sender import ThrottledSender, synthetic_block
from d4pg_tpu.fleet.sweep import (
    SWEEP_NS,
    default_chaos,
    run_sweep,
    shard_sweep,
)

__all__ = [
    "ActorChaos",
    "ChaosConfig",
    "ChaosEvent",
    "ChaosPolicy",
    "StallGate",
    "FleetConfig",
    "FleetHarness",
    "ThrottledSender",
    "synthetic_block",
    "SWEEP_NS",
    "default_chaos",
    "run_sweep",
    "shard_sweep",
]
